"""Disaggregated serving cluster: ingest tier ⇄ device tier over the flight plane.

Everything the engine scaled so far (packed bf16, fair queues, tp continuous
batching, hot-swap) lives inside one process. This module is the
millions-of-users step: it splits serving into an **ingest tier** — the
ordinary stream runtime doing parse/SQL/coalesce/admission/response-cache —
and a **device tier** of worker processes each hosting a
``ServingRunnerCore``-backed processor chain (``tpu_inference`` runner pools
or ``tpu_generate`` generation servers). Batches travel between the tiers as
Arrow IPC over the framed wire protocol ``connect/flight.py`` already speaks
(the reference's Ballista analog), so prefill→decode page streaming later is
an extension of this plane, not a rewrite.

Wire protocol (extends the flight framing; ``arkflow://host:port``):

- ``register``  — handshake: the ingest side learns ``worker_id``, protocol
  version and the hosted processor types.
- ``heartbeat`` — liveness + load report: the worker's advertised AIMD
  admission window and drain estimate (the PR-5 overload signals, computed
  by a per-worker ``OverloadController``), in-flight depth, device health
  reports and response-cache stats. The ingest side re-exports them as
  per-worker autoscaling gauges.
- ``drain``     — ``{"drain": true|false}``: a draining worker refuses new
  ``infer`` requests (they re-route to the hash ring's next worker) while
  in-flight steps finish — the building block of rolling fleet swaps and
  graceful scale-in.
- ``swap``      — ``{"checkpoint": path}``: run the worker's own PR-10
  ``ModelSwapManager`` (canary + per-unit probe + rollback) on its hosted
  processors.
- ``infer``     — the request JSON frame is followed by ONE raw frame of
  Arrow IPC (the batch, metadata columns included); the worker replies a
  status frame, then tagged data frames (processed batches), then the
  zero-length end frame. A processing error after streaming began uses the
  0x01 error tag, exactly like remote scans.
- ``kv_push``   — prefill/decode disaggregation: a prefill-role worker
  streams one finished prompt's KV pages to a decode-role worker. The
  request frame carries the page-table metadata (prompt ids, first token,
  page geometry, shard count); ``2 * shards`` raw frames follow — the K
  then V page slabs, one frame per tp shard (split along kv_heads, the
  axis the receiving pool shards on). The receiver adopts the pages into
  its own pool and decodes to completion, answering ONE status frame with
  the full token list. A draining or role-mismatched receiver refuses
  retryably (after consuming the slab frames), so the prefill side
  re-plans to the next decode candidate.

Roles (``worker: {role: prefill|decode|both}``, default ``both``): prompts
route to prefill-capable workers by prefix hash (prefix-cache affinity
survives the split verbatim); the prefill worker picks its decode
destination from the occupancy-ordered candidate list the dispatcher
attaches to the request (slot/page pressure advertised in heartbeats). A
decode-role worker refuses ``infer`` retryably, a prefill-role worker
refuses ``kv_push`` retryably — misrouted work re-routes instead of
wedging.

Routing (``remote_tpu`` dispatch stage): consistent hashing on
``batch_fingerprint`` (or the prompt prefix) over a virtual-node ring, so a
redelivered or byte-identical duplicate batch lands on the SAME worker and
its response/prefix caches keep hitting after scale-out. The hash owner is
skipped only when it is dead, draining, or has no advertised window headroom
— then the dispatch spills to the next live worker on the ring (affinity
trades for throughput only under saturation). A worker death mid-dispatch
retries on the ring's successors; if every worker fails the error surfaces
to the stream, whose existing nack path redelivers — at-least-once is
preserved end to end.

Run a device worker with::

    python -m arkflow_tpu --cluster-worker --config worker.yaml --port 50052

and point an ingest stream's pipeline at the fleet::

    processors:
      - type: remote_tpu
        workers: ["arkflow://host-a:50052", "arkflow://host-b:50052"]
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import socket
import uuid
from collections import Counter, deque
from typing import Any, Mapping, Optional, Sequence

from arkflow_tpu.batch import MessageBatch, batch_fingerprint
from arkflow_tpu.components.base import Resource
from arkflow_tpu.components.registry import build_component, ensure_plugins_loaded
from arkflow_tpu.connect.flight import (
    DEFAULT_MAX_FRAME,
    ERROR_TAG,
    TRACE_TAG,
    _end_stream,
    _read_frame,
    _send_data,
    _send_frame,
    _send_stream_error,
    batch_to_ipc,
    ipc_to_batches,
    parse_remote_url,
)
from arkflow_tpu.errors import (
    ConfigError,
    ConnectError,
    FrameIntegrityError,
    Overloaded,
    ProcessError,
    ReadError,
    SwapError,
)
from arkflow_tpu.obs import global_registry
from arkflow_tpu.obs.trace import (
    TraceContext,
    Tracer,
    TracingConfig,
    activate,
    global_tracer,
    stage_span,
)

logger = logging.getLogger("arkflow.cluster")

#: wire-protocol version carried in register responses; the ingest side
#: refuses a worker speaking a newer major protocol than it understands
PROTO_VERSION = 1

ROUTE_KEYS = ("fingerprint", "prefix")

#: prefill/decode disaggregation roles a worker can declare
WORKER_ROLES = ("prefill", "decode", "both")


# ---------------------------------------------------------------------------
# KV-page export wire codec (numpy only — the ingest tier must never
# import jax, and the slabs cross processes as raw frames)
# ---------------------------------------------------------------------------


def _wire_dtype(name: str):
    """Resolve a dtype name from the wire; bf16 lives in ml_dtypes (which
    ships with jax but imports without it)."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def kv_export_to_wire(export: Mapping) -> tuple[dict, list[bytes]]:
    """Split a ``GenerationServer.prefill_export`` payload into the JSON
    metadata dict and the ordered raw slab frames (K shards then V shards,
    one frame per tp shard — the receiver reassembles along kv_heads)."""
    import numpy as np

    meta = {k: export[k] for k in
            ("prompt", "max_new_tokens", "first_token") if k in export}
    meta["tokens"] = [int(t) for t in export.get("tokens") or []]
    if export.get("done"):
        meta["done"] = True
        return meta, []
    meta["page_size"] = int(export["page_size"])
    meta["shards"] = int(export["shards"])
    meta["dtype"] = str(export["dtype"])
    meta["shape"] = [int(d) for d in export["k"][0].shape]
    frames = [np.ascontiguousarray(a).tobytes()
              for a in list(export["k"]) + list(export["v"])]
    return meta, frames


def kv_export_from_wire(meta: Mapping, frames: Sequence[bytes]) -> dict:
    """Inverse of :func:`kv_export_to_wire`: rebuild the export dict the
    decode side's ``generate_from_pages`` adopts. Bitwise: the slabs are
    reinterpreted at their original dtype/shape, never converted."""
    import numpy as np

    out = dict(meta)
    if out.get("done"):
        return out
    shards = int(meta["shards"])
    if len(frames) != 2 * shards:
        raise ConnectError(
            f"kv_push carried {len(frames)} slab frames, expected "
            f"{2 * shards} (K+V x {shards} shards)")
    shape = tuple(int(d) for d in meta["shape"])
    dt = _wire_dtype(str(meta["dtype"]))
    expect = int(np.prod(shape)) * dt.itemsize
    for i, fr in enumerate(frames):
        # the slabs are raw device memory with no Arrow IPC validation —
        # a truncated or padded frame must fail HERE with an attributable
        # error, not reshape into garbage pages downstream
        if len(fr) != expect:
            kind = "K" if i < shards else "V"
            raise ConnectError(
                f"kv_push slab {i + 1}/{2 * shards} ({kind} shard "
                f"{i % shards}) is {len(fr)} bytes, expected {expect} "
                f"({shape} x {dt.name}); refusing to adopt corrupt pages")
    out["k"] = [np.frombuffer(frames[i], dtype=dt).reshape(shape)
                for i in range(shards)]
    out["v"] = [np.frombuffer(frames[shards + i], dtype=dt).reshape(shape)
                for i in range(shards)]
    return out


# ---------------------------------------------------------------------------
# consistent hashing
# ---------------------------------------------------------------------------


def _ring_hash(data: bytes) -> int:
    """Stable 64-bit ring position (blake2b — NOT Python's randomized hash;
    affinity must survive process restarts on both tiers)."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


class HashRing:
    """Consistent hash ring with virtual nodes.

    ``candidates(key)`` returns every distinct node in ring order starting
    at the key's position — index 0 is the affinity owner, the rest are the
    failover/spill order. Adding or removing one node only remaps the keys
    that hashed to it (the property that keeps response/prefix caches warm
    through scale-out)."""

    def __init__(self, nodes: Sequence[str] = (), virtual_nodes: int = 64):
        if virtual_nodes < 1:
            raise ConfigError(
                f"virtual_nodes must be >= 1, got {virtual_nodes}")
        self.virtual_nodes = virtual_nodes
        self._points: list[tuple[int, str]] = []  # sorted (position, node)
        for n in nodes:
            self.add(n)

    def __len__(self) -> int:
        return len({n for _, n in self._points})

    def add(self, node: str) -> None:
        import bisect

        for i in range(self.virtual_nodes):
            pt = (_ring_hash(f"{node}#{i}".encode()), node)
            idx = bisect.bisect_left(self._points, pt)
            if idx < len(self._points) and self._points[idx] == pt:
                continue  # idempotent
            self._points.insert(idx, pt)

    def remove(self, node: str) -> None:
        self._points = [p for p in self._points if p[1] != node]

    def candidates(self, key: bytes) -> list[str]:
        """All distinct nodes in ring order from the key's hash point."""
        if not self._points:
            return []
        import bisect

        # U+FFFF sorts after any node name: start strictly past every
        # point at this exact hash position
        start = bisect.bisect_right(self._points, (_ring_hash(key), "\uffff"))
        out: list[str] = []
        seen: set[str] = set()
        n = len(self._points)
        for i in range(n):
            node = self._points[(start + i) % n][1]
            if node not in seen:
                seen.add(node)
                out.append(node)
        return out


# ---------------------------------------------------------------------------
# shared introspection helpers (mirror engine.py's _inner-chain walks)
# ---------------------------------------------------------------------------


def _walk_inner(proc: Any, attr: str) -> Optional[Any]:
    """First ``attr`` found on a processor or its ``_inner`` wrapper chain."""
    node, seen = proc, set()
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        val = getattr(node, attr, None)
        if val is not None:
            return val
        node = getattr(node, "_inner", None)
    return None


def _runner_reports(processors: Sequence[Any]) -> list[dict]:
    reports: list[dict] = []
    for proc in processors:
        runner = _walk_inner(proc, "runner")
        report = getattr(runner, "health_report", None)
        if report is None:
            continue
        try:
            rep = report()
        except Exception:  # a sick runner must not break heartbeats
            logger.exception("worker health_report failed")
            continue
        reports.extend(rep if isinstance(rep, list) else [rep])
    return reports


def _cache_reports(processors: Sequence[Any]) -> list[dict]:
    out = []
    for proc in processors:
        cache = _walk_inner(proc, "cache")
        report = getattr(cache, "report", None)
        if report is not None:
            try:
                out.append(report())
            except Exception:
                logger.exception("worker cache report failed")
    return out


def _swappers(processors: Sequence[Any]) -> list:
    out = []
    for proc in processors:
        sw = _walk_inner(proc, "swapper")
        if sw is not None and hasattr(sw, "swap"):
            out.append(sw)
    return out


def _combine_epochs(epochs: Sequence[str]) -> str:
    """One heartbeat-sized digest over every monitor's epoch (most workers
    host one monitor, where this is the identity-ish passthrough)."""
    if len(epochs) == 1:
        return epochs[0]
    h = hashlib.blake2b(digest_size=16)
    for e in epochs:
        h.update(e.encode())
    return h.hexdigest()


def _integrity_monitors(processors: Sequence[Any]) -> list:
    """SDC monitors (tpu/integrity.py) hosted by this worker's processors
    — the heartbeat's ``param_digest`` epoch + corrupt-member summary, and
    the targets of the dispatcher's ``integrity_probe`` tiebreak."""
    out = []
    for proc in processors:
        mon = _walk_inner(proc, "integrity")
        if mon is not None and hasattr(mon, "probe_now"):
            out.append(mon)
    return out


def _shape_reports(processors: Sequence[Any]) -> list:
    """Per-processor serving shape grids, positional (None = no model
    stage). Rides the heartbeat so the ingest fleet controller can replay
    the incumbent grid into a freshly spawned worker's warmup — the tuner's
    committed shapes win over the static config the template carries."""
    out: list = []
    for proc in processors:
        shape = None
        tuner = _walk_inner(proc, "tuner")
        incumbent = getattr(tuner, "_incumbent", None)
        if incumbent is not None and hasattr(incumbent, "report"):
            try:
                shape = incumbent.report()
            except Exception:
                logger.exception("worker shape report failed")
        if shape is None:
            runner = _walk_inner(proc, "runner")
            buckets = getattr(runner, "buckets", None)
            if buckets is not None and hasattr(buckets, "batch_buckets"):
                shape = {"batch_buckets": list(buckets.batch_buckets),
                         "seq_buckets": list(buckets.seq_buckets),
                         "example_scale": int(
                             getattr(buckets, "example_scale", 1))}
        out.append(shape)
    return out if any(s is not None for s in out) else []


# ---------------------------------------------------------------------------
# device tier: the cluster worker server
# ---------------------------------------------------------------------------


class ClusterWorkerServer:
    """A device-tier worker: hosts a processor chain behind the flight-framed
    ``infer`` action, with register/heartbeat/drain/swap lifecycle frames.

    Load discipline: ``max_in_flight`` device lanes guarded by a semaphore
    (device steps must not interleave unboundedly); a per-worker
    ``OverloadController`` observes the semaphore wait and step latency so
    the heartbeat can advertise a genuine AIMD window + drain estimate — the
    ingest tier's routing weights and autoscaling gauges."""

    def __init__(self, processors: Sequence[Any], *, host: str = "127.0.0.1",
                 port: int = 50052, worker_id: Optional[str] = None,
                 max_in_flight: int = 1, max_frame: int = DEFAULT_MAX_FRAME,
                 tracing: Optional[TracingConfig] = None,
                 grace_s: float = 30.0, role: str = "both",
                 io_deadline_s: float = 30.0, crc: bool = True):
        from arkflow_tpu.runtime.overload import OverloadConfig, OverloadController
        from arkflow_tpu.runtime.pipeline import Pipeline

        if max_in_flight < 1:
            raise ConfigError(
                f"worker.max_in_flight must be >= 1, got {max_in_flight}")
        if role not in WORKER_ROLES:
            raise ConfigError(
                f"worker.role must be one of {WORKER_ROLES}, got {role!r}")
        if io_deadline_s <= 0:
            raise ConfigError(
                f"worker.io_deadline must be > 0, got {io_deadline_s}")
        self.role = role
        self.pipeline = Pipeline(list(processors))
        self.host = host
        self.port = port
        self.worker_id = worker_id or f"{socket.gethostname()}-{os.getpid()}"
        #: incarnation epoch: minted fresh per server object (and re-minted
        #: when the ingest tier fences this one), so a partition-healed
        #: zombie is distinguishable from the worker it used to be. The
        #: worker_id names the IDENTITY; the incarnation names the EPOCH.
        self.incarnation = uuid.uuid4().hex[:12]
        #: advertise crc32 frame integrity at register; peers that saw the
        #: capability send crc-trailed frames and this worker echoes
        self.crc = bool(crc)
        #: per-frame read deadline: a peer stalling mid-frame (slow-loris)
        #: must not pin a connection task forever
        self.io_deadline_s = float(io_deadline_s)
        #: the worker's OWN tracer (never the process-global one): spans for
        #: an infer request accumulate here and export back to the ingest
        #: tier in a TRACE_TAG frame — per-instance so in-process test
        #: fleets keep their tiers separated exactly like real processes.
        #: No explicit config = the env-aware default (ARKFLOW_TRACE=0
        #: must silence device-tier workers too).
        from arkflow_tpu.obs.trace import _default_config

        self.tracer = Tracer(tier=f"worker:{self.worker_id}",
                             config=tracing or _default_config())
        self.max_in_flight = max_in_flight
        self.max_frame = int(max_frame)
        self.draining = False
        #: SIGTERM/SIGINT grace budget: how long a self-draining worker
        #: waits for in-flight batches before exiting anyway (spot
        #: preemption notices are time-boxed; blowing the budget means the
        #: still-running batches nack through redelivery, not vanish)
        self.grace_s = float(grace_s)
        self._stopping = asyncio.Event()
        self._drain_task: Optional[asyncio.Task] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._sem: Optional[asyncio.Semaphore] = None  # bound at start()
        self._inflight = 0  # accepted infer requests not yet answered
        self._served = 0  # completed OK since process start
        self._errors = 0
        # prefill/decode disaggregation counters (heartbeat-visible)
        self._kv_pushed = 0        # exports this worker shipped downstream
        self._kv_push_retries = 0  # decode candidates that refused/failed over
        self._kv_adopted = 0       # exports adopted + decoded locally
        self._kv_refused = 0       # kv_push receives refused (drain/role)
        # network-robustness counters (heartbeat-visible)
        self._stalled_reads = 0    # reads killed by the io_deadline
        self._crc_errors = 0       # frames that failed the crc32 check
        self._fence_refused = 0    # requests refused: this epoch was fenced
        self.m_stalled = global_registry().counter(
            "arkflow_cluster_stalled_reads_total",
            "worker-side frame reads that stalled past io_deadline "
            "(slow-loris / wedged peer)", {"worker": self.worker_id})
        # the PR-5 admission signals, re-used verbatim: window adapts by
        # AIMD on the semaphore wait, drain estimate = queued * step EWMA
        self.ctrl = OverloadController(
            OverloadConfig.from_config({"enabled": True,
                                        "max_window": max_in_flight * 4}),
            name=f"worker-{self.worker_id}", workers=max_in_flight)

    # -- lifecycle ---------------------------------------------------------

    async def connect(self) -> None:
        """Pre-flight the hosted chain (model warmup compiles) BEFORE the
        port opens: a worker that answers ``register`` is ready to serve."""
        await self.pipeline.connect()

    async def start(self) -> None:
        self._sem = asyncio.Semaphore(self.max_in_flight)
        self._server = await asyncio.start_server(self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("cluster worker %s listening on %s:%d",
                    self.worker_id, self.host, self.port)

    async def serve_forever(self) -> None:
        """Serve until cancelled OR gracefully stopped (a SIGTERM-initiated
        self-drain completes by setting the stop event — see
        :meth:`begin_self_drain`)."""
        if self._server is None:
            await self.start()
        async with self._server:
            serve = asyncio.create_task(self._server.serve_forever())
            stop = asyncio.create_task(self._stopping.wait())
            try:
                await asyncio.wait({serve, stop},
                                   return_when=asyncio.FIRST_COMPLETED)
            finally:
                for t in (serve, stop):
                    t.cancel()
                    try:
                        await t
                    except (asyncio.CancelledError, Exception):
                        pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 1.0)
            except asyncio.TimeoutError:
                pass
        await self.pipeline.close()

    # -- preemption-safe self-drain (the SIGTERM primitive) ----------------

    def begin_self_drain(self, reason: str = "signal") -> None:
        """Flip to draining and schedule the graceful exit: new ``infer``
        requests are refused (retryable → the ingest ring re-routes them),
        in-flight batches get ``grace_s`` to finish, then the serve loop
        stops. Idempotent — a double SIGTERM doesn't shorten the budget.

        Usable standalone (any embedder can call it); ``run_worker`` wires
        it to SIGTERM/SIGINT so a spot preemption or a fleet-controller
        retire is routine, not a mid-batch kill."""
        if self.draining and self._drain_task is not None:
            return
        self.draining = True
        logger.info("cluster worker %s self-draining (%s): %d in-flight, "
                    "grace %.1fs", self.worker_id, reason, self._inflight,
                    self.grace_s)
        self._drain_task = asyncio.get_running_loop().create_task(
            self._drain_then_stop())

    async def _drain_then_stop(self) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.grace_s
        while self._inflight > 0 and loop.time() < deadline:
            await asyncio.sleep(0.05)
        if self._inflight > 0:
            logger.warning(
                "cluster worker %s: %d batches still in flight after %.1fs "
                "grace; exiting anyway (they nack through redelivery)",
                self.worker_id, self._inflight, self.grace_s)
        else:
            logger.info("cluster worker %s drained clean; exiting",
                        self.worker_id)
        self._stopping.set()

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT = preemption notice, not a crash: self-drain
        under the grace budget instead of dying mid-batch."""
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(
                    sig, self.begin_self_drain, sig.name)
            except (NotImplementedError, RuntimeError, ValueError):
                # non-main thread or platform without loop signal support:
                # the embedder owns signals then
                return

    # -- introspection -----------------------------------------------------

    def load_report(self) -> dict:
        """The heartbeat payload: identity + the advertised routing/
        autoscaling signals + nested device health and cache stats.

        Generation occupancy (``gen_slots_busy`` / ``page_pool_occupancy``)
        is lifted out of the nested health reports into first-class fields:
        decode placement and the fleet controller read REAL decode pressure
        from here, not just the AIMD window."""
        health = _runner_reports(self.pipeline.processors)
        rep = {
            "worker_id": self.worker_id,
            "proto": PROTO_VERSION,
            "role": self.role,
            "incarnation": self.incarnation,
            "crc": self.crc,
            "draining": self.draining,
            "stalled_reads": self._stalled_reads,
            "crc_errors": self._crc_errors,
            "fence_refused": self._fence_refused,
            "inflight": self._inflight,
            "served": self._served,
            "errors": self._errors,
            "window": int(self.ctrl.window),
            "drain_s": round(self.ctrl.estimated_drain_s(), 3),
            "step_ewma_ms": round(self.ctrl.step_s() * 1000.0, 3),
            "kv_pushed": self._kv_pushed,
            "kv_push_retries": self._kv_push_retries,
            "kv_adopted": self._kv_adopted,
            "kv_refused": self._kv_refused,
            "health": health,
            "caches": _cache_reports(self.pipeline.processors),
            "shapes": _shape_reports(self.pipeline.processors),
        }
        monitors = _integrity_monitors(self.pipeline.processors)
        if monitors:
            # SDC defense signals: the combined param-digest epoch (None
            # until every member is baselined) lets the dispatcher spot a
            # digest-outlier against same-model peers; a nonzero corrupt
            # count fences this worker outright
            epochs = [m.digest_epoch() for m in monitors]
            rep["param_digest"] = (_combine_epochs(epochs)
                                   if all(epochs) else None)
            rep["integrity_corrupt"] = sum(m.corrupt_members()
                                           for m in monitors)
        gen = [h for h in health if h.get("serving") == "continuous"]
        if gen:
            rep["gen_slots"] = sum(int(h.get("slots", 0)) for h in gen)
            rep["gen_slots_busy"] = sum(int(h.get("slots_busy", 0))
                                        for h in gen)
            rep["page_pool_occupancy"] = round(
                max(float(h.get("page_pool_occupancy", 0.0)) for h in gen), 4)
            ttfts = [h["ttft"] for h in gen if isinstance(h.get("ttft"), dict)]
            if ttfts:
                rep["ttft_p99_ms"] = max(float(t.get("p99_ms", 0.0))
                                         for t in ttfts)
        return rep

    # -- request handling --------------------------------------------------

    async def _read_bounded(self, reader, what: str):
        """One frame under the per-frame io_deadline: a peer stalling
        mid-frame (slow-loris) is cut loose and counted instead of pinning
        this connection task forever."""
        try:
            return await asyncio.wait_for(
                _read_frame(reader, self.max_frame, what=what),
                self.io_deadline_s)
        except asyncio.TimeoutError:
            self._stalled_reads += 1
            self.m_stalled.inc()
            raise ConnectError(
                f"read of {what} frame stalled past the "
                f"{self.io_deadline_s:.1f}s io_deadline (slow-loris or "
                "wedged peer); dropping the connection") from None

    def _fence_check(self, req: dict) -> bool:
        """True when the peer declared THIS incarnation fenced (it was
        staleness-declared dead, e.g. across a healed partition). The
        request is refused retryably and the worker re-mints its epoch, so
        the next heartbeat re-admits it as a provably fresh member instead
        of a zombie serving stale occupancy."""
        fenced = req.get("fenced") or []
        if self.incarnation not in fenced:
            return False
        self._fence_refused += 1
        old, self.incarnation = self.incarnation, uuid.uuid4().hex[:12]
        logger.warning(
            "cluster worker %s: incarnation %s was fenced by the ingest "
            "tier (stale after a partition?); re-minted as %s",
            self.worker_id, old, self.incarnation)
        return True

    async def _serve(self, reader, writer) -> None:
        crc = False
        try:
            raw = await self._read_bounded(reader, "request")
            if raw is None:
                return
            # echo negotiation: reply with crc trailers iff the request
            # frame carried one (the peer learned the capability from our
            # register report) and integrity is enabled locally
            crc = bool(getattr(reader, "_arkflow_crc", False)) and self.crc
            req = json.loads(raw.decode())
            action = req.get("action")
            if action == "register":
                fence = req.get("fence")
                if fence and fence == self.incarnation:
                    # explicit heal handshake: the ingest tier fenced this
                    # epoch and asks for a fresh one before re-admission
                    self._fence_refused += 1
                    self.incarnation = uuid.uuid4().hex[:12]
                    logger.info(
                        "cluster worker %s: fenced incarnation %s healed; "
                        "now %s", self.worker_id, fence, self.incarnation)
                await _send_frame(writer, json.dumps({
                    "ok": True,
                    "processors": [type(p).__name__
                                   for p in self.pipeline.processors],
                    **self.load_report(),
                }).encode(), crc=crc)
            elif action == "heartbeat":
                await _send_frame(writer, json.dumps(
                    {"ok": True, **self.load_report()}).encode(), crc=crc)
            elif action == "drain":
                self.draining = bool(req.get("drain", True))
                logger.info("cluster worker %s drain=%s (inflight=%d)",
                            self.worker_id, self.draining, self._inflight)
                await _send_frame(writer, json.dumps(
                    {"ok": True, **self.load_report()}).encode(), crc=crc)
            elif action == "integrity_probe":
                await self._do_integrity_probe(writer, crc=crc)
            elif action == "swap":
                await self._do_swap(req, writer)
            elif action == "infer":
                await self._do_infer(req, reader, writer, crc=crc)
            elif action == "kv_push":
                await self._do_kv_push(req, reader, writer, crc=crc)
            else:
                await _send_frame(writer, json.dumps(
                    {"ok": False, "error": f"unknown action {action!r}"}
                ).encode(), crc=crc)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception as e:
            if isinstance(e, FrameIntegrityError):
                self._crc_errors += 1
            # the reader records the crc negotiation BEFORE validating, so
            # even a refusal of a corrupted request carries a trailer — the
            # reply crosses the same corrupting link the request did, and
            # unprotected it would reach the peer as undecodable garbage
            crc = bool(getattr(reader, "_arkflow_crc", False)) and self.crc
            try:
                if getattr(writer, "_arkflow_streaming", False):
                    await _send_stream_error(writer, repr(e)[:500], crc=crc)
                    await _end_stream(writer)
                else:
                    status = {"ok": False, "error": repr(e)[:500]}
                    if isinstance(e, FrameIntegrityError):
                        # a corrupted REQUEST was never processed — refuse
                        # retryably so the ingest ring fails the batch over
                        # instead of quarantining it as a processing error;
                        # the reason lets the client count it as a frame
                        # error rather than a drain
                        status["retryable"] = True
                        status["reason"] = "frame_integrity"
                    await _send_frame(writer, json.dumps(status).encode(),
                                      crc=crc)
            except Exception:
                pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _do_integrity_probe(self, writer, crc: bool = False) -> None:
        """On-demand full integrity pass — the dispatcher's shadow-verify
        tiebreak: when two workers disagree on one batch, each runs its
        golden probes NOW and the corrupt one self-identifies (and its
        local monitor quarantines + repairs it on the spot)."""
        monitors = _integrity_monitors(self.pipeline.processors)
        summaries: list[dict] = []
        ok = True
        for mon in monitors:
            try:
                summaries.append(await mon.probe_now())
            except Exception as e:
                ok = False
                summaries.append({"error": repr(e)[:200]})
        mismatches = sum(int(s.get("mismatches", 0)) for s in summaries)
        await _send_frame(writer, json.dumps({
            "ok": ok, "worker_id": self.worker_id,
            "probed": len(monitors),
            "mismatches": mismatches,
            "corrupt": sum(m.corrupt_members() for m in monitors),
            "summaries": summaries,
        }).encode(), crc=crc)

    async def _do_swap(self, req: dict, writer) -> None:
        """Apply a rolling hot-swap to the hosted processors via their own
        PR-10 managers (canary + probe + rollback happen worker-side)."""
        ckpt = req.get("checkpoint")
        if not ckpt or not isinstance(ckpt, str):
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "swap needs a 'checkpoint' path"}).encode())
            return
        swappers = _swappers(self.pipeline.processors)
        if not swappers:
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "no hot-swappable processors on this "
                                       "worker"}).encode())
            return
        results, ok_all = [], True
        for sw in swappers:
            try:
                results.append({"ok": True, **(await sw.swap(ckpt))})
            except SwapError as e:
                ok_all = False
                results.append({"ok": False, "error": str(e)})
            except Exception as e:  # an unexpected bug must still answer
                ok_all = False
                results.append({"ok": False,
                                "error": f"{type(e).__name__}: {e}"})
        await _send_frame(writer, json.dumps(
            {"ok": ok_all, "worker_id": self.worker_id,
             "results": results}).encode())

    async def _do_infer(self, req: dict, reader, writer,
                        crc: bool = False) -> None:
        ipc = await self._read_bounded(reader, "infer batch")
        if ipc is None:
            raise ConnectError("infer request carried no batch frame")
        if self._fence_check(req):
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "worker incarnation was fenced "
                 "(stale epoch); re-minted — retry on the ring",
                 "retryable": True}).encode(), crc=crc)
            return
        if self.draining:
            # retryable: the dispatcher re-routes to the ring's next worker
            # instead of surfacing a processing error
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "worker is draining",
                 "retryable": True, "incarnation": self.incarnation}
            ).encode(), crc=crc)
            return
        if self.role == "decode":
            # a decode-role worker only adopts kv_push pages; prompts
            # re-route to a prefill-capable worker on the ring
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "worker role is 'decode': accepts "
                 "kv_push only", "retryable": True,
                 "incarnation": self.incarnation}).encode(), crc=crc)
            return
        # cross-tier trace context: the ingest dispatcher parents the
        # worker's spans under its hop span; absent = untraced (old peer)
        tctx = (TraceContext.from_json(req.get("trace"))
                if self.tracer.enabled else None)
        t_deser = asyncio.get_running_loop().time()
        batches = ipc_to_batches(ipc)
        if not batches:
            raise ConnectError("infer batch frame decoded to zero batches")
        batch = MessageBatch(batches[0])
        await _send_frame(writer, json.dumps(
            {"ok": True, "incarnation": self.incarnation}).encode(), crc=crc)
        writer._arkflow_streaming = True
        loop = asyncio.get_running_loop()
        self.tracer.record(tctx, "remote_deserialize", loop.time() - t_deser)
        self._inflight += 1
        self.ctrl.on_enqueue()
        t_q = loop.time()
        try:
            async with self._sem:  # one device, max_in_flight lanes
                q_wait = loop.time() - t_q
                self.ctrl.on_dequeue(q_wait, loop.time())
                self.tracer.record(tctx, "remote_queue_wait", q_wait)
                t0 = loop.time()
                # activate the worker's tracer so the hosted chain's spans
                # (infeed prep, device step) nest under remote_step
                decode_urls = [str(u) for u in req.get("decode_workers") or []]
                decode_crc = {str(u) for u in req.get("decode_crc") or []}
                fenced = [str(f) for f in req.get("fenced") or []]
                disagg = (self._disagg_handle()
                          if self.role == "prefill" and decode_urls else None)
                with activate(self.tracer, tctx):
                    if disagg is not None:
                        # prefill role two-hop: prefill locally, stream the
                        # KV pages to a decode candidate, relay its tokens
                        with stage_span("remote_step"):
                            exports = await disagg.prefill_rows(batch)
                        with stage_span("remote_kv_push"):
                            token_lists = [await self._push_export(
                                e, decode_urls, crc_urls=decode_crc,
                                fenced=fenced) for e in exports]
                        results = disagg.finalize_rows(batch, token_lists)
                    else:
                        with stage_span("remote_step"):
                            results = await self.pipeline.process(batch)
                self.ctrl.observe_step(loop.time() - t0)
            t_ser = loop.time()
            for out in results:
                await _send_data(writer, batch_to_ipc(out.record_batch),
                                 crc=crc)
            self.tracer.record(tctx, "remote_serialize", loop.time() - t_ser)
            spans = self.tracer.export_open(tctx)
            if spans:
                await _send_frame(writer, TRACE_TAG + json.dumps(
                    {"spans": spans}).encode(), crc=crc)
            await _end_stream(writer)
            self._served += 1
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            self.tracer.export_open(tctx)  # don't strand the open entry
            raise
        except Exception:
            self._errors += 1
            # a FAILED step is exactly the trace forced sampling exists
            # for: ship the worker-tier spans ahead of the error frame the
            # outer handler will send (the connection is still alive here)
            spans = self.tracer.export_open(tctx)
            if spans:
                try:
                    await _send_frame(writer, TRACE_TAG + json.dumps(
                        {"spans": spans}).encode())
                except Exception:
                    pass  # the error frame still matters more
            raise
        finally:
            self._inflight -= 1

    # -- prefill/decode disaggregation -------------------------------------

    def _disagg_handle(self) -> Optional[Any]:
        """The hosted chain's disaggregation adapter (a continuous
        ``tpu_generate`` processor exposes itself as ``.disagg`` — same
        ``_inner``-chain convention as ``.runner``/``.swapper``)."""
        for proc in self.pipeline.processors:
            d = _walk_inner(proc, "disagg")
            if d is not None and hasattr(d, "prefill_rows"):
                return d
        return None

    def _generation_server(self) -> Optional[Any]:
        """The hosted continuous generation server (adopt target)."""
        for proc in self.pipeline.processors:
            runner = _walk_inner(proc, "runner")
            if runner is not None and hasattr(runner, "generate_from_pages"):
                return runner
        return None

    async def _push_export(self, export: Mapping, urls: Sequence[str],
                           crc_urls: Optional[set] = None,
                           fenced: Optional[Sequence[str]] = None) -> list[int]:
        """Ship one prompt's KV pages to the first decode candidate that
        accepts, in the occupancy order the dispatcher planned. A retryable
        refusal (draining / role mismatch) or a transport error re-plans to
        the next candidate; a processing failure on an ACCEPTED push is
        terminal (the decode side already owns the request). All candidates
        exhausted raises ConnectError — the infer stream errors, and the
        ingest tier's normal nack/redelivery re-prefills."""
        if export.get("done"):
            return [int(t) for t in export.get("tokens") or []]
        meta, frames = kv_export_to_wire(export)
        last: Optional[BaseException] = None
        for url in urls:
            host, port = parse_remote_url(url)
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), 5.0)
            except (OSError, asyncio.TimeoutError) as e:
                self._kv_push_retries += 1
                last = e
                continue
            # crc per peer: the dispatcher tells us which decode candidates
            # advertised frame integrity — raw bf16 slabs bypass Arrow IPC
            # validation, so the trailer is the ONLY corruption check
            use_crc = self.crc and crc_urls is not None and url in crc_urls
            try:
                try:
                    push_req: dict = {"action": "kv_push", "meta": meta}
                    if fenced:
                        push_req["fenced"] = list(fenced)
                    await _send_frame(writer, json.dumps(push_req).encode(),
                                      crc=use_crc)
                    for fr in frames:
                        await _send_frame(writer, fr, crc=use_crc)
                    raw = await asyncio.wait_for(
                        _read_frame(reader, self.max_frame,
                                    what="kv_push status"), 120.0)
                    if raw is None:
                        raise ConnectError(
                            f"decode worker {url} closed before a status")
                    status = json.loads(raw.decode())
                except (ConnectionError, OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError, ConnectError,
                        ReadError) as e:
                    self._kv_push_retries += 1
                    last = e
                    continue
            finally:
                try:
                    writer.close()
                except Exception:
                    pass
            if status.get("ok"):
                self._kv_pushed += 1
                return [int(t) for t in status.get("tokens") or []]
            if status.get("retryable"):
                self._kv_push_retries += 1
                last = ConnectError(
                    f"decode worker {url} refused kv_push: {status.get('error')}")
                continue
            raise ProcessError(
                f"decode worker {url} failed adopted decode: "
                f"{status.get('error')}")
        raise ConnectError(
            f"kv_push: no decode worker accepted the pages "
            f"({len(urls)} candidates tried; last: {last!r})")

    async def _do_kv_push(self, req: dict, reader, writer,
                          crc: bool = False) -> None:
        """Adopt a prefill worker's KV pages and decode to completion.

        The slab frames are consumed BEFORE any refusal (same ordering as
        ``infer`` under drain: the peer already committed the frames to the
        socket), then draining / role-mismatch / a fenced incarnation
        refuse RETRYABLY so the prefill side re-plans to the ring's next
        decode candidate instead of surfacing a processing error."""
        meta = req.get("meta")
        if not isinstance(meta, Mapping):
            await _send_frame(writer, json.dumps(
                {"ok": False,
                 "error": "kv_push needs a 'meta' mapping"}).encode(),
                crc=crc)
            return
        frames: list[bytes] = []
        if not meta.get("done"):
            shards = meta.get("shards", 1)
            if (isinstance(shards, bool) or not isinstance(shards, int)
                    or not 1 <= shards <= 64):
                await _send_frame(writer, json.dumps(
                    {"ok": False,
                     "error": f"kv_push shards invalid: {shards!r}"}
                ).encode(), crc=crc)
                return
            for i in range(2 * shards):
                fr = await self._read_bounded(
                    reader, f"kv_push slab {i + 1}/{2 * shards}")
                if fr is None:
                    raise ConnectError(
                        "kv_push ended before all page-slab frames")
                frames.append(bytes(fr))
        if self._fence_check(req):
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "worker incarnation was fenced "
                 "(stale epoch); re-minted — retry the next candidate",
                 "retryable": True}).encode(), crc=crc)
            return
        if self.draining:
            self._kv_refused += 1
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "worker is draining",
                 "retryable": True}).encode(), crc=crc)
            return
        if self.role == "prefill":
            self._kv_refused += 1
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "worker role is 'prefill': cannot "
                 "adopt KV pages it would never decode",
                 "retryable": True}).encode(), crc=crc)
            return
        server = self._generation_server()
        if server is None:
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": "no continuous generation server "
                 "hosted on this worker"}).encode(), crc=crc)
            return
        export = kv_export_from_wire(meta, frames)
        loop = asyncio.get_running_loop()
        self._inflight += 1
        self.ctrl.on_enqueue()
        t_q = loop.time()
        try:
            async with self._sem:  # adopted decode holds a device lane too
                self.ctrl.on_dequeue(loop.time() - t_q, loop.time())
                t0 = loop.time()
                tokens = await server.generate_from_pages(export)
                self.ctrl.observe_step(loop.time() - t0)
            self._kv_adopted += 1
            self._served += 1
            await _send_frame(writer, json.dumps(
                {"ok": True, "worker_id": self.worker_id,
                 "incarnation": self.incarnation,
                 "tokens": [int(t) for t in tokens]}).encode(), crc=crc)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            raise
        except Exception as e:
            self._errors += 1
            await _send_frame(writer, json.dumps(
                {"ok": False, "error": repr(e)[:500]}).encode(), crc=crc)
        finally:
            self._inflight -= 1


# -- worker config / entry point -------------------------------------------


def parse_worker_config(m: Any) -> tuple[list[dict], dict]:
    """Worker-mode config -> (processor config list, worker options).

    Accepts the natural shapes: ``{processors: [...]}``, a stream-style
    ``{pipeline: {processors: [...]}}``, or a full engine config (the FIRST
    stream's pipeline is hosted) — so a worker can reuse the exact
    processor block of the single-process config it was split out of.
    Options ride under ``worker: {id, max_in_flight, max_frame, grace,
    role, io_deadline, crc}`` (``grace`` = the SIGTERM self-drain budget,
    default 30s; ``io_deadline`` = the per-frame read deadline bounding
    slow-loris peers, default 30s; ``crc`` = advertise crc32 frame
    integrity, default true)."""
    if not isinstance(m, Mapping):
        raise ConfigError("cluster worker config must be a mapping")
    procs: Any = m.get("processors")
    if procs is None and isinstance(m.get("pipeline"), Mapping):
        procs = m["pipeline"].get("processors")
    if procs is None and isinstance(m.get("streams"), list) and m["streams"]:
        s0 = m["streams"][0]
        if isinstance(s0, Mapping) and isinstance(s0.get("pipeline"), Mapping):
            procs = s0["pipeline"].get("processors")
    if not isinstance(procs, list) or not procs:
        raise ConfigError(
            "cluster worker config needs a non-empty processor list "
            "(top-level 'processors:', 'pipeline.processors:', or the first "
            "stream of an engine config)")
    for p in procs:
        if not isinstance(p, Mapping) or not p.get("type"):
            raise ConfigError(f"worker processor config must be a mapping "
                              f"with a 'type' tag, got {p!r}")
    opts_raw = m.get("worker") or {}
    if not isinstance(opts_raw, Mapping):
        raise ConfigError("'worker' options must be a mapping")
    opts: dict = {}
    mif = opts_raw.get("max_in_flight", 1)
    if isinstance(mif, bool) or not isinstance(mif, int) or mif < 1:
        raise ConfigError(
            f"worker.max_in_flight must be an int >= 1, got {mif!r}")
    opts["max_in_flight"] = mif
    mf = opts_raw.get("max_frame", DEFAULT_MAX_FRAME)
    if isinstance(mf, bool) or not isinstance(mf, int) or mf < 1024:
        raise ConfigError(
            f"worker.max_frame must be an int >= 1024, got {mf!r}")
    opts["max_frame"] = mf
    wid = opts_raw.get("id")
    if wid is not None and not isinstance(wid, str):
        raise ConfigError(f"worker.id must be a string, got {wid!r}")
    opts["worker_id"] = wid
    role = opts_raw.get("role", "both")
    if role not in WORKER_ROLES:
        raise ConfigError(
            f"worker.role must be one of {WORKER_ROLES}, got {role!r}")
    opts["role"] = role
    from arkflow_tpu.utils.duration import parse_duration

    grace = opts_raw.get("grace", "30s")
    try:
        grace_s = parse_duration(grace)
    except (ConfigError, TypeError, ValueError) as e:
        raise ConfigError(f"worker.grace invalid: {e}") from e
    if grace_s <= 0:
        raise ConfigError(f"worker.grace must be > 0, got {grace!r}")
    opts["grace_s"] = grace_s
    io_deadline = opts_raw.get("io_deadline", "30s")
    try:
        io_deadline_s = parse_duration(io_deadline)
    except (ConfigError, TypeError, ValueError) as e:
        raise ConfigError(f"worker.io_deadline invalid: {e}") from e
    if io_deadline_s <= 0:
        raise ConfigError(
            f"worker.io_deadline must be > 0, got {io_deadline!r}")
    opts["io_deadline_s"] = io_deadline_s
    crc = opts_raw.get("crc", True)
    if not isinstance(crc, bool):
        raise ConfigError(f"worker.crc must be a bool, got {crc!r}")
    opts["crc"] = crc
    # a worker accepts the same top-level `tracing:` block as the engine
    # (sample knobs matter less here — the ingest tier owns the sampling
    # decision — but span caps and the kill switch do). Parsed even when
    # absent: from_mapping(None) is what consults the ARKFLOW_TRACE env
    # kill switch, which must bind device-tier workers too.
    opts["tracing"] = TracingConfig.from_mapping(m.get("tracing"))
    return [dict(p) for p in procs], opts


def build_worker_server(config: Mapping, *, host: str = "127.0.0.1",
                        port: int = 50052,
                        worker_id: Optional[str] = None,
                        max_frame: Optional[int] = None) -> ClusterWorkerServer:
    """Build (but don't start) a worker server from a parsed config mapping."""
    procs_cfg, opts = parse_worker_config(config)
    ensure_plugins_loaded()
    resource = Resource()
    processors = [build_component("processor", p, resource) for p in procs_cfg]
    return ClusterWorkerServer(
        processors, host=host, port=port,
        worker_id=worker_id or opts["worker_id"],
        max_in_flight=opts["max_in_flight"],
        max_frame=max_frame or opts["max_frame"],
        tracing=opts["tracing"],
        grace_s=opts["grace_s"],
        role=opts["role"],
        io_deadline_s=opts["io_deadline_s"],
        crc=opts["crc"])


async def run_worker(config: Mapping, *, host: str = "127.0.0.1",
                     port: int = 50052, worker_id: Optional[str] = None,
                     max_frame: Optional[int] = None) -> None:
    """CLI entry: build, warm up, then serve until cancelled, stopped by a
    SIGTERM self-drain, or (multi-host follower) released by the primary.

    With a ``distributed:`` block (or the ``ARKFLOW_*`` distributed env)
    naming more than one process, the worker joins a multi-host
    ``jax.distributed`` mesh: every process builds the IDENTICAL processor
    chain (so ``mesh: {pp: N}`` spans the global device list), process 0
    opens the serving port and broadcasts each infer batch, processes > 0
    run the lockstep follower loop (parallel/distributed.py) — one model
    too big for one process, served across several."""
    from arkflow_tpu.parallel.distributed import multihost_from_config

    mh = multihost_from_config(config)
    server = build_worker_server(config, host=host, port=port,
                                 worker_id=worker_id, max_frame=max_frame)
    if mh is not None and not mh.is_primary:
        from arkflow_tpu.parallel.distributed import run_follower

        # follower: same warmup (lockstep with the primary's), then replay
        # the primary's broadcast batches instead of serving a port
        await server.pipeline.connect()
        try:
            await run_follower(mh, server.pipeline)
        finally:
            await server.pipeline.close()
        return
    if mh is not None:
        from arkflow_tpu.parallel.distributed import LockstepPipeline

        # primary: every pipeline entry (warmup's compiles excepted — the
        # followers run connect() themselves, in the same order) fans the
        # batch out to the followers BEFORE processing, keeping the
        # multi-host collectives lockstep across processes
        server.pipeline = LockstepPipeline(mh, server.pipeline)
    await server.connect()  # warmup compiles BEFORE the port opens
    server.install_signal_handlers()
    try:
        await server.serve_forever()
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# ingest tier: worker handles, dispatcher, fleet swap
# ---------------------------------------------------------------------------


class _RemoteProcessingError(Exception):
    """The worker ran the batch and FAILED (model error, poison batch).

    Not retried on another worker: a deterministic failure would fail
    everywhere, and transient device faults heal through the stream's own
    nack/redelivery — which re-routes by hash to the same (by then probed
    and healed) worker."""


class _WorkerDraining(Exception):
    """The worker refused the batch because it is draining — routable."""


class RetryBudgetExhausted(Overloaded):
    """The dispatcher's ring-retry token bucket is empty: a fleet-wide
    brownout is amplifying offered load through failover retries, and the
    budget caps the amplification. The stream sheds the batch through the
    never-silent error-output path tagged ``reason=retry_budget`` (the
    ``shed_reason`` attribute is the stream's generic hook) instead of
    retry-storming a struggling fleet."""

    shed_reason = "retry_budget"


class RemoteWorker:
    """Ingest-side handle for one device worker: liveness, the advertised
    load signals, client-side in-flight accounting, and the per-worker
    autoscaling gauges."""

    def __init__(self, url: str, name: str):
        self.url = url
        self.host, self.port = parse_remote_url(url)
        self.worker_id: Optional[str] = None
        self.alive = False
        self.draining = False
        #: advertised AIMD window (heartbeat); routing headroom bound
        self.window = 1
        #: advertised queue-drain estimate (heartbeat)
        self.drain_s = 0.0
        #: client-side outstanding requests (fresh, unlike the heartbeat)
        self.inflight = 0
        self.dispatched = 0
        #: advertised disaggregation role (heartbeat; default both)
        self.role = "both"
        #: advertised incarnation epoch (register/heartbeat); fencing keys
        #: on it — a worker_id names the identity, this names the epoch
        self.incarnation: Optional[str] = None
        #: epochs declared dead by staleness/probe-timeout: frames from
        #: them are zombie frames and get rejected until the heal handshake
        #: re-mints (bounded — old fences age out, they only matter while
        #: the zombie could still be holding the stale epoch)
        self.fenced: deque = deque(maxlen=8)
        #: peer advertised crc32 frame-integrity support at register
        self.crc = False
        #: decode-side occupancy (heartbeat): generation slots and KV page
        #: pool pressure — real decode saturation, not just the AIMD window
        self.gen_slots = 0
        self.gen_slots_busy = 0
        self.page_occupancy = 0.0
        #: SDC defense signals (heartbeat; tpu/integrity.py): the combined
        #: param-digest epoch (None until the worker baselines), the
        #: worker's self-reported quarantined-member count, and the last
        #: digest value that passed an on-demand probe (so a legitimate
        #: weights-version outlier is not re-probed every beat)
        self.param_digest: Optional[str] = None
        self.integrity_corrupt = 0
        self.digest_cleared: Optional[str] = None
        self.last_report: dict = {}
        self.last_seen = 0.0
        self.last_error: Optional[str] = None
        reg = global_registry()
        labels = {"stream": name, "worker": url}
        self.m_alive = reg.gauge(
            "arkflow_cluster_worker_alive",
            "1 when the device worker answers register/heartbeat", labels)
        self.m_window = reg.gauge(
            "arkflow_cluster_worker_window",
            "worker-advertised AIMD admission window (autoscaling signal)",
            labels)
        self.m_drain = reg.gauge(
            "arkflow_cluster_worker_drain_seconds",
            "worker-advertised queue drain estimate (autoscaling signal)",
            labels)
        self.m_inflight = reg.gauge(
            "arkflow_cluster_worker_inflight",
            "ingest-side in-flight dispatches to this worker", labels)
        self.m_dispatched = reg.counter(
            "arkflow_cluster_dispatch_total",
            "batches dispatched to this worker", labels)

    def note_report(self, rep: dict, now: float) -> None:
        self.worker_id = rep.get("worker_id", self.worker_id)
        self.alive = True
        self.draining = bool(rep.get("draining", False))
        self.window = max(1, int(rep.get("window", 1)))
        self.drain_s = float(rep.get("drain_s", 0.0))
        inc = rep.get("incarnation")
        if isinstance(inc, str) and inc:
            self.incarnation = inc
        self.crc = bool(rep.get("crc", False))
        role = rep.get("role", "both")
        self.role = role if role in WORKER_ROLES else "both"
        self.gen_slots = int(rep.get("gen_slots", 0) or 0)
        self.gen_slots_busy = int(rep.get("gen_slots_busy", 0) or 0)
        self.page_occupancy = float(rep.get("page_pool_occupancy", 0.0) or 0.0)
        dig = rep.get("param_digest")
        self.param_digest = dig if isinstance(dig, str) and dig else None
        self.integrity_corrupt = int(rep.get("integrity_corrupt", 0) or 0)
        self.last_report = rep
        self.last_seen = now
        self.last_error = None
        self.m_alive.set(1.0)
        self.m_window.set(self.window)
        self.m_drain.set(self.drain_s)

    def note_down(self, err: BaseException) -> None:
        self.alive = False
        self.last_error = f"{type(err).__name__}: {err}"
        self.m_alive.set(0.0)

    def fence(self) -> Optional[str]:
        """Fence the current incarnation: it was declared dead while
        possibly still running (staleness / an unresponsive probe), so any
        later frame from it is a zombie's. Returns the fenced epoch."""
        inc = self.incarnation
        if inc and inc not in self.fenced:
            self.fenced.append(inc)
        return inc

    def is_fenced(self, incarnation: Optional[str]) -> bool:
        return bool(incarnation) and incarnation in self.fenced

    def serves(self, role: str) -> bool:
        """True when this worker accepts work of the given role."""
        return self.role == "both" or self.role == role

    def has_headroom(self) -> bool:
        if self.inflight >= self.window:
            return False
        # decode-side saturation folded in: every generation slot busy or
        # a nearly-full KV page pool means new work queues regardless of
        # what the AIMD window (which adapts a cycle behind) still admits
        if self.gen_slots and self.gen_slots_busy >= self.gen_slots:
            return False
        if self.page_occupancy >= 0.95:
            return False
        return True

    def report(self) -> dict:
        state = ("dead" if not self.alive
                 else "draining" if self.draining else "alive")
        out = {
            "worker": self.url,
            "worker_id": self.worker_id,
            "state": state,
            "role": self.role,
            "window": self.window,
            "drain_s": self.drain_s,
            "inflight": self.inflight,
            "dispatched": self.dispatched,
        }
        if self.gen_slots:
            out["gen_slots"] = self.gen_slots
            out["gen_slots_busy"] = self.gen_slots_busy
            out["page_pool_occupancy"] = self.page_occupancy
        if self.fenced:
            out["incarnation"] = self.incarnation
            out["fenced"] = list(self.fenced)
        if self.param_digest:
            out["param_digest"] = self.param_digest
        if self.integrity_corrupt:
            out["integrity_corrupt"] = self.integrity_corrupt
        if self.last_error:
            out["last_error"] = self.last_error
        remote_health = self.last_report.get("health")
        if remote_health:
            out["remote_health"] = remote_health
        remote_caches = self.last_report.get("caches")
        if remote_caches:
            out["remote_caches"] = remote_caches
        return out


class ClusterDispatcher:
    """The ingest tier's ``remote_tpu`` routing core.

    Owns the worker handles, the consistent-hash ring, the heartbeat loop,
    and the dispatch/retry discipline described in the module docstring."""

    def __init__(self, urls: Sequence[str], *, name: str = "cluster",
                 route_key: str = "fingerprint", prefix_bytes: int = 64,
                 text_field: Optional[str] = None, virtual_nodes: int = 64,
                 heartbeat_s: float = 2.0, request_timeout_s: float = 60.0,
                 connect_timeout_s: float = 5.0,
                 heartbeat_timeout_s: Optional[float] = None,
                 max_frame: int = DEFAULT_MAX_FRAME,
                 decode_candidates: int = 3,
                 crc: bool = True, io_deadline_floor_s: float = 0.1,
                 hedge: Optional[Mapping] = None,
                 retry_budget: Optional[Mapping] = None,
                 shadow_verify: Optional[Mapping] = None):
        from arkflow_tpu.batch import DEFAULT_BINARY_VALUE_FIELD

        if not urls:
            raise ConfigError("remote_tpu needs a non-empty 'workers' list")
        if len(set(urls)) != len(urls):
            raise ConfigError(f"remote_tpu workers must be distinct, got {urls}")
        if route_key not in ROUTE_KEYS:
            raise ConfigError(
                f"remote_tpu.route_key must be one of {ROUTE_KEYS}, "
                f"got {route_key!r}")
        self.name = name
        self.route_key = route_key
        self.prefix_bytes = prefix_bytes
        self.text_field = text_field or DEFAULT_BINARY_VALUE_FIELD
        self.heartbeat_s = heartbeat_s
        self.request_timeout_s = request_timeout_s
        self.connect_timeout_s = connect_timeout_s
        #: heartbeats older than this mark the member DEAD proactively — a
        #: SIGKILLed or network-wedged worker must fall out of the routing
        #: table on the heartbeat clock, not at the next 60s transport
        #: timeout. Also caps the probe round-trip itself, so one wedged
        #: member can't stall the whole heartbeat sweep.
        self.heartbeat_timeout_s = (
            heartbeat_timeout_s if heartbeat_timeout_s is not None
            else max(5.0 * heartbeat_s, 10.0))
        if self.heartbeat_timeout_s <= heartbeat_s:
            raise ConfigError(
                f"remote_tpu.heartbeat_timeout ({self.heartbeat_timeout_s}s) "
                f"must exceed the heartbeat period ({heartbeat_s}s)")
        if decode_candidates < 1:
            raise ConfigError(
                f"remote_tpu.decode_candidates must be >= 1, "
                f"got {decode_candidates}")
        #: how many occupancy-ordered decode destinations ride along with
        #: each prefill dispatch (failover depth for the second hop)
        self.decode_candidates = int(decode_candidates)
        self.virtual_nodes = virtual_nodes
        self.max_frame = int(max_frame)
        #: send crc32-trailed frames to workers that advertised support
        self.crc = bool(crc)
        #: floor under the deadline-derived per-hop I/O timeout: a batch
        #: with 3ms of budget left still gets a read window the transport
        #: can physically meet (it will shed at admission next hop anyway)
        self.io_deadline_floor_s = float(io_deadline_floor_s)
        # hedged dispatch (None = disabled): after a p99-EWMA delay (or the
        # configured fixed delay) re-send the infer to the ring successor,
        # first response wins — duplicates are safe because fingerprint
        # affinity + response caches make them idempotent under
        # at-least-once. Budget-capped so hedges can't melt spare capacity.
        self._hedge = dict(hedge) if hedge is not None else None
        if self._hedge is not None:
            self._hedge.setdefault("delay_s", None)  # None = auto (p99 EWMA)
            self._hedge.setdefault("max_fraction", 0.1)
            self._hedge.setdefault("burst", 4)
            self._hedge.setdefault("min_delay_s", 0.01)
        self._lat_samples: deque = deque(maxlen=128)
        self._p99_ewma: Optional[float] = None
        self._dispatch_count = 0
        self._hedges_issued = 0
        # ring-retry token bucket (None = unlimited, the historical
        # behavior): each dispatch deposits ``ratio`` tokens, each ring
        # failover spends one, so retries/offered <= ratio (+burst)
        self._retry_budget = (dict(retry_budget)
                              if retry_budget is not None else None)
        if self._retry_budget is not None:
            self._retry_budget.setdefault("ratio", 0.5)
            self._retry_budget.setdefault("burst", 8)
        self._retry_tokens = (float(self._retry_budget["burst"])
                              if self._retry_budget is not None else None)
        # shadow verification (None = disabled): every (1/fraction)-th
        # dispatch is ALSO sent to the ring successor and the two
        # responses' fingerprints compared — the defense against corruption
        # a worker cannot see in itself (its digests hash the corrupt tree
        # it already has; its golden probe runs on the corrupt chip).
        # Deterministic round-counting, not RNG: fraction 1.0 must shadow
        # EVERY batch (the soak's zero-corrupt-rows proof depends on it).
        self._shadow = dict(shadow_verify) if shadow_verify is not None else None
        if self._shadow is not None:
            self._shadow.setdefault("fraction", 0.05)
            self._shadow_every = max(
                1, round(1.0 / float(self._shadow["fraction"])))
        self._shadow_count = 0
        #: run when a worker is fenced for proven corruption — the ingest
        #: response cache epoch-bumps here (its cached answers from that
        #: worker may be poisoned)
        self.integrity_hooks: list = []
        #: in-process chaos transport (chaoswire.ChaosWire); armed by the
        #: fault plugin's net_* kinds, wraps the next opened connection
        self.chaos = None
        self.workers: dict[str, RemoteWorker] = {
            url: RemoteWorker(url, name) for url in urls}
        self.ring = HashRing(list(urls), virtual_nodes)
        self._hb_task: Optional[asyncio.Task] = None
        reg = global_registry()
        labels = {"stream": name}
        self.m_retries = reg.counter(
            "arkflow_cluster_retry_total",
            "dispatches that failed over to another ring worker", labels)
        self.m_spills = reg.counter(
            "arkflow_cluster_spill_total",
            "dispatches routed off the hash owner for load/drain reasons",
            labels)
        self.m_deaths = reg.counter(
            "arkflow_cluster_worker_down_total",
            "times a worker was marked down after a failed call", labels)
        self.m_fenced = reg.counter(
            "arkflow_cluster_fenced_total",
            "frames/reports rejected because they came from a fenced "
            "(staleness-declared-dead) worker incarnation", labels)
        self.m_frame_errors = reg.counter(
            "arkflow_cluster_frame_error_total",
            "flight frames that failed the crc32 integrity check", labels)
        self.m_retry_shed = reg.counter(
            "arkflow_cluster_retry_budget_exhausted_total",
            "dispatches shed because the ring-retry token bucket was empty",
            labels)
        self.m_hedge = {
            o: reg.counter(
                "arkflow_cluster_hedge_total",
                "hedged dispatch outcomes (issued / win = hedge beat the "
                "owner / primary_win = owner answered first / denied = "
                "budget cap / failed = both attempts failed)",
                {**labels, "outcome": o})
            for o in ("issued", "win", "primary_win", "denied", "failed")
        }
        self.m_shadow = {
            o: reg.counter(
                "arkflow_shadow_verify_total",
                "shadow-verify outcomes (issued / match / diverged / "
                "skipped = no partner or one attempt failed, so no "
                "comparison happened)",
                {**labels, "outcome": o})
            for o in ("issued", "match", "diverged", "skipped")
        }
        self.m_integrity_fence = reg.counter(
            "arkflow_cluster_integrity_fence_total",
            "workers fenced for proven or self-reported silent-data-"
            "corruption (heartbeat corrupt report, digest outlier confirmed "
            "by probe, or shadow-verify tiebreak)", labels)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Register with the fleet and start the heartbeat loop. At least
        one worker must answer — a stream with zero reachable workers is a
        deployment error worth failing loudly at connect; workers that come
        up later are adopted by the heartbeat."""
        if self._hb_task is not None:
            return
        await asyncio.gather(*(self._probe(w) for w in self.workers.values()),
                             return_exceptions=True)
        alive = [w for w in self.workers.values() if w.alive]
        if not alive:
            errs = "; ".join(f"{w.url}: {w.last_error}"
                             for w in self.workers.values())
            raise ConnectError(
                f"remote_tpu[{self.name}]: no cluster worker reachable "
                f"({errs})")
        logger.info("remote_tpu[%s]: %d/%d workers registered", self.name,
                    len(alive), len(self.workers))
        self._hb_task = asyncio.create_task(
            self._heartbeat_loop(), name=f"{self.name}-cluster-heartbeat")

    async def close(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except (asyncio.CancelledError, Exception):
                pass
            self._hb_task = None

    async def _heartbeat_loop(self) -> None:
        # per-worker probe tasks, NOT a gathered round: a black-holed member
        # pins its probe for the full heartbeat_timeout, and waiting on it
        # would stretch the round past the staleness cutoff — stale-fencing
        # HEALTHY siblings that answered every probe they were sent
        inflight: dict[str, asyncio.Task] = {}
        try:
            while True:
                await asyncio.sleep(self.heartbeat_s)
                self._expire_stale()
                for w in list(self.workers.values()):
                    t = inflight.get(w.url)
                    if t is not None and not t.done():
                        continue  # previous probe still inside its timeout
                    inflight[w.url] = asyncio.create_task(self._probe(w))
        finally:
            for t in inflight.values():
                t.cancel()

    def _is_stale(self, w: RemoteWorker, now: float) -> bool:
        return (w.alive and w.last_seen > 0.0
                and now - w.last_seen > self.heartbeat_timeout_s)

    def _expire_stale(self, now: Optional[float] = None) -> None:
        """Proactively kill members whose heartbeats went quiet (the
        SIGKILL / network-wedge case: the socket may still accept, so no
        transport failure ever fires). Runs on the heartbeat clock AND at
        plan time, so routing never waits on the sweep."""
        if now is None:
            now = asyncio.get_running_loop().time()
        for w in self.workers.values():
            if self._is_stale(w, now):
                self.m_deaths.inc()
                fenced = w.fence()
                logger.warning(
                    "remote_tpu[%s]: worker %s heartbeats stale for %.1fs "
                    "(timeout %.1fs); marking dead, fencing incarnation %s",
                    self.name, w.url, now - w.last_seen,
                    self.heartbeat_timeout_s, fenced)
                w.note_down(ConnectError(
                    f"heartbeats stale for {now - w.last_seen:.1f}s"))

    async def _probe(self, w: RemoteWorker) -> None:
        """One register/heartbeat round-trip; flips liveness both ways.
        Bounded by the heartbeat timeout, NOT the request timeout — a
        wedged member answering nothing must not pin the sweep for the
        full infer budget."""
        action = "heartbeat" if w.worker_id is not None else "register"
        try:
            rep = await self._unary(w, {"action": action},
                                    timeout=self.heartbeat_timeout_s)
        except asyncio.TimeoutError as e:
            # answered nothing inside the probe bound: unresponsive but
            # possibly still RUNNING (one-way partition, wedge) — fence the
            # epoch so its frames are rejectable if it resurfaces
            if w.alive:
                self.m_deaths.inc()
                logger.warning(
                    "remote_tpu[%s]: worker %s probe timed out; marking "
                    "dead, fencing incarnation %s", self.name, w.url,
                    w.fence())
            w.note_down(e)
            return
        except Exception as e:
            if w.alive:
                self.m_deaths.inc()
                logger.warning("remote_tpu[%s]: worker %s down: %s",
                               self.name, w.url, e)
            w.note_down(e)
            return
        inc = rep.get("incarnation")
        if w.is_fenced(inc):
            # a partition-healed zombie heartbeating from its fenced epoch:
            # reject the report (its occupancy/window are stale), then heal
            # explicitly — ask it to re-mint, and admit the FRESH epoch
            self.m_fenced.inc()
            logger.warning(
                "remote_tpu[%s]: worker %s answered from fenced incarnation "
                "%s (partition-healed zombie); rejecting its report and "
                "requesting a re-mint", self.name, w.url, inc)
            try:
                rep = await self._unary(
                    w, {"action": "register", "fence": inc},
                    timeout=self.heartbeat_timeout_s)
            except Exception as e:
                w.note_down(e)
                return
            if w.is_fenced(rep.get("incarnation")):
                w.note_down(ConnectError(
                    f"worker {w.url} still answering from fenced "
                    f"incarnation {inc} after a heal handshake"))
                return
        if not rep.get("ok") or not rep.get("worker_id"):
            # answers-but-refuses is NOT alive: a scan-tier FlightWorker (or
            # any wrong endpoint) replies {"ok": false, "error": "unknown
            # action ..."} — marking it alive would pass the connect gate on
            # a fleet with zero usable workers
            w.note_down(ConnectError(
                f"worker {w.url} rejected {action}: {rep.get('error')!r} "
                "(is this really a cluster worker?)"))
            return
        proto = int(rep.get("proto", 1))
        if proto > PROTO_VERSION:
            w.note_down(ConnectError(
                f"worker speaks protocol {proto}, this engine speaks "
                f"{PROTO_VERSION}"))
            return
        if not w.alive:
            logger.info("remote_tpu[%s]: worker %s up (id=%s)", self.name,
                        w.url, rep.get("worker_id"))
        w.note_report(rep, asyncio.get_running_loop().time())
        await self._integrity_check(w)

    # -- SDC defense (tpu/integrity.py, cluster tier) ----------------------

    def _fence_for_integrity(self, w: RemoteWorker, reason: str) -> None:
        """Fence a worker on proven (or self-reported) corruption through
        the PR-19 incarnation path: its epoch is dead to the ring until the
        heal handshake re-mints it, and anything caching its past answers
        flushes. A worker whose member stays CORRUPT keeps re-reporting it
        on every heartbeat, so backoff alone never re-admits it — only a
        successful worker-side repair does."""
        self.m_integrity_fence.inc()
        self.m_deaths.inc()
        logger.error(
            "remote_tpu[%s]: fencing worker %s for integrity: %s "
            "(incarnation %s)", self.name, w.url, reason, w.fence())
        w.note_down(ProcessError(f"integrity: {reason}"))
        for hook in self.integrity_hooks:
            try:
                hook()
            except Exception:
                logger.exception("integrity fence hook failed")

    async def _integrity_check(self, w: RemoteWorker) -> None:
        """Heartbeat-time SDC fencing. A worker self-reporting quarantined
        (CORRUPT) members serves nothing until repaired. A worker whose
        param-digest epoch disagrees with the majority of digest-reporting
        peers (3+ reporting) is an OUTLIER — but an outlier is only proof
        of different weights, not corruption (a mid-roll hot-swap looks
        identical), so it is fenced only when its own on-demand golden
        probe confirms a mismatch; a clean probe clears that digest value
        until it changes again."""
        if w.integrity_corrupt:
            self._fence_for_integrity(
                w, f"{w.integrity_corrupt} corrupt member(s) self-reported")
            return
        dig = w.param_digest
        if not dig or dig == w.digest_cleared:
            return
        peers = [x.param_digest for x in self.workers.values()
                 if x.alive and x.param_digest]
        if len(peers) < 3:
            return  # no majority to compare against
        major, nmaj = Counter(peers).most_common(1)[0]
        if dig == major or nmaj <= len(peers) // 2:
            return
        try:
            rep = await self._unary(w, {"action": "integrity_probe"},
                                    timeout=self.request_timeout_s)
        except Exception as e:
            w.note_down(e)
            return
        if int(rep.get("mismatches", 0) or 0) or int(rep.get("corrupt", 0)
                                                     or 0):
            self._fence_for_integrity(
                w, f"digest outlier ({nmaj}/{len(peers)} peers agree on "
                   f"{major[:12]}, this worker reports {dig[:12]}) confirmed "
                   "by golden probe")
            return
        w.digest_cleared = dig
        logger.warning(
            "remote_tpu[%s]: worker %s is a param-digest outlier but passed "
            "its golden probe — different weights version (mid-swap?), not "
            "corruption; admitting", self.name, w.url)

    # -- wire helpers ------------------------------------------------------

    def chaos_arm(self, kind: str, *, duration_s: float = 0.0,
                  seed: int = 0) -> None:
        """Arm one network fault on the next flight connection this
        dispatcher opens (the ``fault`` plugin's ``net_*`` kinds land
        here). Lazily creates the seeded chaos transport."""
        if self.chaos is None:
            from arkflow_tpu.connect.chaoswire import ChaosWire

            self.chaos = ChaosWire(seed=seed)
        self.chaos.arm(kind, duration_s=duration_s)

    async def _open(self, w: RemoteWorker):
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(w.host, w.port),
                self.connect_timeout_s)
        except (OSError, asyncio.TimeoutError) as e:
            raise ConnectError(
                f"cluster worker {w.url} unreachable: {e}") from e
        if self.chaos is not None and self.chaos.pending():
            reader, writer = self.chaos.wrap(reader, writer)
        return reader, writer

    async def _unary(self, w: RemoteWorker, request: dict,
                     timeout: Optional[float] = None) -> dict:
        """One request frame -> one JSON status frame."""
        reader, writer = await self._open(w)
        what = f"{request.get('action', 'unary')} status"
        try:
            await _send_frame(writer, json.dumps(request).encode(),
                              crc=self.crc and w.crc)
            raw = await asyncio.wait_for(
                _read_frame(reader, self.max_frame, what=what),
                timeout or self.request_timeout_s)
            if raw is None:
                raise ConnectError(
                    f"cluster worker {w.url} closed before a status")
            return json.loads(raw.decode())
        finally:
            try:
                writer.close()
            except Exception:
                pass

    # -- routing -----------------------------------------------------------

    def routing_key(self, batch: MessageBatch) -> bytes:
        """``fingerprint`` keys on the batch's stable identity (dedup /
        response-cache affinity: redeliveries and byte-identical retries
        hash equal). ``prefix`` keys on the first ``prefix_bytes`` of the
        first row's payload (prompt-prefix affinity: conversations sharing
        a system prompt land where their KV prefix is cached)."""
        if self.route_key == "prefix":
            try:
                values, offsets = batch.payload_view(self.text_field)
                end = min(int(offsets[0]) + self.prefix_bytes, int(offsets[1]))
                return values[int(offsets[0]):end].tobytes()
            except Exception:
                pass  # no payload column: fall through to the fingerprint
        return batch_fingerprint(batch)

    def plan(self, key: bytes, *,
             role: Optional[str] = None) -> list[RemoteWorker]:
        """Candidate order for a key: ring order over live, non-draining
        workers, weighted by each worker's advertised load signals. The hash
        owner serves unless it has no headroom against its advertised AIMD
        window — then the dispatch spills to the successor with the least
        load (fewest outstanding dispatches, then smallest advertised drain
        estimate). Bounded-load consistent hashing: affinity is traded only
        under saturation, counted in ``arkflow_cluster_spill_total``.

        With ``role`` set (a role-split fleet), only workers serving that
        role are candidates — the ring walk skips the others, so prefix
        affinity over the PREFILL sub-ring survives exactly as it would on
        an undivided fleet.

        Stale members are expired here too (not only on the heartbeat
        clock): a dead worker's hash range falls to its ring successor the
        moment any batch routes, so affinity keys re-home deterministically
        with zero dispatches burned on the corpse."""
        try:
            self._expire_stale()
        except RuntimeError:
            pass  # no running loop (sync planning in tests): skip expiry
        live = [self.workers[u] for u in self.ring.candidates(key)
                if u in self.workers
                and self.workers[u].alive and not self.workers[u].draining
                and (role is None or self.workers[u].serves(role))]
        if len(live) < 2 or live[0].has_headroom():
            return live
        with_room = [w for w in live[1:] if w.has_headroom()]
        if with_room:
            best = min(with_room, key=lambda w: (w.inflight, w.drain_s))
            self.m_spills.inc()
            return [best] + [w for w in live if w is not best]
        # the whole fleet is saturated: queue on the owner (keeping
        # affinity) unless its advertised drain estimate is pathologically
        # worse than the best alternative's — a wedged-but-alive owner must
        # not absorb the queue forever
        floor = min(w.drain_s for w in live)
        if live[0].drain_s > 2.0 * floor + 1.0:
            best = min(live, key=lambda w: w.drain_s)
            self.m_spills.inc()
            return [best] + [w for w in live if w is not best]
        return live

    def role_split(self) -> bool:
        """True when any live worker declared a non-``both`` role — the
        fleet is running disaggregated and dispatch goes two-hop."""
        return any(w.role != "both"
                   for w in self.workers.values() if w.alive)

    def decode_targets(self) -> list[RemoteWorker]:
        """Decode placement order: live, non-draining decode-capable
        workers sorted by real decode pressure from the heartbeats — slot
        occupancy first, then KV page pressure, then outstanding
        dispatches. The prefill worker tries them in this order, so pages
        land where slots are actually free (capped at
        ``decode_candidates``)."""
        cands = [w for w in self.workers.values()
                 if w.alive and not w.draining and w.serves("decode")]
        cands.sort(key=lambda w: (
            (w.gen_slots_busy / w.gen_slots) if w.gen_slots else 0.0,
            w.page_occupancy, w.inflight, w.url))
        return cands[: self.decode_candidates]

    def _hop_timeout(self, batch: Optional[MessageBatch]) -> float:
        """Per-hop I/O deadline: the batch's remaining end-to-end budget
        (``__meta_ext_deadline_ms``) when it carries one, clamped between
        the floor and the flat request timeout. A wedged owner then costs
        the batch's own budget, not 30-60s of everyone's."""
        t = self.request_timeout_s
        if batch is None:
            return t
        try:
            rem = batch.remaining_deadline_ms()
        except Exception:
            rem = None
        if rem is None:
            return t
        return max(self.io_deadline_floor_s, min(t, rem / 1000.0))

    def _note_latency(self, dt: float) -> None:
        self._lat_samples.append(dt)
        if len(self._lat_samples) >= 8:
            s = sorted(self._lat_samples)
            p99 = s[min(len(s) - 1, int(0.99 * len(s)))]
            self._p99_ewma = (p99 if self._p99_ewma is None
                              else 0.8 * self._p99_ewma + 0.2 * p99)

    def latency_snapshot(self) -> list[float]:
        """Recent per-dispatch latencies (seconds) — soaks read p99 here."""
        return sorted(self._lat_samples)

    def _hedge_delay_s(self) -> float:
        assert self._hedge is not None
        fixed = self._hedge["delay_s"]
        if fixed is not None:
            return fixed
        floor = self._hedge["min_delay_s"]
        if self._p99_ewma is not None:
            return max(self._p99_ewma, floor)
        # cold start (no latency samples yet): hedge late rather than
        # doubling every warmup dispatch
        return max(self.request_timeout_s / 4.0, floor)

    def _hedge_budget_ok(self) -> bool:
        assert self._hedge is not None
        return (self._hedges_issued
                < self._hedge["max_fraction"] * self._dispatch_count
                + self._hedge["burst"])

    async def _attempt(self, w: RemoteWorker, batch: MessageBatch, *,
                       ctx, tracer, decode_urls: Sequence[str],
                       decode_crc: Sequence[str],
                       fenced: Sequence[str],
                       timeout_s: float) -> list[MessageBatch]:
        """One dispatch attempt on one worker, with the per-worker
        accounting that used to live inline in the dispatch loop. Raises
        classified: ``_WorkerDraining`` (marked), ``_RemoteProcessingError``
        (terminal), transport errors (worker marked down)."""
        w.inflight += 1
        w.m_inflight.set(w.inflight)
        try:
            out = await self._infer_on(w, batch, ctx=ctx, tracer=tracer,
                                       decode_urls=decode_urls,
                                       decode_crc=decode_crc, fenced=fenced,
                                       timeout_s=timeout_s)
        except _WorkerDraining:
            w.draining = True
            raise
        except _RemoteProcessingError:
            raise
        except FrameIntegrityError as e:
            # one corrupted frame is transport damage, not a dead worker:
            # fail over for THIS batch, keep the worker in the ring
            self.m_frame_errors.inc()
            logger.warning(
                "remote_tpu[%s]: corrupt frame from %s (%s); failing over "
                "without marking it down", self.name, w.url, e)
            raise
        except (ConnectError, ConnectionError, OSError,
                asyncio.IncompleteReadError, asyncio.TimeoutError) as e:
            if w.alive:
                self.m_deaths.inc()
                logger.warning(
                    "remote_tpu[%s]: worker %s failed mid-dispatch (%s); "
                    "retrying on the ring's next worker", self.name,
                    w.url, e)
            w.note_down(e)
            raise
        else:
            w.dispatched += 1
            w.m_dispatched.inc()
            return out
        finally:
            w.inflight -= 1
            w.m_inflight.set(w.inflight)

    async def _attempt_hedged(self, primary: RemoteWorker,
                              hedge_w: RemoteWorker, batch: MessageBatch,
                              **kw) -> list[MessageBatch]:
        """Race the owner against its ring successor: the hedge launches
        only after the hedge delay (p99 EWMA or configured) AND under the
        hedge budget; first success wins, the loser is cancelled. Safe
        duplicate execution: both workers compute the same fingerprint, so
        response caches keep the answers byte-identical."""
        p_task = asyncio.ensure_future(self._attempt(primary, batch, **kw))
        done, _ = await asyncio.wait({p_task}, timeout=self._hedge_delay_s())
        if p_task in done:
            return p_task.result()  # raises through, classified
        if not self._hedge_budget_ok():
            self.m_hedge["denied"].inc()
            return await p_task
        self._hedges_issued += 1
        self.m_hedge["issued"].inc()
        h_task = asyncio.ensure_future(self._attempt(hedge_w, batch, **kw))
        pending = {p_task, h_task}
        failures: list[BaseException] = []
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    try:
                        result = t.result()
                    except _RemoteProcessingError:
                        raise  # terminal: no point waiting on the sibling
                    except Exception as e:
                        failures.append(e)
                        continue
                    loser = primary if t is h_task else hedge_w
                    self.m_hedge["win" if t is h_task
                                 else "primary_win"].inc()
                    if t is h_task:
                        logger.info(
                            "remote_tpu[%s]: hedge to %s won the race; "
                            "cancelled the owner %s", self.name,
                            hedge_w.url, loser.url)
                    return result
            self.m_hedge["failed"].inc()
            raise failures[-1]
        finally:
            for t in (p_task, h_task):
                if not t.done():
                    t.cancel()
            # settle the cancelled loser so its inflight accounting and
            # connection teardown finish before we return
            await asyncio.gather(p_task, h_task, return_exceptions=True)

    async def _attempt_shadow(self, primary: RemoteWorker,
                              shadow_w: RemoteWorker, batch: MessageBatch,
                              **kw) -> list[MessageBatch]:
        """Dual-dispatch one sampled batch to the owner AND its ring
        successor and compare response signatures. Unlike a hedge (first
        success wins) shadow-verify needs BOTH answers: a lone corrupted
        worker produces a plausible, well-formed response that only
        disagreement can expose. On divergence neither side is trusted by
        fiat — each runs its golden probe, and whichever fails it is fenced
        as corrupt; the other's answer is delivered. Transport failure on
        either leg degrades to normal single delivery ("skipped")."""
        self.m_shadow["issued"].inc()
        p_task = asyncio.ensure_future(self._attempt(primary, batch, **kw))
        s_task = asyncio.ensure_future(self._attempt(shadow_w, batch, **kw))
        results = await asyncio.gather(p_task, s_task, return_exceptions=True)
        p_res, s_res = results
        if isinstance(p_res, _RemoteProcessingError):
            raise p_res  # terminal regardless of what the shadow said
        if isinstance(p_res, BaseException) and isinstance(s_res,
                                                           BaseException):
            raise p_res  # both legs died: classified failover as usual
        if isinstance(p_res, BaseException) or isinstance(s_res,
                                                          BaseException):
            # one leg lost transport — no comparison possible this round
            self.m_shadow["skipped"].inc()
            return s_res if isinstance(p_res, BaseException) else p_res
        p_sig = tuple(batch_fingerprint(b) for b in p_res)
        s_sig = tuple(batch_fingerprint(b) for b in s_res)
        if p_sig == s_sig:
            self.m_shadow["match"].inc()
            return p_res
        self.m_shadow["diverged"].inc()
        logger.error(
            "remote_tpu[%s]: shadow-verify divergence between %s and %s; "
            "running golden-probe tiebreak", self.name, primary.url,
            shadow_w.url)
        bad: list[RemoteWorker] = []
        for w in (primary, shadow_w):
            try:
                rep = await self._unary(w, {"action": "integrity_probe"},
                                        timeout=self.request_timeout_s)
            except Exception as e:
                w.note_down(e)
                bad.append(w)
                continue
            if int(rep.get("mismatches", 0) or 0) or int(
                    rep.get("corrupt", 0) or 0):
                self._fence_for_integrity(
                    w, "shadow-verify divergence confirmed by golden probe")
                bad.append(w)
        if primary not in bad:
            return p_res
        if shadow_w not in bad:
            return s_res
        raise ConnectError(
            f"remote_tpu[{self.name}]: shadow-verify divergence between "
            f"{primary.url} and {shadow_w.url} and neither passed its "
            "golden probe; failing over")

    async def dispatch(self, batch: MessageBatch) -> list[MessageBatch]:
        """Route one emission to the fleet; failover along the ring on
        transport errors, bounded by the retry budget; hedged against the
        ring successor when configured. Raises on remote PROCESSING errors
        (no sibling retry — see _RemoteProcessingError) and when every
        worker is down (the stream's nack path then preserves
        at-least-once).

        On a role-split fleet the plan is two-hop: prompts go to a
        prefill-capable worker chosen by prefix hash (hop 1), carrying the
        occupancy-ordered decode candidate list; the prefill worker streams
        finished KV pages to the first accepting decode worker (hop 2) and
        relays its tokens on this same infer stream."""
        decode_urls: list[str] = []
        decode_crc: list[str] = []
        if self.role_split():
            candidates = self.plan(self.routing_key(batch), role="prefill")
            targets = self.decode_targets()
            decode_urls = [w.url for w in targets]
            decode_crc = [w.url for w in targets if w.crc]
        else:
            candidates = self.plan(self.routing_key(batch))
        if not candidates:
            raise ConnectError(
                f"remote_tpu[{self.name}]: no live cluster worker "
                f"(fleet: {[w.report()['state'] for w in self.workers.values()]})")
        # fence list rides with the request: a worker (or its kv_push
        # peers) whose incarnation appears here knows it was declared dead
        # and refuses retryably instead of serving from a stale epoch
        fenced = sorted({f for w in self.workers.values() for f in w.fenced})
        # prefer the ambient stream scope (hops then parent under the
        # process span, and in-process test fleets keep tier separation);
        # fall back to the batch's own column for direct dispatcher use
        from arkflow_tpu.obs.trace import current_scope

        scope = current_scope()
        if scope is not None:
            tracer, ctx = scope.tracer, scope.ctx
        else:
            tracer = global_tracer()
            ctx = batch.trace_context() if tracer.enabled else None
        self._dispatch_count += 1
        if self._retry_tokens is not None:
            self._retry_tokens = min(
                self._retry_tokens + self._retry_budget["ratio"],
                float(self._retry_budget["burst"]))
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        kw = dict(ctx=ctx, tracer=tracer, decode_urls=decode_urls,
                  decode_crc=decode_crc, fenced=fenced,
                  timeout_s=self._hop_timeout(batch))
        last_exc: Optional[BaseException] = None
        i, n = 0, len(candidates)
        # deterministic every-Nth sampling (no RNG: fraction 1.0 must
        # shadow EVERY batch, and the soak's accounting depends on it);
        # role-split fleets skip it — prefill/decode answers aren't
        # comparable across the two-hop path
        do_shadow = False
        if self._shadow is not None and not self.role_split():
            self._shadow_count += 1
            if self._shadow_count % self._shadow_every == 0:
                if n >= 2:
                    do_shadow = True
                else:
                    self.m_shadow["skipped"].inc()
        while i < n:
            if i > 0:
                if self._retry_tokens is not None:
                    if self._retry_tokens < 1.0:
                        self.m_retry_shed.inc()
                        raise RetryBudgetExhausted(
                            f"remote_tpu[{self.name}]: ring retry budget "
                            f"exhausted after {i} attempt(s) (ratio "
                            f"{self._retry_budget['ratio']}, last: "
                            f"{last_exc}); shedding instead of amplifying "
                            "a fleet-wide brownout",
                            retry_after_s=self.heartbeat_s)
                    self._retry_tokens -= 1.0
                self.m_retries.inc()
            w = candidates[i]
            shadow_w = (candidates[i + 1]
                        if do_shadow and i + 1 < n else None)
            hedge_w = (candidates[i + 1]
                       if shadow_w is None and self._hedge is not None
                       and i + 1 < n else None)
            try:
                if shadow_w is not None:
                    out = await self._attempt_shadow(w, shadow_w, batch,
                                                     **kw)
                elif hedge_w is not None:
                    out = await self._attempt_hedged(w, hedge_w, batch, **kw)
                else:
                    out = await self._attempt(w, batch, **kw)
            except _RemoteProcessingError as e:
                raise ProcessError(
                    f"cluster worker {w.url} failed the batch: {e}") from e
            except (_WorkerDraining, ConnectError, ConnectionError, OSError,
                    asyncio.IncompleteReadError, asyncio.TimeoutError,
                    ReadError) as e:
                last_exc = (ConnectError(f"worker {w.url} draining")
                            if isinstance(e, _WorkerDraining) else e)
                # a shadowed/hedged round consumed two candidates; skip both
                i += (2 if (hedge_w is not None or shadow_w is not None)
                      else 1)
                continue
            else:
                self._note_latency(loop.time() - t0)
                return out
        raise ConnectError(
            f"remote_tpu[{self.name}]: all {n} candidate "
            f"workers failed for this batch (last: {last_exc}); leaving it "
            "to the redelivery path")

    async def _infer_on(self, w: RemoteWorker, batch: MessageBatch, *,
                        ctx: Optional[TraceContext] = None,
                        tracer: Optional[Tracer] = None,
                        decode_urls: Sequence[str] = (),
                        decode_crc: Sequence[str] = (),
                        fenced: Sequence[str] = (),
                        timeout_s: Optional[float] = None) -> list[MessageBatch]:
        import time as _time

        from arkflow_tpu.obs.trace import _new_id

        if timeout_s is None:
            timeout_s = self.request_timeout_s
        use_crc = self.crc and w.crc
        # per-hop tracing: the hop span's id is minted BEFORE the call so
        # the worker can parent its spans under it; serialize / transport /
        # deserialize are ingest-side children, remote_* spans arrive in the
        # worker's TRACE_TAG frame. A retried dispatch records one hop span
        # per attempted worker.
        hop_id = _new_id() if ctx is not None else ""
        t_hop = _time.perf_counter()
        hop_ok = False
        reader, writer = await self._open(w)
        try:
            req: dict = {"action": "infer"}
            if decode_urls:
                # two-hop disagg plan: the prefill worker pushes finished
                # KV pages to these, in this occupancy order (skipping
                # itself — a 'both' worker just decodes locally)
                req["decode_workers"] = [u for u in decode_urls if u != w.url]
                if decode_crc:
                    # subset of decode_workers that negotiated crc framing,
                    # so the prefill worker protects its kv_push slabs too
                    req["decode_crc"] = [u for u in decode_crc if u != w.url]
            if fenced:
                req["fenced"] = list(fenced)
            if ctx is not None:
                req["trace"] = ctx.with_parent(hop_id).to_dict()
            t0 = _time.perf_counter()
            ipc = batch_to_ipc(batch.record_batch)
            if tracer is not None:
                tracer.record(ctx, "flight_serialize",
                              _time.perf_counter() - t0, parent_id=hop_id)
            t_send = _time.perf_counter()
            await _send_frame(writer, json.dumps(req).encode(), crc=use_crc)
            await _send_frame(writer, ipc, crc=use_crc)
            raw = await asyncio.wait_for(
                _read_frame(reader, self.max_frame, what="infer status"),
                timeout_s)
            if raw is None:
                raise ConnectError(f"worker {w.url} closed before a status")
            if tracer is not None:
                # send -> status round trip: wire + the worker's accept path
                # (its own decode/queue/step costs arrive as remote_* spans)
                tracer.record(ctx, "flight_transport",
                              _time.perf_counter() - t_send, parent_id=hop_id)
            try:
                status = json.loads(raw.decode())
            except (UnicodeDecodeError, ValueError) as e:
                # a status frame that isn't JSON is wire damage from a peer
                # without crc trailers (negotiated-off, or a corrupted
                # register) — fail over loudly, don't quarantine the batch
                raise FrameIntegrityError(
                    f"undecodable infer status frame from {w.url}: "
                    f"{e!r}") from e
            inc = status.get("incarnation")
            if isinstance(inc, str) and w.is_fenced(inc):
                # a partition-healed zombie answered from its fenced epoch:
                # its caches and occupancy are stale — reject and fail over
                self.m_fenced.inc()
                raise ConnectError(
                    f"worker {w.url} answered from fenced incarnation "
                    f"{inc}; rejecting the zombie's response")
            if not status.get("ok"):
                if status.get("reason") == "frame_integrity":
                    # OUR request arrived corrupted; the worker refused it
                    # unprocessed — surface as the same loud integrity error
                    # a corrupted response raises (failover, counted, and no
                    # draining/death bookkeeping for a healthy worker)
                    raise FrameIntegrityError(status.get("error"))
                if status.get("retryable"):
                    raise _WorkerDraining(status.get("error"))
                raise _RemoteProcessingError(status.get("error"))
            results: list[MessageBatch] = []
            deser_s = 0.0
            while True:
                frame = await asyncio.wait_for(
                    _read_frame(reader, self.max_frame, what="infer frame"),
                    timeout_s)
                if frame is None:
                    if tracer is not None:
                        tracer.record(ctx, "flight_deserialize", deser_s,
                                      parent_id=hop_id)
                    hop_ok = True
                    return results
                tag, payload = frame[:1], frame[1:]
                if tag == ERROR_TAG:
                    raise _RemoteProcessingError(
                        json.loads(payload.decode()).get("error"))
                if tag == TRACE_TAG:
                    if tracer is not None:
                        try:
                            tracer.adopt_spans(
                                ctx, json.loads(payload.decode()).get("spans") or [])
                        except (ValueError, AttributeError, TypeError):
                            # a mangled trace frame must never fail a batch
                            # whose results already streamed fine
                            logger.warning("malformed trace frame from %s", w.url)
                    continue
                t_d = _time.perf_counter()
                for rb in ipc_to_batches(payload):
                    results.append(MessageBatch(rb))
                deser_s += _time.perf_counter() - t_d
        finally:
            if tracer is not None and ctx is not None:
                # EVERY attempt roots its subtree — a failed hop's
                # flight/worker children must not dangle, and the failure
                # itself is worth seeing in the tree
                tracer.record(
                    ctx, "cluster_hop", _time.perf_counter() - t_hop,
                    span_id=hop_id,
                    attrs={"worker": w.url,
                           **({} if hop_ok else {"error": True})})
            try:
                writer.close()
            except Exception:
                pass

    # -- fleet lifecycle (drain / swap legs / elastic membership) ----------

    def add_worker(self, url: str) -> RemoteWorker:
        """Adopt a worker into the routing table and hash ring at runtime
        (fleet scale-out). Idempotent on url. Virtual-node hashing means
        only the keys that land on the newcomer's points remap — existing
        workers' response/prefix caches stay warm."""
        existing = self.workers.get(url)
        if existing is not None:
            return existing
        parse_remote_url(url)  # raises ConfigError on malformed urls
        w = RemoteWorker(url, self.name)
        self.workers[url] = w
        self.ring.add(url)
        logger.info("remote_tpu[%s]: worker %s added to the ring (fleet "
                    "size %d)", self.name, url, len(self.workers))
        return w

    def remove_worker(self, url: str) -> None:
        """Retire a worker from the table and ring (fleet scale-in or a
        departed spawn). Its key ranges fall to the ring successors; no-op
        for unknown urls."""
        if self.workers.pop(url, None) is None:
            return
        self.ring.remove(url)
        logger.info("remote_tpu[%s]: worker %s removed from the ring "
                    "(fleet size %d)", self.name, url, len(self.workers))

    async def set_drain(self, w: RemoteWorker, drain: bool) -> dict:
        rep = await self._unary(w, {"action": "drain", "drain": drain})
        if rep.get("ok"):
            w.draining = drain
        return rep

    async def wait_drained(self, w: RemoteWorker, timeout_s: float) -> None:
        """Poll the worker until its in-flight steps finished."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while True:
            rep = await self._unary(w, {"action": "heartbeat"})
            if int(rep.get("inflight", 0)) == 0:
                return
            if loop.time() >= deadline:
                raise SwapError(
                    f"worker {w.url} still has {rep.get('inflight')} "
                    f"in-flight steps after {timeout_s:.1f}s drain budget")
            await asyncio.sleep(min(0.1, timeout_s / 10.0))

    async def swap_on(self, w: RemoteWorker, checkpoint: str) -> dict:
        # restore+canary+probe can take a while: give it the drain budget
        # on top of the normal request timeout
        return await self._unary(w, {"action": "swap", "checkpoint": checkpoint},
                                 timeout=max(self.request_timeout_s, 300.0))

    # -- introspection -----------------------------------------------------

    def report(self) -> dict:
        out = {
            "workers": {u: w.report() for u, w in sorted(self.workers.items())},
            "alive": sum(1 for w in self.workers.values() if w.alive),
            "route_key": self.route_key,
            "retries": self.m_retries.value,
            "spills": self.m_spills.value,
            "fenced_rejections": self.m_fenced.value,
            "frame_errors": self.m_frame_errors.value,
        }
        if self._hedge is not None:
            out["hedge"] = {
                "dispatches": self._dispatch_count,
                "issued": self._hedges_issued,
                "outcomes": {k: c.value for k, c in self.m_hedge.items()},
                "p99_ewma_s": self._p99_ewma,
            }
        if self._retry_tokens is not None:
            out["retry_budget"] = {
                "tokens": self._retry_tokens,
                "shed": self.m_retry_shed.value,
            }
        if self._shadow is not None:
            out["shadow_verify"] = {
                "fraction": self._shadow["fraction"],
                "every": self._shadow_every,
                "outcomes": {k: c.value for k, c in self.m_shadow.items()},
            }
        out["integrity_fences"] = self.m_integrity_fence.value
        return out

    def health_reports(self) -> list[dict]:
        """Engine /health and /readiness aggregation: one report per worker
        in the shape the engine's runner walk expects (``state`` keys to
        the readiness check — an all-dead fleet flips the replica 503)."""
        return [w.report() for w in sorted(self.workers.values(),
                                           key=lambda w: w.url)]


class ClusterSwapper:
    """Fleet-wide rolling hot-swap: ``POST /admin/swap`` on the ingest
    engine reaches this via the processor's ``swapper`` attribute and rolls
    worker-by-worker — drain (the ring serves on N-1), swap via the
    worker's OWN canary/probe/rollback manager, undrain. A failed worker
    swap stops the roll: its own manager already rolled that worker back,
    committed workers keep the new version, and the raised SwapError names
    both sets so the operator can re-POST either checkpoint."""

    def __init__(self, dispatcher: ClusterDispatcher,
                 drain_timeout_s: float = 30.0):
        self.dispatcher = dispatcher
        self.drain_timeout_s = drain_timeout_s
        self._commit_hooks: list = []
        self._swapping = False
        self._last: dict = {}

    def add_commit_hook(self, hook) -> None:
        """Runs when any worker flipped (the PR-10 cache discipline: a
        flipped worker may have answered live traffic with new weights, so
        the ingest response cache must epoch-flush even on a partial roll)."""
        self._commit_hooks.append(hook)

    def _run_commit_hooks(self) -> None:
        for hook in self._commit_hooks:
            try:
                hook()
            except Exception:
                logger.exception("cluster swap commit hook failed")

    async def swap(self, checkpoint: str) -> dict:
        if self._swapping:
            raise SwapError("a cluster swap is already in progress")
        live = [w for w in self.dispatcher.workers.values() if w.alive]
        if not live:
            raise SwapError("no live cluster workers to swap")
        self._swapping = True
        committed: list[str] = []
        try:
            for w in sorted(live, key=lambda w: w.url):
                try:
                    await self.dispatcher.set_drain(w, True)
                    await self.dispatcher.wait_drained(w, self.drain_timeout_s)
                    rep = await self.dispatcher.swap_on(w, checkpoint)
                except SwapError:
                    raise
                except Exception as e:
                    raise SwapError(
                        f"cluster swap aborted at worker {w.url} "
                        f"({type(e).__name__}: {e}); committed: "
                        f"{committed or 'none'}") from e
                finally:
                    try:
                        await self.dispatcher.set_drain(w, False)
                    except Exception:
                        logger.exception("undrain of %s failed", w.url)
                if not rep.get("ok"):
                    raise SwapError(
                        f"worker {w.url} rejected the swap: "
                        f"{rep.get('error') or rep.get('results')}; that "
                        f"worker rolled itself back; committed workers "
                        f"({committed or 'none'}) keep the new version — "
                        "re-POST the previous checkpoint to converge back")
                committed.append(w.url)
            self._last = {"checkpoint": checkpoint, "committed": committed}
            return {"cluster": True, "committed": committed,
                    "workers": len(committed)}
        finally:
            self._swapping = False
            if committed:
                # even a partial roll changed what some answers were
                # computed with — flush the ingest-side response cache
                self._run_commit_hooks()

    def report(self) -> dict:
        return {"cluster": True, "swapping": self._swapping,
                "last": self._last or None}


# ---------------------------------------------------------------------------
# the remote_tpu processor (ingest dispatch stage)
# ---------------------------------------------------------------------------


class _ClusterRunnerView:
    """Adapter giving the engine's runner-health walk (`proc.runner
    .health_report()`) the per-worker fleet view."""

    def __init__(self, dispatcher: ClusterDispatcher):
        self._dispatcher = dispatcher

    def health_report(self) -> list[dict]:
        return self._dispatcher.health_reports()


class RemoteTpuProcessor:
    """Ingest-tier dispatch stage: ships each emission to the device tier
    over the flight plane, with hash-affine routing and failover.

    Composes with everything the ingest stream already does — admission /
    AIMD / fairness run before it, coalescing buffers feed it, and an
    optional ingest-side response cache short-circuits duplicates before
    they pay the network + device (config ``response_cache``, same
    semantics as ``tpu_inference``'s)."""

    def __init__(self, dispatcher: ClusterDispatcher, *, response_cache=None,
                 drain_timeout_s: float = 30.0, fleet=None):
        self.dispatcher = dispatcher
        self.cache = response_cache
        self.swapper = ClusterSwapper(dispatcher, drain_timeout_s)
        if self.cache is not None:
            self.swapper.add_commit_hook(self.cache.bump_epoch)
            # integrity satellite: a worker fenced for corruption may have
            # poisoned cached answers — epoch-flush so a byte-identical
            # duplicate recomputes on a healthy worker
            dispatcher.integrity_hooks.append(self.cache.bump_epoch)
        #: elastic-fleet controller (runtime/fleet.py); None = static fleet
        self.fleet = fleet
        #: engine /health + /readiness integration (runner-shaped view)
        self.runner = _ClusterRunnerView(dispatcher)

    def attach_overload_controller(self, controller) -> None:
        """Stream hook: align the cache's tenant-hit label capping with the
        admission controller (same contract as tpu_inference)."""
        if self.cache is not None:
            self.cache.set_tenant_policy(controller.cfg.tenants)

    def cluster_report(self) -> dict:
        """Fleet snapshot for the engine's /health payload (including the
        controller's per-event decision log when elastic)."""
        rep = self.dispatcher.report()
        if self.fleet is not None:
            rep["fleet"] = self.fleet.report()
        return rep

    async def connect(self) -> None:
        await self.dispatcher.start()
        if self.fleet is not None:
            await self.fleet.start()

    async def close(self) -> None:
        if self.fleet is not None:
            await self.fleet.close()
        await self.dispatcher.close()

    async def process(self, batch: MessageBatch) -> list[MessageBatch]:
        if batch.num_rows == 0:
            return []
        if self.cache is not None:
            key = batch_fingerprint(batch)
            rbs = await self.cache.get_or_compute(
                key, lambda: self._dispatch_ipc(batch), tenant=batch.tenant())
            # cached value holds Arrow record batches (bitwise-identical
            # responses); the wrapper is rebuilt per delivery
            return [MessageBatch(rb) for rb in rbs]
        return await self.dispatcher.dispatch(batch)

    async def _dispatch_ipc(self, batch: MessageBatch):
        return [b.record_batch for b in await self.dispatcher.dispatch(batch)]


def parse_remote_tpu_config(config: Mapping) -> dict:
    """Validate ``remote_tpu`` processor config -> dispatcher kwargs + the
    drain timeout. Pure parse (no sockets, no metric series) so config.py
    can run it at ``--validate`` time."""
    from arkflow_tpu.runtime.respcache import parse_response_cache_config
    from arkflow_tpu.utils.duration import parse_duration

    workers = config.get("workers")
    if not isinstance(workers, list) or not workers:
        raise ConfigError("remote_tpu needs a non-empty 'workers' list of "
                          "arkflow://host:port URLs")
    for u in workers:
        if not isinstance(u, str):
            raise ConfigError(f"remote_tpu.workers entries must be strings, "
                              f"got {u!r}")
        parse_remote_url(u)  # raises ConfigError with the offending URL
    if len(set(workers)) != len(workers):
        raise ConfigError(f"remote_tpu.workers must be distinct, got {workers}")
    route_key = config.get("route_key", "fingerprint")
    if route_key not in ROUTE_KEYS:
        raise ConfigError(f"remote_tpu.route_key must be one of "
                          f"{ROUTE_KEYS}, got {route_key!r}")
    out: dict = {"workers": [str(u) for u in workers],
                 "route_key": str(route_key)}

    def _int(key: str, default: int, minimum: int) -> int:
        v = config.get(key, default)
        if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
            raise ConfigError(
                f"remote_tpu.{key} must be an int >= {minimum}, got {v!r}")
        return v

    def _dur(key: str, default: str) -> float:
        v = config.get(key, default)
        try:
            s = parse_duration(v)
        except (ConfigError, TypeError, ValueError) as e:
            raise ConfigError(f"remote_tpu.{key} invalid: {e}") from e
        if s <= 0:
            raise ConfigError(f"remote_tpu.{key} must be > 0, got {v!r}")
        return s

    out["prefix_bytes"] = _int("prefix_bytes", 64, 1)
    out["virtual_nodes"] = _int("virtual_nodes", 64, 1)
    out["max_frame"] = _int("max_frame", DEFAULT_MAX_FRAME, 1024)
    out["decode_candidates"] = _int("decode_candidates", 3, 1)
    out["heartbeat_s"] = _dur("heartbeat", "2s")
    out["request_timeout_s"] = _dur("request_timeout", "60s")
    out["connect_timeout_s"] = _dur("connect_timeout", "5s")
    out["drain_timeout_s"] = _dur("drain_timeout", "30s")
    # staleness bound: default 5 heartbeat periods (floor 10s); must exceed
    # one period or every member would flap dead between beats
    if config.get("heartbeat_timeout") is not None:
        ht = _dur("heartbeat_timeout", "10s")
    else:
        ht = max(5.0 * out["heartbeat_s"], 10.0)
    if ht <= out["heartbeat_s"]:
        raise ConfigError(
            f"remote_tpu.heartbeat_timeout ({ht}s) must exceed the "
            f"heartbeat period ({out['heartbeat_s']}s)")
    out["heartbeat_timeout_s"] = ht
    tf = config.get("text_field")
    if tf is not None and not isinstance(tf, str):
        raise ConfigError(f"remote_tpu.text_field must be a string, got {tf!r}")
    out["text_field"] = tf
    crc = config.get("crc", True)
    if not isinstance(crc, bool):
        raise ConfigError(f"remote_tpu.crc must be a bool, got {crc!r}")
    out["crc"] = crc
    out["io_deadline_floor_s"] = _dur("io_deadline_floor", "100ms")

    hedge = config.get("hedge")
    if hedge is not None:
        if not isinstance(hedge, Mapping):
            raise ConfigError(
                f"remote_tpu.hedge must be a mapping, got {hedge!r}")
        unknown = set(hedge) - {"delay", "max_fraction", "burst", "min_delay"}
        if unknown:
            raise ConfigError(
                f"remote_tpu.hedge: unknown keys {sorted(unknown)} "
                "(allowed: delay, max_fraction, burst, min_delay)")
        h: dict = {}
        delay = hedge.get("delay", "auto")
        if delay == "auto":
            h["delay_s"] = None  # p99-EWMA of recent dispatch latency
        else:
            try:
                d = parse_duration(delay)
            except (ConfigError, TypeError, ValueError) as e:
                raise ConfigError(
                    f"remote_tpu.hedge.delay must be 'auto' or a "
                    f"duration: {e}") from e
            if d <= 0:
                raise ConfigError(
                    f"remote_tpu.hedge.delay must be > 0, got {delay!r}")
            h["delay_s"] = d
        frac = hedge.get("max_fraction", 0.1)
        if isinstance(frac, bool) or not isinstance(frac, (int, float)) \
                or not 0.0 < frac <= 1.0:
            raise ConfigError(
                f"remote_tpu.hedge.max_fraction must be in (0, 1], "
                f"got {frac!r}")
        h["max_fraction"] = float(frac)
        burst = hedge.get("burst", 4)
        if isinstance(burst, bool) or not isinstance(burst, int) or burst < 0:
            raise ConfigError(
                f"remote_tpu.hedge.burst must be an int >= 0, got {burst!r}")
        h["burst"] = burst
        md = hedge.get("min_delay", "10ms")
        try:
            mds = parse_duration(md)
        except (ConfigError, TypeError, ValueError) as e:
            raise ConfigError(f"remote_tpu.hedge.min_delay invalid: {e}") from e
        if mds <= 0:
            raise ConfigError(
                f"remote_tpu.hedge.min_delay must be > 0, got {md!r}")
        h["min_delay_s"] = mds
        out["hedge"] = h
    else:
        out["hedge"] = None

    rb = config.get("retry_budget")
    if rb is not None:
        if not isinstance(rb, Mapping):
            raise ConfigError(
                f"remote_tpu.retry_budget must be a mapping, got {rb!r}")
        unknown = set(rb) - {"ratio", "burst"}
        if unknown:
            raise ConfigError(
                f"remote_tpu.retry_budget: unknown keys {sorted(unknown)} "
                "(allowed: ratio, burst)")
        ratio = rb.get("ratio", 0.5)
        if isinstance(ratio, bool) or not isinstance(ratio, (int, float)) \
                or ratio <= 0:
            raise ConfigError(
                f"remote_tpu.retry_budget.ratio must be > 0, got {ratio!r}")
        burst = rb.get("burst", 8)
        if isinstance(burst, bool) or not isinstance(burst, int) or burst < 1:
            raise ConfigError(
                f"remote_tpu.retry_budget.burst must be an int >= 1, "
                f"got {burst!r}")
        out["retry_budget"] = {"ratio": float(ratio), "burst": burst}
    else:
        out["retry_budget"] = None

    sv = config.get("shadow_verify")
    if sv is not None:
        if not isinstance(sv, Mapping):
            raise ConfigError(
                f"remote_tpu.shadow_verify must be a mapping, got {sv!r}")
        unknown = set(sv) - {"fraction"}
        if unknown:
            raise ConfigError(
                f"remote_tpu.shadow_verify: unknown keys {sorted(unknown)} "
                "(allowed: fraction)")
        frac = sv.get("fraction", 0.05)
        if isinstance(frac, bool) or not isinstance(frac, (int, float)) \
                or not 0.0 < frac <= 1.0:
            raise ConfigError(
                f"remote_tpu.shadow_verify.fraction must be in (0, 1], "
                f"got {frac!r}")
        out["shadow_verify"] = {"fraction": float(frac)}
    else:
        out["shadow_verify"] = None
    parse_response_cache_config(config.get("response_cache"))
    # elastic-fleet block (runtime/fleet.py owns the parse rules); pure —
    # config.py reaches this through fault.inner chains at --validate time
    from arkflow_tpu.runtime.fleet import parse_fleet_config

    out["fleet"] = parse_fleet_config(
        config.get("fleet"), static_workers=len(out["workers"]))
    return out


def build_remote_tpu(config: dict, resource: Resource) -> RemoteTpuProcessor:
    """Builder for ``type: remote_tpu`` (registered from
    plugins/processor/remote_tpu.py)."""
    from arkflow_tpu.runtime.respcache import build_response_cache

    parsed = parse_remote_tpu_config(config)
    name = str(config.get("name") or "cluster")
    dispatcher = ClusterDispatcher(
        parsed["workers"], name=name, route_key=parsed["route_key"],
        prefix_bytes=parsed["prefix_bytes"], text_field=parsed["text_field"],
        virtual_nodes=parsed["virtual_nodes"],
        heartbeat_s=parsed["heartbeat_s"],
        request_timeout_s=parsed["request_timeout_s"],
        connect_timeout_s=parsed["connect_timeout_s"],
        heartbeat_timeout_s=parsed["heartbeat_timeout_s"],
        max_frame=parsed["max_frame"],
        decode_candidates=parsed["decode_candidates"],
        crc=parsed["crc"],
        io_deadline_floor_s=parsed["io_deadline_floor_s"],
        hedge=parsed["hedge"],
        retry_budget=parsed["retry_budget"],
        shadow_verify=parsed["shadow_verify"])
    cache = build_response_cache(config.get("response_cache"), name=name)
    fleet = None
    fleet_cfg = parsed["fleet"]
    if fleet_cfg is not None:
        from arkflow_tpu.runtime.fleet import (
            FleetController,
            SubprocessSpawner,
        )

        spawner = None
        if fleet_cfg.template is not None:
            spawner = SubprocessSpawner(fleet_cfg.template,
                                        host=fleet_cfg.spawn_host)
        fleet = FleetController(dispatcher, spawner, fleet_cfg, name=name)
    return RemoteTpuProcessor(dispatcher, response_cache=cache,
                              drain_timeout_s=parsed["drain_timeout_s"],
                              fleet=fleet)
