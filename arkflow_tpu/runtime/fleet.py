"""Elastic fleet: the autoscaling controller for the disaggregated device tier.

PR 11 made every cluster worker advertise autoscaling signals over its
heartbeat (the AIMD admission ``window``, the queue-drain estimate
``drain_s``, in-flight depth — see ``runtime/cluster.py``); this module is
the consumer. A :class:`FleetController` runs inside the ingest tier next to
the ``remote_tpu`` dispatcher and closes the loop:

- **scale-out** — when window exhaustion or queue-wait growth is sustained
  past the configured policy, spawn a new cluster-worker process from the
  worker template. The newcomer's processor configs are overlaid with the
  fleet's *incumbent shape grid* (the live workers' tuner-committed
  batch/seq buckets, carried on their heartbeats) so its ``warmup`` compiles
  exactly the shapes traffic settled on — the port opens warm.
- **scale-in** — when headroom is sustained and the fleet is above
  ``min_workers``, pick the least-loaded worker, drive the existing
  ``drain`` frame (in-flight batches finish; new ones re-route along the
  hash ring), retire the process after the drain completes.
- **preemption is routine** — a worker that vanishes (spot preemption,
  SIGKILL, network wedge) is detected by the dispatcher's heartbeat
  staleness check; the controller respawns a replacement to hold
  ``min_workers``. The hash ring needs no explicit handoff: dead workers are
  filtered at plan time, so the dead member's key range lands on its ring
  successor deterministically, and in-flight batches nack through the
  stream's normal redelivery path (at-least-once, zero silent loss).

Every decision is appended to a bounded event log (exported on ``/health``
through the processor's ``cluster_report``) with a human-readable reason,
and counted on ``arkflow_fleet_size`` / ``arkflow_fleet_scale_out_total`` /
``arkflow_fleet_scale_in_total`` / ``arkflow_fleet_preempt_total``.

The controller talks to processes through a small ``Spawner`` interface so
tests can run an in-process fleet; :class:`SubprocessSpawner` is the real
one (``python -m arkflow_tpu --cluster-worker`` from a template config).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import socket
import sys
import time
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Optional, Sequence

from arkflow_tpu.errors import ConfigError

logger = logging.getLogger("arkflow.fleet")

#: controller-spawned workers get ids in this namespace so an operator can
#: tell a template spawn from the statically configured fleet at a glance
SPAWN_ID_PREFIX = "fleet"


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FleetConfig:
    """Parsed ``fleet:`` block of a ``remote_tpu`` processor."""

    enabled: bool = True
    #: floor the controller defends: preempted workers are respawned and
    #: scale-in never drops below it
    min_workers: int = 1
    #: ceiling for scale-out
    max_workers: int = 4
    #: control-loop period
    interval_s: float = 2.0
    #: how long window exhaustion / queue-wait growth must persist before a
    #: scale-out fires (absorbs single-batch blips)
    scale_out_sustain_s: float = 10.0
    #: advertised drain estimate (seconds of queued work) that counts as
    #: queue-wait growth even when windows still show nominal headroom
    drain_high_s: float = 3.0
    #: how long fleet-wide idleness must persist before a scale-in fires
    scale_in_sustain_s: float = 30.0
    #: fleet counts as idle when aggregate in-flight <= idle_frac * aggregate
    #: advertised window
    idle_frac: float = 0.25
    #: minimum gap between any two controller actions (lets the signals
    #: resettle after a membership change before the next decision)
    cooldown_s: float = 15.0
    #: respawn departed members to hold min_workers (spot preemption policy)
    respawn: bool = True
    #: worker template: a worker-mode config mapping (``processors:`` et al,
    #: exactly what ``--cluster-worker --config`` accepts) or a path to one
    template: Any = None
    #: bind host for spawned workers
    spawn_host: str = "127.0.0.1"
    #: budget for a spawned worker to warm up and answer register
    spawn_timeout_s: float = 240.0
    #: drain budget when retiring a worker on scale-in
    drain_s: float = 30.0
    #: per-role floors/ceilings for a disaggregated (prefill/decode) fleet:
    #: ``{role: (min, max)}``. When set, respawn floors, pressure-driven
    #: scale-out and idle scale-in are decided PER ROLE (spawned workers
    #: get ``worker.role`` overlaid on the template); None = role-blind.
    roles: Any = None

    def report(self) -> dict:
        rep = {
            "enabled": self.enabled,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "interval_s": self.interval_s,
            "scale_out_sustain_s": self.scale_out_sustain_s,
            "scale_in_sustain_s": self.scale_in_sustain_s,
            "drain_high_s": self.drain_high_s,
            "idle_frac": self.idle_frac,
            "cooldown_s": self.cooldown_s,
            "respawn": self.respawn,
        }
        if self.roles:
            rep["roles"] = {r: {"min": lo, "max": hi}
                            for r, (lo, hi) in sorted(self.roles.items())}
        return rep


def parse_fleet_config(cfg: Any, *, static_workers: int = 1,
                       who: str = "remote_tpu") -> Optional[FleetConfig]:
    """Pure parse of a ``fleet:`` block (no sockets, no subprocesses, no
    metric series) so ``config.py`` can run it at ``--validate`` time
    through fault ``inner`` chains like every other block. ``None`` /
    ``enabled: false`` = no controller."""
    from arkflow_tpu.utils.duration import parse_duration

    if cfg is None:
        return None
    if cfg is False:
        return None
    if cfg is True:
        cfg = {}
    if not isinstance(cfg, Mapping):
        raise ConfigError(
            f"{who}.fleet must be a mapping or boolean, got {cfg!r}")
    known = {"enabled", "min_workers", "max_workers", "interval",
             "scale_out_sustain", "scale_in_sustain", "drain_high",
             "idle_frac", "cooldown", "respawn", "template", "spawn_host",
             "spawn_timeout", "drain_timeout", "roles"}
    unknown = set(cfg) - known
    if unknown:
        raise ConfigError(
            f"{who}.fleet: unknown keys {sorted(unknown)} "
            f"(known: {sorted(known)})")
    enabled = cfg.get("enabled", True)
    if not isinstance(enabled, bool):
        raise ConfigError(
            f"{who}.fleet.enabled must be a boolean, got {enabled!r}")
    if not enabled:
        return None

    def _int(key: str, default: int, minimum: int) -> int:
        v = cfg.get(key, default)
        if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
            raise ConfigError(
                f"{who}.fleet.{key} must be an int >= {minimum}, got {v!r}")
        return v

    def _dur(key: str, default: str) -> float:
        v = cfg.get(key, default)
        try:
            s = parse_duration(v)
        except (ConfigError, TypeError, ValueError) as e:
            raise ConfigError(f"{who}.fleet.{key} invalid: {e}") from e
        if s <= 0:
            raise ConfigError(f"{who}.fleet.{key} must be > 0, got {v!r}")
        return s

    min_workers = _int("min_workers", static_workers, 1)
    max_workers = _int("max_workers", max(min_workers, static_workers) + 2, 1)
    if max_workers < min_workers:
        raise ConfigError(
            f"{who}.fleet.max_workers ({max_workers}) must be >= "
            f"min_workers ({min_workers})")
    idle_frac = cfg.get("idle_frac", 0.25)
    if isinstance(idle_frac, bool) or not isinstance(idle_frac, (int, float)) \
            or not 0.0 < float(idle_frac) <= 1.0:
        raise ConfigError(
            f"{who}.fleet.idle_frac must be a number in (0, 1], "
            f"got {idle_frac!r}")
    respawn = cfg.get("respawn", True)
    if not isinstance(respawn, bool):
        raise ConfigError(
            f"{who}.fleet.respawn must be a boolean, got {respawn!r}")
    template = cfg.get("template")
    if template is not None and not isinstance(template, (str, Mapping)):
        raise ConfigError(
            f"{who}.fleet.template must be a worker-config mapping or a "
            f"path string, got {type(template).__name__}")
    if isinstance(template, Mapping):
        # validate the embedded worker config NOW — a malformed template
        # otherwise only fails at the first scale-out, mid-incident
        from arkflow_tpu.runtime.cluster import parse_worker_config

        parse_worker_config(template)
    spawn_host = cfg.get("spawn_host", "127.0.0.1")
    if not isinstance(spawn_host, str) or not spawn_host:
        raise ConfigError(
            f"{who}.fleet.spawn_host must be a non-empty string, "
            f"got {spawn_host!r}")
    roles_raw = cfg.get("roles")
    roles = None
    if roles_raw is not None:
        from arkflow_tpu.runtime.cluster import WORKER_ROLES

        if not isinstance(roles_raw, Mapping) or not roles_raw:
            raise ConfigError(
                f"{who}.fleet.roles must be a non-empty mapping of "
                f"role -> {{min, max}}, got {roles_raw!r}")
        roles = {}
        for rname, spec in roles_raw.items():
            if rname not in WORKER_ROLES:
                raise ConfigError(
                    f"{who}.fleet.roles: unknown role {rname!r} "
                    f"(known: {list(WORKER_ROLES)})")
            if not isinstance(spec, Mapping):
                raise ConfigError(
                    f"{who}.fleet.roles.{rname} must be a mapping with "
                    f"min/max, got {spec!r}")
            bad = set(spec) - {"min", "max"}
            if bad:
                raise ConfigError(
                    f"{who}.fleet.roles.{rname}: unknown keys "
                    f"{sorted(bad)} (known: ['max', 'min'])")
            lo = spec.get("min", 0)
            if isinstance(lo, bool) or not isinstance(lo, int) or lo < 0:
                raise ConfigError(
                    f"{who}.fleet.roles.{rname}.min must be an int >= 0, "
                    f"got {lo!r}")
            hi = spec.get("max", max(lo, 1))
            if isinstance(hi, bool) or not isinstance(hi, int) or hi < lo:
                raise ConfigError(
                    f"{who}.fleet.roles.{rname}.max must be an int >= "
                    f"min ({lo}), got {hi!r}")
            roles[str(rname)] = (lo, hi)
        # A role split must be able to serve both sides: a fleet whose
        # ceilings only ever admit prefill-capable workers (or only
        # decode-capable ones) can never finish a request — catch it at
        # --validate instead of as an eternal ConnectError at runtime.
        def _cap(role: str) -> int:
            return sum(hi for r, (_lo, hi) in roles.items()
                       if r == role or r == "both")
        if _cap("prefill") == 0 or _cap("decode") == 0:
            missing = "prefill" if _cap("prefill") == 0 else "decode"
            raise ConfigError(
                f"{who}.fleet.roles is one-sided: no capacity for "
                f"{missing!r} (every request needs both a prefill- and a "
                f"decode-capable worker; add a {missing!r} or 'both' "
                f"entry with max >= 1)")
    return FleetConfig(
        enabled=True,
        min_workers=min_workers,
        max_workers=max_workers,
        interval_s=_dur("interval", "2s"),
        scale_out_sustain_s=_dur("scale_out_sustain", "10s"),
        scale_in_sustain_s=_dur("scale_in_sustain", "30s"),
        drain_high_s=_dur("drain_high", "3s"),
        idle_frac=float(idle_frac),
        cooldown_s=_dur("cooldown", "15s"),
        respawn=respawn,
        template=template,
        spawn_host=spawn_host,
        spawn_timeout_s=_dur("spawn_timeout", "240s"),
        drain_s=_dur("drain_timeout", "30s"),
        roles=roles,
    )


# ---------------------------------------------------------------------------
# spawners
# ---------------------------------------------------------------------------


def free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def overlay_shapes(worker_cfg: Mapping, shapes: Sequence[Optional[dict]]) -> dict:
    """Warm replay: graft the fleet's incumbent shape grid onto a worker
    template so the newcomer's ``warmup`` compiles the buckets traffic
    settled on, not the template's cold defaults.

    ``shapes`` is positional — entry *i* overlays processor *i* of the
    template (``None`` = leave alone), matching the order workers report
    them on heartbeats. The overlay follows the template's ``fault.inner``
    chains so a chaos-wrapped model stage still gets its grid."""
    import copy

    out = copy.deepcopy(dict(worker_cfg))
    procs = out.get("processors")
    if procs is None and isinstance(out.get("pipeline"), Mapping):
        procs = out["pipeline"].get("processors")
    if not isinstance(procs, list):
        return out
    for i, shape in enumerate(shapes):
        if not shape or i >= len(procs):
            continue
        node = procs[i]
        # descend wrapper chains to the component that owns bucket keys
        while isinstance(node, dict) and isinstance(node.get("inner"), dict):
            node = node["inner"]
        if not isinstance(node, dict):
            continue
        for key in ("batch_buckets", "seq_buckets", "example_scale"):
            if shape.get(key) is not None:
                node[key] = shape[key]
    return out


class SubprocessSpawner:
    """The real spawner: launches ``python -m arkflow_tpu --cluster-worker``
    from the template config and reaps the processes it started.

    Owns only its own children — statically configured workers (or anything
    else on the ring) are never touched by ``retire``."""

    def __init__(self, template: Any, *, host: str = "127.0.0.1",
                 env: Optional[Mapping[str, str]] = None,
                 log_dir: Optional[str] = None):
        if template is None:
            raise ConfigError(
                "fleet: scale-out needs a 'template' (worker-config mapping "
                "or path) to spawn workers from")
        self.template = template
        self.host = host
        self.env = dict(env) if env is not None else None
        self.log_dir = log_dir
        self._procs: dict[str, Any] = {}  # url -> Popen
        self._seq = 0
        self._tmpdir: Optional[str] = None

    def _template_mapping(self) -> dict:
        if isinstance(self.template, Mapping):
            return dict(self.template)
        import yaml

        try:
            with open(self.template) as f:
                raw = yaml.safe_load(f) or {}
        except OSError as e:
            raise ConfigError(
                f"fleet.template {self.template!r} unreadable: {e}") from e
        if not isinstance(raw, Mapping):
            raise ConfigError(
                f"fleet.template {self.template!r} must parse to a mapping")
        return dict(raw)

    def _write_config(self, cfg: dict) -> str:
        import tempfile

        import yaml

        if self._tmpdir is None:
            self._tmpdir = tempfile.mkdtemp(prefix="arkflow-fleet-")
        self._seq += 1
        path = os.path.join(self._tmpdir, f"worker-{self._seq}.yaml")
        with open(path, "w") as f:
            yaml.safe_dump(cfg, f)
        return path

    async def spawn(self, shapes: Sequence[Optional[dict]] = (),
                    role: Optional[str] = None) -> str:
        """Launch one worker; returns its ``arkflow://`` URL immediately —
        readiness (warmup compiles before the port opens) is the
        controller's adopt-probe's problem, with its own budget.

        ``role`` overlays ``worker.role`` on the template, so one template
        serves every role of a disaggregated fleet."""
        import subprocess

        cfg = overlay_shapes(self._template_mapping(), shapes)
        if role is not None:
            w = dict(cfg.get("worker") or {})
            w["role"] = role
            cfg["worker"] = w
        port = free_port(self.host)
        url = f"arkflow://{self.host}:{port}"
        cfg_path = self._write_config(cfg)
        worker_id = f"{SPAWN_ID_PREFIX}-{os.getpid()}-{self._seq}"
        cmd = [sys.executable, "-m", "arkflow_tpu", "--cluster-worker",
               "--config", cfg_path, "--host", self.host,
               "--port", str(port), "--worker-id", worker_id]
        stdout: Any = subprocess.DEVNULL
        if self.log_dir:
            stdout = open(os.path.join(
                self.log_dir, f"{worker_id}.log"), "ab")
        self._procs[url] = subprocess.Popen(
            cmd, env=self.env, stdout=stdout, stderr=subprocess.STDOUT)
        logger.info("fleet: spawned worker %s (pid %d, id %s)", url,
                    self._procs[url].pid, worker_id)
        return url

    async def retire(self, url: str, *, grace_s: float = 30.0) -> None:
        """SIGTERM (the worker self-drains — runtime/cluster.py) and, past
        the grace budget, SIGKILL. Unknown urls are ignored: the controller
        never retires workers it didn't spawn, but a double-retire after a
        preemption race must not raise."""
        proc = self._procs.pop(url, None)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        deadline = time.monotonic() + grace_s
        while proc.poll() is None and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if proc.poll() is None:
            logger.warning("fleet: worker %s ignored SIGTERM for %.1fs; "
                           "killing", url, grace_s)
            proc.kill()

    def owns(self, url: str) -> bool:
        return url in self._procs

    def reap(self, url: str) -> None:
        """Forget a departed child (its process already exited)."""
        proc = self._procs.pop(url, None)
        if proc is not None and proc.poll() is None:
            proc.kill()

    async def close(self) -> None:
        for url in list(self._procs):
            await self.retire(url, grace_s=5.0)


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------


@dataclass
class _Sustain:
    """Edge-triggered sustain tracker: ``since`` is the monotonic time the
    condition became continuously true, or None while false."""

    since: Optional[float] = None

    def observe(self, cond: bool, now: float) -> float:
        """Returns how long the condition has been continuously true."""
        if not cond:
            self.since = None
            return 0.0
        if self.since is None:
            self.since = now
        return now - self.since


class FleetController:
    """The control loop. One instance per ``remote_tpu`` processor, started
    after the dispatcher (it needs live heartbeat state to read).

    All decisions run in one task — there is never more than one membership
    change in flight, so the signals each action perturbs are re-sampled
    before the next one (enforced belt-and-braces by ``cooldown_s``)."""

    def __init__(self, dispatcher, spawner, cfg: FleetConfig, *,
                 name: str = "cluster",
                 clock: Optional[Callable[[], float]] = None):
        from arkflow_tpu.obs import global_registry

        self.dispatcher = dispatcher
        self.spawner = spawner
        self.cfg = cfg
        self.name = name
        self.clock = clock or time.monotonic
        self._task: Optional[asyncio.Task] = None
        self._pressure = _Sustain()
        self._idle = _Sustain()
        #: per-role sustain trackers (disaggregated fleets)
        self._role_pressure: dict[str, _Sustain] = {}
        self._role_idle: dict[str, _Sustain] = {}
        self._last_action_t: Optional[float] = None
        self._events: collections.deque = collections.deque(maxlen=64)
        self._known_dead: set[str] = set()
        reg = global_registry()
        labels = {"stream": name}
        self.m_size = reg.gauge(
            "arkflow_fleet_size", "live cluster workers under fleet control",
            labels)
        self.m_scale_out = reg.counter(
            "arkflow_fleet_scale_out_total",
            "workers spawned for sustained load", labels)
        self.m_scale_in = reg.counter(
            "arkflow_fleet_scale_in_total",
            "workers drained and retired for sustained headroom", labels)
        self.m_preempt = reg.counter(
            "arkflow_fleet_preempt_total",
            "worker departures detected (missed heartbeats / process exit)",
            labels)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._task is not None:
            return
        self._refresh_size()
        self._task = asyncio.create_task(
            self._loop(), name=f"{self.name}-fleet-controller")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        close = getattr(self.spawner, "close", None)
        if close is not None:
            try:
                await close()
            except Exception:
                logger.exception("fleet[%s]: spawner close failed", self.name)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_s)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # a sick control loop must never take serving down with it
                logger.exception("fleet[%s]: tick failed", self.name)

    # -- the decision tick -------------------------------------------------

    def _live(self) -> list:
        return [w for w in self.dispatcher.workers.values()
                if w.alive and not w.draining]

    def _refresh_size(self) -> int:
        n = len(self._live())
        self.m_size.set(float(n))
        return n

    def _event(self, action: str, reason: str, **extra: Any) -> dict:
        ev = {"t": round(time.time(), 3), "action": action, "reason": reason,
              **extra}
        self._events.append(ev)
        logger.info("fleet[%s]: %s — %s %s", self.name, action, reason,
                    {k: v for k, v in extra.items()} or "")
        # decisions are trace-visible: a forced root span per action means
        # the decision survives head sampling and lands in /trace with its
        # reason attached, next to the serving spans it will reshape
        try:
            from arkflow_tpu.obs.trace import global_tracer

            tracer = global_tracer()
            if tracer.enabled:
                ctx = tracer.begin()
                tracer.record(ctx, f"fleet_{action}", 0.0,
                              attrs={"reason": reason, **{
                                  k: v for k, v in extra.items()
                                  if isinstance(v, (str, int, float, bool))}})
                # "fleet" is a forced status: a membership decision is rare
                # and always worth a trace slot, like a shed or an error
                tracer.finish(ctx, status="fleet")
        except Exception:
            pass  # tracing is best-effort by design
        return ev

    def incumbent_shapes(self) -> list:
        """Freshest live worker's advertised shape grid (heartbeat
        ``shapes``), positional per template processor. Empty when no live
        worker has reported one — the template then warms its own grid."""
        best: list = []
        best_seen = -1.0
        for w in self.dispatcher.workers.values():
            if not w.alive:
                continue
            shapes = w.last_report.get("shapes")
            if shapes and w.last_seen > best_seen:
                best, best_seen = shapes, w.last_seen
        return best

    async def tick(self) -> Optional[dict]:
        """One control decision; returns the event fired (None = no-op).
        Public so tests and the chaos soak can drive the loop headlessly."""
        now = self.clock()
        await self._note_departures()
        n_live = self._refresh_size()
        live = self._live()

        if self.cfg.roles:
            return await self._tick_roles(now, n_live, live)

        # preemption floor first: holding min_workers outranks policy timers
        if self.cfg.respawn and n_live < self.cfg.min_workers:
            return await self._scale_out(
                f"fleet below min_workers ({n_live} < "
                f"{self.cfg.min_workers}) after departure", kind="respawn")

        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < self.cfg.cooldown_s)

        # scale-out: window exhaustion (no live worker has headroom against
        # its advertised AIMD window) or queue-wait growth (advertised drain
        # estimate high fleet-wide), sustained past the policy
        exhausted = bool(live) and all(not w.has_headroom() for w in live)
        min_drain = min((w.drain_s for w in live), default=0.0)
        queue_growth = bool(live) and min_drain > self.cfg.drain_high_s
        pressured_for = self._pressure.observe(
            exhausted or queue_growth, now)
        if (pressured_for >= self.cfg.scale_out_sustain_s
                and not in_cooldown):
            if n_live >= self.cfg.max_workers:
                self._event(
                    "scale_out_capped",
                    f"pressure sustained {pressured_for:.1f}s but fleet at "
                    f"max_workers ({self.cfg.max_workers})")
                self._pressure.since = now  # re-arm, don't spam the log
                return None
            why = ("window exhaustion" if exhausted else
                   f"queue-wait growth (min drain_s "
                   f"{min_drain:.2f} > {self.cfg.drain_high_s})")
            return await self._scale_out(
                f"{why} sustained {pressured_for:.1f}s "
                f">= {self.cfg.scale_out_sustain_s:.1f}s")

        # scale-in: sustained fleet-wide idleness above the floor
        total_window = sum(w.window for w in live)
        total_inflight = sum(w.inflight for w in live)
        idle = (bool(live)
                and total_inflight <= self.cfg.idle_frac * total_window
                and all(w.drain_s <= self.cfg.drain_high_s for w in live))
        idle_for = self._idle.observe(idle, now)
        if (idle_for >= self.cfg.scale_in_sustain_s
                and n_live > self.cfg.min_workers and not in_cooldown):
            return await self._scale_in(
                f"headroom sustained {idle_for:.1f}s >= "
                f"{self.cfg.scale_in_sustain_s:.1f}s (inflight "
                f"{total_inflight} <= {self.cfg.idle_frac} * window "
                f"{total_window})")
        return None

    async def _tick_roles(self, now: float, n_live: int,
                          live: list) -> Optional[dict]:
        """Role-aware decision pass for a disaggregated fleet: floors,
        pressure and idleness are judged per role (a starved prefill tier
        must not be masked by idle decode slots, and vice versa). Spawned
        workers get the role overlaid on the template; the global
        ``max_workers`` ceiling still binds across roles."""
        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < self.cfg.cooldown_s)

        def _own(role: str) -> list:
            return [w for w in live if getattr(w, "role", "both") == role]

        # respawn floors first, in deterministic role order
        for role, (lo, _hi) in sorted(self.cfg.roles.items()):
            n_role = len(_own(role))
            if self.cfg.respawn and n_role < lo:
                return await self._scale_out(
                    f"role '{role}' below floor ({n_role} < {lo}) after "
                    f"departure", kind="respawn", role=role)

        # pressure scale-out: judged over the workers that can SERVE the
        # role ('both' members count for either side)
        for role, (_lo, hi) in sorted(self.cfg.roles.items()):
            capable = [w for w in live
                       if getattr(w, "role", "both") in (role, "both")]
            exhausted = bool(capable) and all(
                not w.has_headroom() for w in capable)
            min_drain = min((w.drain_s for w in capable), default=0.0)
            queue_growth = bool(capable) and min_drain > self.cfg.drain_high_s
            tr = self._role_pressure.setdefault(role, _Sustain())
            p_for = tr.observe(exhausted or queue_growth, now)
            if p_for < self.cfg.scale_out_sustain_s or in_cooldown:
                continue
            if len(_own(role)) >= hi or n_live >= self.cfg.max_workers:
                self._event(
                    "scale_out_capped",
                    f"role '{role}' pressure sustained {p_for:.1f}s but at "
                    f"role max ({hi}) or fleet max ({self.cfg.max_workers})")
                tr.since = now  # re-arm, don't spam the log
                return None
            why = ("window exhaustion" if exhausted else
                   f"queue-wait growth (min drain_s {min_drain:.2f} > "
                   f"{self.cfg.drain_high_s})")
            return await self._scale_out(
                f"role '{role}': {why} sustained {p_for:.1f}s "
                f">= {self.cfg.scale_out_sustain_s:.1f}s", role=role)

        # idle scale-in, per role, above each role's floor
        for role, (lo, _hi) in sorted(self.cfg.roles.items()):
            own = _own(role)
            if not own:
                continue
            total_window = sum(w.window for w in own)
            total_inflight = sum(w.inflight for w in own)
            idle = (total_inflight <= self.cfg.idle_frac * total_window
                    and all(w.drain_s <= self.cfg.drain_high_s for w in own))
            tr = self._role_idle.setdefault(role, _Sustain())
            i_for = tr.observe(idle, now)
            if (i_for >= self.cfg.scale_in_sustain_s
                    and len(own) > lo and not in_cooldown):
                return await self._scale_in(
                    f"role '{role}' headroom sustained {i_for:.1f}s >= "
                    f"{self.cfg.scale_in_sustain_s:.1f}s (inflight "
                    f"{total_inflight} <= {self.cfg.idle_frac} * window "
                    f"{total_window})", candidates=own)
        return None

    async def _note_departures(self) -> None:
        """Count workers newly seen dead (missed heartbeats flip them via
        the dispatcher's staleness check; a crashed child also shows here)
        and drop controller-spawned corpses from the routing table — a
        static member may come back on its address, a preempted spawn never
        does (its replacement gets a fresh port)."""
        for url, w in list(self.dispatcher.workers.items()):
            if w.alive:
                self._known_dead.discard(url)
                continue
            if url in self._known_dead:
                continue
            self._known_dead.add(url)
            self.m_preempt.inc()
            self._event("departure", w.last_error or "worker went dead",
                        worker=url)
            if self.spawner is not None and getattr(
                    self.spawner, "owns", lambda u: False)(url):
                reap = getattr(self.spawner, "reap", None)
                if reap is not None:
                    reap(url)
                self.dispatcher.remove_worker(url)
                self._known_dead.discard(url)

    async def _scale_out(self, reason: str, *,
                         kind: str = "scale_out",
                         role: Optional[str] = None) -> Optional[dict]:
        if self.spawner is None:
            self._event(f"{kind}_skipped", f"{reason}; no spawner/template "
                        "configured")
            self._last_action_t = self.clock()
            return None
        shapes = self.incumbent_shapes()
        try:
            # role passed only when set: role-blind spawners (tests, older
            # embedders) keep their (shapes)-only signature
            if role is not None:
                url = await self.spawner.spawn(shapes, role=role)
            else:
                url = await self.spawner.spawn(shapes)
        except Exception as e:
            self._event(f"{kind}_failed", f"{reason}; spawn failed: "
                        f"{type(e).__name__}: {e}")
            self._last_action_t = self.clock()
            return None
        ok = await self._adopt(url)
        self._last_action_t = self.clock()
        self._pressure.since = None
        self._idle.since = None
        if not ok:
            try:
                await self.spawner.retire(url, grace_s=5.0)
            except Exception:
                pass
            self.dispatcher.remove_worker(url)
            ev = self._event(
                f"{kind}_failed",
                f"{reason}; worker {url} never answered register within "
                f"{self.cfg.spawn_timeout_s:.0f}s")
            return ev
        if kind == "respawn":
            pass  # departures already counted on m_preempt
        else:
            self.m_scale_out.inc()
        self._refresh_size()
        return self._event(kind, reason, worker=url,
                           warm_shapes=bool(shapes))

    async def _adopt(self, url: str) -> bool:
        """Add the newcomer to the routing table and wait for its register
        (warmup compiles happen before its port opens, so answering means
        serving-ready and shape-warm)."""
        w = self.dispatcher.add_worker(url)
        deadline = self.clock() + self.cfg.spawn_timeout_s
        while True:
            try:
                await self.dispatcher._probe(w)
            except Exception:
                pass
            if w.alive:
                return True
            if self.clock() >= deadline:
                return False
            await asyncio.sleep(min(0.25, self.cfg.interval_s))

    async def _scale_in(self, reason: str,
                        candidates: Optional[list] = None) -> Optional[dict]:
        live = candidates if candidates is not None else self._live()
        # least-loaded: fewest outstanding dispatches, then smallest drain
        # estimate; prefer retiring our own spawns over static members (the
        # yaml fleet is the operator's floor topology)
        victim = min(live, key=lambda w: (
            0 if getattr(self.spawner, "owns", lambda u: False)(w.url) else 1,
            w.inflight, w.drain_s))
        self._last_action_t = self.clock()
        self._idle.since = None
        try:
            await self.dispatcher.set_drain(victim, True)
            await self.dispatcher.wait_drained(victim, self.cfg.drain_s)
        except Exception as e:
            # a worker that won't drain keeps serving; undrain and move on
            try:
                await self.dispatcher.set_drain(victim, False)
            except Exception:
                pass
            self._event("scale_in_failed",
                        f"{reason}; drain of {victim.url} failed: "
                        f"{type(e).__name__}: {e}")
            return None
        if getattr(self.spawner, "owns", lambda u: False)(victim.url):
            try:
                await self.spawner.retire(victim.url, grace_s=self.cfg.drain_s)
            except Exception:
                logger.exception("fleet[%s]: retire of %s failed", self.name,
                                 victim.url)
        self.dispatcher.remove_worker(victim.url)
        self._known_dead.discard(victim.url)
        self.m_scale_in.inc()
        self._refresh_size()
        return self._event("scale_in", reason, worker=victim.url)

    # -- introspection -----------------------------------------------------

    def report(self) -> dict:
        # workers that died with a fenced incarnation and have not
        # re-registered under a fresh one: partition-healed zombies the
        # dispatcher is actively rejecting (runtime/cluster.py fencing) —
        # the operator's first question after a partition event
        fenced = {w.url: list(w.fenced)
                  for w in self.dispatcher.workers.values()
                  if w.fenced and not w.alive}
        return {
            "size": len(self._live()),
            "policy": self.cfg.report(),
            "scale_outs": int(self.m_scale_out.value),
            "scale_ins": int(self.m_scale_in.value),
            "departures": int(self.m_preempt.value),
            "fenced": fenced,
            "events": list(self._events),
        }
