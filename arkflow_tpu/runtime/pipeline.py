"""Pipeline: a sequential processor chain with fan-out.

Mirrors the reference's ``Pipeline::process`` fold (ref:
crates/arkflow-core/src/pipeline/mod.rs:57-85): each processor maps every
in-flight batch to zero or more batches; an empty result short-circuits the
chain (the ``ProcessResult::None`` drop path); multiple results fan out
through the remaining processors (``ProcessResult::Multiple``).
"""

from __future__ import annotations

from typing import Sequence

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.components.base import Processor


class Pipeline:
    def __init__(self, processors: Sequence[Processor]):
        self.processors = list(processors)

    async def connect(self) -> None:
        """Pre-flight every processor (e.g. model warmup) before data flows.

        Tolerates duck-typed processors without the optional hook."""
        for proc in self.processors:
            hook = getattr(proc, "connect", None)
            if hook is not None:
                await hook()

    async def process(self, batch: MessageBatch) -> list[MessageBatch]:
        current = [batch]
        for proc in self.processors:
            nxt: list[MessageBatch] = []
            for b in current:
                nxt.extend(await proc.process(b))
            if not nxt:
                return []
            current = nxt
        return current

    async def close(self) -> None:
        for proc in self.processors:
            await proc.close()
