"""Process-sharded ingest hot path behind one endpoint.

``pipeline.process_pool`` (runtime/procpool.py) escapes the GIL for the
processor chain only: decode, coalescing, admission and dispatch still run
in the parent process, and at saturation the profile shows the batch
spending most of its end-to-end time in ``queue_wait`` — the host wall is
the single-process hot loop, not the chain. ``pipeline.ingest_shards: N``
breaks that wall by running the ENTIRE hot path (coalesce -> admission ->
process) in N shard processes behind the parent's single endpoint:

- The parent keeps the input, the output and the error_output — one
  endpoint, one ack domain, one place where the zero-silent-loss identity
  (offered == delivered + shed) is enforced.
- The stage queue between input and workers becomes an Arrow-IPC flight
  hop over a unix socket (the same length-prefixed frames and zero-copy
  ``batch_to_ipc`` the cluster plane uses, connect/flight.py).
- Batches are partitioned by the existing ``batch_fingerprint`` (or the
  tenant hash when tenant accounting is on) over a ``HashRing``
  (runtime/cluster.py), so each shard owns a disjoint key range:
  byte-identical duplicates coalesce in ONE shard, response-cache entries
  stay hot in the shard that made them, and per-key poison/attempt state
  never needs cross-shard coordination.
- Each shard runs its own AIMD admission window / deadline / priority /
  WDRR fairness (``OverloadConfig.shard_local``), while tenant QUOTAS are
  granted exactly once in the parent's shared quota plane
  (``OverloadController.admit_quota``) — N shards each holding the full
  quota would over-grant every tenant's contract N times.
- The parent assigns one global sequence number per dispatched delivery
  and restores global output order with a reorder window keyed on those
  seqs; a merged (coalesced) shard emission anchors at the LOWEST covered
  seq, which is exactly where the single-process stream would have
  emitted it.
- A SIGKILLed shard is detected by socket EOF: its in-flight deliveries
  are redispatched in seq order to the ring survivors (the parent still
  holds every batch + ack until disposition). Respawning replacement
  shards is the fleet controller's job (runtime/fleet.py), not this
  plane's.

Tracing: the shard records ``shard_hop`` (send->receive), buffer/coalesce
waits, ``queue_wait`` and ``process`` spans into its own process-local
tracer and exports them with each disposition; the parent grafts them
into the batch's trace (``Tracer.adopt_spans``) before finishing it, so
``stage_breakdown`` shows the sharded pipeline end to end.

Device processors (``tpu_inference``/``tpu_generate``) are allowed in
shards — in CPU/tiny mode every shard owns an independent XLA client.
Against one REAL device, N shards would thrash it exactly like N pool
workers; use the cluster/remote_tpu plane for that split instead.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

import pyarrow as pa

from arkflow_tpu.batch import META_INGEST_TIME, MessageBatch, batch_fingerprint
from arkflow_tpu.components.base import Input, NoopAck, Output, Resource
from arkflow_tpu.components.registry import build_component
from arkflow_tpu.config import StreamConfig
from arkflow_tpu.connect.flight import (
    DEFAULT_MAX_FRAME,
    _read_frame,
    _send_frame,
    batch_to_ipc,
    ipc_to_batches,
)
from arkflow_tpu.errors import EndOfInput, ProcessError
from arkflow_tpu.obs import global_registry
from arkflow_tpu.obs.trace import TracingConfig, global_tracer
from arkflow_tpu.runtime.cluster import HashRing
from arkflow_tpu.runtime.overload import OverloadConfig, input_pauses_on_overload
from arkflow_tpu.runtime.pipeline import Pipeline
from arkflow_tpu.runtime.stream import MAX_PENDING, Stream, _Done, _WorkItem

logger = logging.getLogger("arkflow.hostshard")

#: ext-metadata key carrying the parent's delivery id across the hop
#: (column ``__meta_ext_shard_delivery``). Ext columns are excluded from
#: ``batch_fingerprint``, so stamping it perturbs neither routing nor the
#: shard-side coalescer/cache identity; the coalescer concatenates it
#: per-row, so a merged emission still names every covered delivery
#: (``MessageBatch.ext_values``).
SHARD_DELIVERY_KEY = "shard_delivery"

#: how long the parent waits for every shard's hello at startup
CONNECT_TIMEOUT_S = 30.0


@dataclass
class ShardSpec:
    """Everything one shard process needs to build its half of the stream
    (pickled through the spawn barrier — plain data only)."""

    shard_id: int
    socket_path: str
    name: str
    processors: list = field(default_factory=list)
    temporaries: list = field(default_factory=list)  # [(name, config), ...]
    buffer: Optional[dict] = None
    #: shard-local overload view (quotas stripped) — see shard_local()
    overload: Optional[OverloadConfig] = None
    thread_num: int = 1
    queue_size: int = 4
    max_frame: int = DEFAULT_MAX_FRAME
    tracing: Optional[TracingConfig] = None


# ---------------------------------------------------------------------------
# shard child process
# ---------------------------------------------------------------------------


class _ShardSocketInput(Input):
    """Child-side input: length-prefixed ``{"op": "batch"}`` header frames +
    one Arrow-IPC frame each, from the parent's dispatcher. ``drain`` (or
    parent EOF) ends the stream, which drains the shard's buffer and
    pipeline through the normal ``EndOfInput`` path."""

    def __init__(self, reader: asyncio.StreamReader, max_frame: int):
        self._reader = reader
        self._max_frame = max_frame
        self._done = False
        self.batches = 0
        self.rows = 0

    async def connect(self) -> None:
        return None

    async def read(self):
        if self._done:
            raise EndOfInput("shard input drained")
        tracer = global_tracer()
        while True:
            try:
                hdr = await _read_frame(self._reader, self._max_frame)
            except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
                self._done = True
                raise EndOfInput(f"parent endpoint closed: {e}")
            if hdr is None:
                continue
            msg = json.loads(hdr)
            op = msg.get("op")
            if op == "drain":
                self._done = True
                raise EndOfInput("drain requested")
            if op != "batch":
                continue
            data = await _read_frame(self._reader, self._max_frame)
            rbs = ipc_to_batches(data)
            batch = (MessageBatch(rbs[0]) if len(rbs) == 1
                     else MessageBatch.from_table(pa.Table.from_batches(rbs)))
            self.batches += 1
            self.rows += batch.num_rows
            ts = msg.get("ts")
            if ts is not None and tracer.enabled:
                ctx = batch.trace_context()
                if ctx is not None:
                    # wall-clock send->receive on ONE host: the queue-hop
                    # cost sharding added, visible in stage_breakdown
                    tracer.record(ctx, "shard_hop",
                                  max(0.0, time.time() - float(ts)))
            return batch, NoopAck()


class _NullOutput(Output):
    """The child stream never writes an output directly — dispositions go
    back over the socket from the ``_emit`` override. A write landing here
    means a code path was missed; fail loudly into the error protocol."""

    async def connect(self) -> None:
        return None

    async def write(self, batch: MessageBatch) -> None:
        raise ProcessError("shard-internal output should never be written")


class _ShardChildStream(Stream):
    """The shard's half of the stream: full hot loop (buffer/coalesce,
    fair queue, shard-local AIMD admission, pipeline), with every terminal
    disposition (results / shed / error) serialized back to the parent
    instead of written/acked locally. The parent owns the real acks, the
    delivery-attempt budget and the trace lifecycle; this class only
    exports its open spans alongside each disposition."""

    def __init__(self, writer: asyncio.StreamWriter, **kw):
        super().__init__(**kw)
        self._writer = writer
        #: one disposition is multiple frames; sheds can fire from the
        #: input/buffer tasks while a worker emits — serialize messages
        self._wlock = asyncio.Lock()
        self._emissions = 0

    def shard_stats(self) -> dict:
        return {"batches": getattr(self.input, "batches", 0),
                "rows": getattr(self.input, "rows", 0),
                "emissions": self._emissions}

    async def _send_msg(self, header: dict, frames=()) -> None:
        async with self._wlock:
            await _send_frame(self._writer,
                              json.dumps(header, separators=(",", ":")).encode())
            for f in frames:
                await _send_frame(self._writer, f)

    def _pop_spans(self, ctx) -> list:
        if ctx is None or not self.tracer.enabled:
            return []
        return self.tracer.export_open(ctx)

    def _trace_emission(self, batch: MessageBatch):
        # Same merge semantics as Stream._trace_emission, except the source
        # traces are NOT finished here: the parent owns every source trace
        # (it finishes them at ack time), so the shard grafts the sources'
        # open spans (shard_hop, input_decode) into the merged context so
        # they ride home with the emission instead of being stranded.
        wait_s = getattr(self.buffer, "last_emission_wait_s", None)
        if wait_s is None:
            ingest = batch.get_meta(META_INGEST_TIME)
            wait_s = (max(0.0, time.time() - float(ingest) / 1000.0)
                      if ingest is not None else 0.0)
        contexts = batch.source_trace_contexts()
        if len(contexts) <= 1:
            ctx = contexts[0] if contexts else self.tracer.begin()
            self.tracer.record(ctx, "buffer_wait", wait_s)
            return batch, ctx
        ctx = self.tracer.begin()
        for src in contexts:
            self.tracer.adopt_spans(ctx, self.tracer.export_open(src))
        self.tracer.record(ctx, "coalesce_wait", wait_s,
                           attrs={"links": [c.trace_id for c in contexts]})
        return batch.with_trace(ctx), ctx

    async def _emit(self, item: _WorkItem, results, err) -> None:
        deliveries = item.batch.ext_values(SHARD_DELIVERY_KEY)
        self._emissions += 1
        spans = self._pop_spans(item.trace)
        if err is not None:
            self.m_errors.inc()
            await self._send_msg({"op": "error", "deliveries": deliveries,
                                  "error": str(err)[:500], "spans": spans})
        else:
            ipcs = [batch_to_ipc(b.record_batch) for b in results]
            await self._send_msg({"op": "result", "deliveries": deliveries,
                                  "n": len(ipcs), "spans": spans}, ipcs)
        await self._safe_ack(item.ack)  # no-op socket acks; keeps counters sane

    async def _shed_item(self, item: _WorkItem, reason: str) -> None:
        deliveries = item.batch.ext_values(SHARD_DELIVERY_KEY)
        spans = self._pop_spans(item.trace)
        await self._send_msg({"op": "shed", "deliveries": deliveries,
                              "reason": reason, "spans": spans})
        await self._safe_ack(item.ack)


async def _shard_run(spec: ShardSpec) -> None:
    from arkflow_tpu.components import ensure_plugins_loaded

    ensure_plugins_loaded()
    tracer = global_tracer()
    if spec.tracing is not None:
        tracer.configure(spec.tracing, tier=f"shard{spec.shard_id}")
    reader, writer = await asyncio.open_unix_connection(spec.socket_path)
    await _send_frame(writer, json.dumps(
        {"op": "hello", "shard": spec.shard_id, "pid": os.getpid()}).encode())
    resource = Resource()
    for tname, tcfg in spec.temporaries:
        resource.temporaries[tname] = build_component("temporary", tcfg, resource)
    procs = [build_component("processor", p, resource) for p in spec.processors]
    buffer = build_component("buffer", spec.buffer, resource) if spec.buffer else None
    stream = _ShardChildStream(
        writer=writer,
        input_=_ShardSocketInput(reader, spec.max_frame),
        pipeline=Pipeline(procs),
        output=_NullOutput(),
        buffer=buffer,
        temporaries=resource.temporaries,
        thread_num=spec.thread_num,
        name=f"{spec.name}-shard{spec.shard_id}",
        queue_size=spec.queue_size,
        overload=spec.overload,
    )
    try:
        await stream.run(asyncio.Event())
    finally:
        try:
            await _send_frame(writer, json.dumps(
                {"op": "bye", "stats": stream.shard_stats()}).encode())
            writer.close()
        except Exception:
            pass  # parent gone; nothing left to report to


def _shard_main(spec: ShardSpec) -> None:
    """Spawn entry point for one ingest shard."""
    logging.basicConfig(level=logging.WARNING)
    try:
        asyncio.run(_shard_run(spec))
    except KeyboardInterrupt:
        pass


# ---------------------------------------------------------------------------
# parent: one endpoint, N shards
# ---------------------------------------------------------------------------


class _ShardConn:
    __slots__ = ("sid", "proc", "reader", "writer", "lock", "connected",
                 "alive", "clean", "stats")

    def __init__(self, sid: int, proc):
        self.sid = sid
        self.proc = proc
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.lock = asyncio.Lock()
        self.connected = asyncio.Event()
        self.alive = True
        self.clean = False  # saw a bye before EOF
        self.stats: dict = {}


class _Outstanding:
    __slots__ = ("d", "seq", "item", "shard", "key")

    def __init__(self, d: str, seq: int, item: _WorkItem, key: bytes):
        self.d = d
        self.seq = seq
        self.item = item
        self.shard: Optional[int] = None
        self.key = key


class _DispatchQueue:
    """Adapter with the one method ``Stream._do_input`` uses (``put``), so
    the parent reuses the battle-tested read/trace/admission loop verbatim
    while the 'queue' is really the flight hop router."""

    def __init__(self, stream: "ShardedIngestStream"):
        self._stream = stream

    async def put(self, item) -> None:
        await self._stream._dispatch(item)


_ORDER_EOF = object()
_RETIRED = object()


class ShardedIngestStream(Stream):
    """Parent endpoint of the sharded ingest plane. Inherits the input
    loop, shed/quarantine/ack plumbing and metrics from ``Stream``; replaces
    the in-process queue+workers with the shard router, per-shard readers
    and a global-seq reorder window."""

    def __init__(self, *, shards: int, spec: ShardSpec, **kw):
        super().__init__(**kw)
        self.num_shards = max(1, shards)
        self._spec = spec
        self._conns: dict[int, _ShardConn] = {}
        self._outstanding: dict[str, _Outstanding] = {}
        self._disp_q: asyncio.Queue = asyncio.Queue()
        self._ring = HashRing()
        self._input_done = 0
        self._tmpdir: Optional[str] = None
        self._server = None
        reg = global_registry()
        labels = {"stream": self.name}
        self.m_shard_dispatch = reg.counter(
            "arkflow_shard_dispatch_total",
            "batches dispatched over the ingest-shard hop", labels)
        self.m_redispatch = reg.counter(
            "arkflow_shard_redispatch_total",
            "in-flight deliveries re-sent to a surviving shard after a "
            "shard death", labels)
        self.m_shards_live = reg.gauge(
            "arkflow_ingest_shards_live", "ingest shard processes alive", labels)

    # -- admission: shared quota plane only --------------------------------

    async def _admit_or_shed(self, item: _WorkItem) -> bool:
        """Parent-side admission is the tenant QUOTA gate alone: quotas are
        a per-tenant contract and must be granted once globally, while the
        congestion controls (AIMD window, deadline, priority, fair share)
        run per shard against each shard's own backlog. NOTE: no
        ``on_enqueue`` here — the parent never dequeues, so window
        accounting would only ratchet upward."""
        ctrl = self.overload
        if ctrl is None:
            return True
        tokens = 0.0
        if ctrl.cfg.tenants is not None:
            item.tenant = ctrl.tenant_label(item.batch.tenant())
            if ctrl.meters_tokens():
                tokens = self._estimate_tokens(item.batch, ctrl.cfg.tenants)
        reason = ctrl.admit_quota(item.tenant, rows=float(item.batch.num_rows),
                                  tokens=tokens)
        if reason is None:
            return True
        await self._shed_item(item, reason)
        return False

    # -- routing -----------------------------------------------------------

    def _route_key(self, item: _WorkItem) -> bytes:
        """Tenant hash when the batch carries one (keeps one tenant's
        fairness lanes and coalescer state in one shard — whether or not
        tenant ACCOUNTING is on), else the batch fingerprint (keeps
        duplicates/cache keys in one shard)."""
        tenant = item.tenant or item.batch.tenant()
        if tenant is not None:
            return tenant.encode()
        return batch_fingerprint(item.batch)

    def _pick_shard(self, key: bytes) -> Optional[int]:
        for node in self._ring.candidates(key):
            conn = self._conns.get(int(node))
            if conn is not None and conn.alive:
                return conn.sid
        return None

    async def _dispatch(self, item) -> None:
        if isinstance(item, _Done):
            self._input_done += 1
            if self._input_done >= self.thread_num:
                await self._begin_drain()
            return
        # backpressure on in-flight deliveries, same bound and event as the
        # single-process reorder window
        while len(self._outstanding) > MAX_PENDING:
            self._drained.clear()
            try:
                await asyncio.wait_for(self._drained.wait(), 1.0)
            except asyncio.TimeoutError:
                pass
        seq = self._seq_assigned
        self._seq_assigned += 1
        d = str(seq)
        ent = _Outstanding(d, seq, item, self._route_key(item))
        self._outstanding[d] = ent
        self.m_pending.set(len(self._outstanding))
        await self._send_to_shard(ent)

    async def _send_to_shard(self, ent: _Outstanding) -> None:
        sid = self._pick_shard(ent.key)
        if sid is None:
            raise ProcessError("all ingest shards are down")
        conn = self._conns[sid]
        ent.shard = sid
        stamped = ent.item.batch.with_ext_metadata({SHARD_DELIVERY_KEY: ent.d})
        hdr = json.dumps({"op": "batch", "d": ent.d, "ts": time.time()},
                         separators=(",", ":")).encode()
        ipc = batch_to_ipc(stamped.record_batch)
        try:
            async with conn.lock:
                await _send_frame(conn.writer, hdr)
                await _send_frame(conn.writer, ipc)
            self.m_shard_dispatch.inc()
        except (ConnectionError, OSError) as e:
            # the shard died under the write; its reader task will reap the
            # connection and redispatch every delivery assigned to it
            # (including this one — ent.shard is already set)
            logger.warning("[%s] dispatch to shard %d failed (%s); awaiting "
                           "redispatch", self.name, sid, e)

    async def _begin_drain(self) -> None:
        # Input EOF does NOT mean the shards are done: a shard death after
        # this point redispatches its in-flight deliveries to the survivors,
        # and a drained survivor stops reading its socket — the redelivery
        # would be lost. Hold the drain op until every outstanding delivery
        # has a disposition (children emit results without needing drain;
        # the op only ends their input loop).
        while self._outstanding and any(c.alive for c in self._conns.values()):
            self._drained.clear()
            if self._outstanding and any(c.alive for c in self._conns.values()):
                try:
                    await asyncio.wait_for(self._drained.wait(), 0.25)
                except asyncio.TimeoutError:
                    pass
        for conn in self._conns.values():
            if not conn.alive:
                continue
            try:
                async with conn.lock:
                    await _send_frame(conn.writer, b'{"op":"drain"}')
            except (ConnectionError, OSError):
                pass

    # -- shard lifecycle ----------------------------------------------------

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            hdr = await _read_frame(reader, self._spec.max_frame)
            sid = int(json.loads(hdr).get("shard", -1))
        except Exception:
            writer.close()
            return
        conn = self._conns.get(sid)
        if conn is None or conn.connected.is_set():
            writer.close()
            return
        conn.reader, conn.writer = reader, writer
        conn.connected.set()

    async def _read_shard(self, conn: _ShardConn) -> None:
        try:
            while True:
                hdr = await _read_frame(conn.reader, self._spec.max_frame)
                if hdr is None:
                    break
                msg = json.loads(hdr)
                op = msg.get("op")
                if op == "result":
                    batches: list[MessageBatch] = []
                    for _ in range(int(msg.get("n", 0))):
                        fr = await _read_frame(conn.reader, self._spec.max_frame)
                        batches.extend(MessageBatch(rb)
                                       for rb in ipc_to_batches(fr))
                    self._resolve(msg.get("deliveries") or [],
                                  ("result", batches, msg.get("spans") or []))
                elif op == "shed":
                    self._resolve(msg.get("deliveries") or [],
                                  ("shed", str(msg.get("reason") or "overloaded"),
                                   msg.get("spans") or []))
                elif op == "error":
                    self._resolve(msg.get("deliveries") or [],
                                  ("error", str(msg.get("error") or "shard error"),
                                   msg.get("spans") or []))
                elif op == "bye":
                    conn.clean = True
                    conn.stats = msg.get("stats") or {}
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            if conn.clean:
                # expected: the child closes its socket right after the bye
                logger.debug("[%s] shard %d closed after bye", self.name,
                             conn.sid)
            else:
                logger.warning("[%s] shard %d connection lost: %s",
                               self.name, conn.sid, e)
        finally:
            await self._on_shard_down(conn)

    def _resolve(self, deliveries: list, disposition: tuple) -> None:
        entries = [self._outstanding.pop(d) for d in deliveries
                   if d in self._outstanding]
        if len(self._outstanding) <= MAX_PENDING:
            self._drained.set()
        self.m_pending.set(len(self._outstanding))
        if entries:
            self._disp_q.put_nowait((entries, disposition))

    async def _on_shard_down(self, conn: _ShardConn) -> None:
        if not conn.alive:
            return
        conn.alive = False
        self._ring.remove(str(conn.sid))
        self.m_shards_live.set(sum(1 for c in self._conns.values() if c.alive))
        pend = sorted((e for e in self._outstanding.values()
                       if e.shard == conn.sid), key=lambda e: e.seq)
        if not pend:
            return
        if not conn.clean:
            logger.error("[%s] shard %d died with %d in-flight deliveries; "
                         "redispatching to survivors", self.name, conn.sid,
                         len(pend))
        if any(c.alive for c in self._conns.values()):
            self.m_redispatch.inc(len(pend))
            for ent in pend:
                await self._send_to_shard(ent)
        else:
            # no survivors: fail the deliveries through the orderer so their
            # seqs release and the attempt/nack machinery disposes of them
            # (redelivery or quarantine — never silent loss)
            self._resolve([e.d for e in pend],
                          ("error", "all ingest shards died", []))

    # -- ordered emission ---------------------------------------------------

    async def _do_shard_output(self) -> None:
        """Reorder dispositions by global seq and execute them contiguously
        (the sharded analogue of ``Stream._do_output``). A multi-delivery
        disposition anchors at its lowest seq; the other covered seqs are
        marked retired and release as the window advances."""
        reorder: dict[int, object] = {}
        next_seq = 0
        while True:
            msg = await self._disp_q.get()
            if msg is _ORDER_EOF:
                for seq in sorted(reorder):
                    val = reorder.pop(seq)
                    if val is not _RETIRED:
                        await self._execute(*val)
                return
            entries, disp = msg
            entries.sort(key=lambda e: e.seq)
            reorder[entries[0].seq] = (entries, disp)
            for e in entries[1:]:
                reorder[e.seq] = _RETIRED
            while next_seq in reorder:
                val = reorder.pop(next_seq)
                next_seq += 1
                self._seq_emitted = next_seq
                if val is not _RETIRED:
                    await self._execute(*val)

    def _strip_delivery(self, batch: MessageBatch) -> MessageBatch:
        """Drop the internal delivery column before the batch reaches the
        user-facing output (column selection shares buffers — no copy)."""
        rb = batch.record_batch
        name = "__meta_ext_" + SHARD_DELIVERY_KEY
        if name not in rb.schema.names:
            return batch
        return MessageBatch(rb.select(
            [n for n in rb.schema.names if n != name]))

    async def _execute(self, entries: list, disp: tuple) -> None:
        kind = disp[0]
        anchor = entries[0]
        spans = disp[2] if len(disp) > 2 else []
        if spans and anchor.item.trace is not None:
            self.tracer.adopt_spans(anchor.item.trace, spans)
        if kind == "result":
            await self._execute_result(entries, disp[1])
        elif kind == "shed":
            for ent in entries:
                await self._shed_item(ent.item, disp[1])
        else:  # "error"
            err = ProcessError(disp[1])
            self.m_errors.inc()
            for ent in entries:
                await self._fail_entry(ent, err)

    async def _execute_result(self, entries: list,
                              batches: list[MessageBatch]) -> None:
        anchor = entries[0]
        loop = asyncio.get_running_loop()
        try:
            t0 = loop.time()
            for b in batches:
                t_w = loop.time()
                await self._write_guarded(self.output, self._out_breaker,
                                          self.output_retry,
                                          self._strip_delivery(b),
                                          f"[{self.name}] output write")
                self.m_write_latency.observe(loop.time() - t_w)
                self.m_batches_out.inc()
                self.m_rows_out.inc(b.num_rows)
            if batches and anchor.item.trace is not None:
                self.tracer.record(anchor.item.trace, "output_write",
                                   loop.time() - t0,
                                   attrs=({"batches": len(batches)}
                                          if len(batches) > 1 else None))
        except Exception as e:
            self.m_write_errors.inc()
            err = ProcessError(f"output write failed: {e}")
            for ent in entries:
                await self._fail_entry(ent, err)
            return
        now = time.time()
        for ent in entries:
            item = ent.item
            self._clear_attempts(item.batch)
            ingest = item.batch.get_meta(META_INGEST_TIME)
            e2e = None
            if ingest is not None:
                e2e = max(0.0, now - ingest / 1000.0)
                self.m_e2e_latency.observe(e2e)
                if self.overload is not None and item.tenant is not None:
                    self.overload.observe_tenant_latency(item.tenant, e2e)
            self.tracer.finish(item.trace, "ok", e2e_s=e2e)
            await self._safe_ack(item.ack)

    async def _fail_entry(self, ent: _Outstanding, err: Exception) -> None:
        """Per-delivery failure disposition — same budget/nack/quarantine
        ladder as ``Stream._emit``'s error path."""
        item = ent.item
        attempts = self._bump_attempts(item.batch, trace=item.trace)
        self.tracer.finish(item.trace, "error",
                           attrs={"error": str(err)[:200], "attempt": attempts})
        if attempts < self.max_delivery_attempts and getattr(
                item.ack, "redeliverable", False):
            await self._safe_nack(item.ack)
            return
        if self.error_output is not None:
            await self._quarantine(item, str(err), attempts)
        else:
            logger.error("[%s] shard processing error (no error_output): %s",
                         self.name, err)
            self._clear_attempts(item.batch)
            await self._safe_ack(item.ack)

    # -- lifecycle ----------------------------------------------------------

    def shard_pids(self) -> dict[int, int]:
        """Live shard pids (chaos tooling kills one mid-load)."""
        return {sid: c.proc.pid for sid, c in self._conns.items() if c.alive}

    def shard_stats(self) -> dict[int, dict]:
        """Per-shard bye stats (routing/affinity assertions in the soak)."""
        return {sid: dict(c.stats) for sid, c in self._conns.items()}

    async def run(self, cancel: asyncio.Event) -> None:
        import multiprocessing as mp

        await self.input.connect()
        await self.output.connect()
        if self.error_output is not None:
            await self.error_output.connect()
        self._pause_source = (self.overload is not None
                              and input_pauses_on_overload(self.input))
        self._tmpdir = tempfile.mkdtemp(prefix="arkflow-hostshard-")
        sock = os.path.join(self._tmpdir, "ingest.sock")
        self._server = await asyncio.start_unix_server(self._on_connect,
                                                       path=sock)
        ctx = mp.get_context("spawn")
        tracing = self.tracer.cfg if self.tracer.enabled else dataclasses.replace(
            self.tracer.cfg, enabled=False)
        for sid in range(self.num_shards):
            spec = dataclasses.replace(self._spec, shard_id=sid,
                                       socket_path=sock, tracing=tracing)
            proc = ctx.Process(target=_shard_main, args=(spec,), daemon=True)
            proc.start()
            self._conns[sid] = _ShardConn(sid, proc)
        readers: list[asyncio.Task] = []
        orderer: Optional[asyncio.Task] = None
        input_task: Optional[asyncio.Task] = None
        try:
            await asyncio.wait_for(
                asyncio.gather(*[c.connected.wait()
                                 for c in self._conns.values()]),
                CONNECT_TIMEOUT_S)
            for sid in self._conns:
                self._ring.add(str(sid))
            self.m_shards_live.set(self.num_shards)
            readers = [asyncio.create_task(self._read_shard(c),
                                           name=f"{self.name}-shard{c.sid}-rx")
                       for c in self._conns.values()]
            orderer = asyncio.create_task(self._do_shard_output(),
                                          name=f"{self.name}-order")
            input_task = asyncio.create_task(
                self._do_input(_DispatchQueue(self), cancel),
                name=f"{self.name}-input")
            await input_task
            await asyncio.gather(*readers)
            # belt-and-braces: anything still outstanding after every reader
            # exited can never get a disposition — fail it through the
            # orderer (nack/quarantine), never drop it silently
            if self._outstanding:
                stuck = sorted(self._outstanding.values(), key=lambda e: e.seq)
                self._outstanding.clear()
                self._disp_q.put_nowait(
                    (stuck, ("error", "shard plane shut down with in-flight "
                             "deliveries", [])))
            self._disp_q.put_nowait(_ORDER_EOF)
            await orderer
        except BaseException:
            for t in (input_task, orderer, *readers):
                if t is not None:
                    t.cancel()
            await asyncio.gather(*(t for t in (input_task, orderer, *readers)
                                   if t is not None), return_exceptions=True)
            raise
        finally:
            await self._teardown()

    async def _teardown(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None
        for conn in self._conns.values():
            try:
                if conn.writer is not None:
                    conn.writer.close()
            except Exception:
                pass
            proc = conn.proc
            if proc.is_alive():
                proc.terminate()
        for conn in self._conns.values():
            conn.proc.join(timeout=5.0)
            if conn.proc.is_alive():
                conn.proc.kill()
                conn.proc.join(timeout=5.0)
        if self._tmpdir is not None:
            import shutil

            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None
        await self._close_all()


def build_sharded_stream(cfg: StreamConfig, name: str) -> ShardedIngestStream:
    """Construct the parent endpoint + shard spec from a stream config
    (the ``build_stream`` seam for ``pipeline.ingest_shards > 0``)."""
    resource = Resource()
    input_ = build_component("input", cfg.input, resource)
    output = build_component("output", cfg.output, resource)
    error_output = (build_component("output", cfg.error_output, resource)
                    if cfg.error_output else None)
    overload_cfg: Optional[OverloadConfig] = cfg.pipeline.overload
    spec = ShardSpec(
        shard_id=-1,
        socket_path="",
        name=name,
        processors=[dict(p) for p in cfg.pipeline.processors],
        temporaries=[(t.name, dict(t.config)) for t in cfg.temporary],
        buffer=dict(cfg.buffer) if cfg.buffer else None,
        overload=(overload_cfg.shard_local()
                  if overload_cfg is not None and overload_cfg.enabled
                  else None),
        thread_num=cfg.pipeline.effective_threads(),
        queue_size=cfg.pipeline.effective_queue_size(),
    )
    return ShardedIngestStream(
        shards=cfg.pipeline.ingest_shards,
        spec=spec,
        input_=input_,
        pipeline=Pipeline([]),  # the chain lives in the shards
        output=output,
        error_output=error_output,
        buffer=None,  # the coalescer lives in the shards
        temporaries={},
        thread_num=cfg.pipeline.effective_threads(),
        name=name,
        output_retry=cfg.output_retry,
        output_breaker=cfg.output_circuit_breaker,
        error_output_retry=cfg.error_output_retry,
        error_output_breaker=cfg.error_output_circuit_breaker,
        max_delivery_attempts=cfg.pipeline.max_delivery_attempts,
        reconnect_retry=cfg.input_reconnect,
        queue_size=cfg.pipeline.effective_queue_size(),
        overload=overload_cfg,
    )
