"""Overload control: deadline-aware admission, AIMD queue windows, shedding.

Once offered load exceeds device throughput, an engine that admits every
batch turns a traffic burst into unbounded queue wait (BENCH_r05's
``saturated_queueing_p99_ms`` ≈ 10.7s) and eventual memory pressure. In the
latency-bound serving regime (Answer Fast / TSP, PAPERS.md) finishing a
stale request is strictly worse than shedding it up front, so the engine
protects itself from its own traffic with three cooperating mechanisms, all
owned by the per-stream :class:`OverloadController`:

1. **Deadline-aware admission** — each batch carries a remaining TTL
   (``pipeline.deadline_ms`` measured from ``__meta_ingest_time``, or an
   absolute ``__meta_ext_deadline_ms`` column stamped upstream). A batch
   whose remaining budget cannot cover the *predicted* queue wait + step
   time is shed before the worker queue — nacked for redelivery or routed
   to ``error_output`` tagged ``overloaded``, never silently dropped.
2. **Adaptive admission window (AIMD)** — the effective worker-queue window
   shrinks multiplicatively when observed queue wait trends above the
   deadline budget and re-grows additively on recovery, replacing the fixed
   ``thread_num * 4`` depth as the only limit. Batches beyond the window are
   shed (``reason=queue``) instead of queued into the latency cliff.
3. **Strict-priority bands** — ``pipeline.priority`` (or a per-batch
   ``__meta_ext_priority`` column) splits traffic into integer bands.
   Bands at/above ``protect_priority`` are never queue-shed (health probes
   and premium traffic survive brownouts); under *persistent* overload at
   the minimum window the admit floor escalates one band at a time
   (``reason=priority``) and relaxes on recovery.

Cooperative backpressure rides on the controller's state: pull-based inputs
(kafka/redis/nats — anything marked ``pause_on_overload``) pause consumption
instead of fetching-then-nacking, and the HTTP input rejects with 429 +
``Retry-After`` computed from the controller's estimated drain time.

4. **Multi-tenant fairness + quotas** — priority bands protect *classes*,
   not tenants: one noisy user in the premium band still monopolizes the
   admission window. With ``overload.tenants`` configured, every batch is
   accounted against its ``__meta_ext_tenant`` id: admission slots inside
   the AIMD window divide by configured tenant *weight* (a tenant at/over
   its share is shed ``reason=queue`` while everyone else keeps admitting —
   its backlog queues behind itself at the broker, not in front of other
   tenants), per-tenant ``TokenBucket`` quotas (rows/s, estimated tokens/s)
   shed ``reason=quota`` through the same never-silent paths, and the
   worker queue itself becomes a weighted deficit-round-robin scheduler
   (:class:`FairQueue`) so admitted batches of a backlogged tenant cannot
   delay other tenants' dequeues either. Tenant labels on metrics are
   cardinality-capped: past ``max_tracked`` distinct ids, the long tail
   collapses into one ``__other__`` bucket (shared state, shared label).

Observability: ``arkflow_overload_state`` (0 admit / 1 throttle / 2 shed),
``arkflow_overload_window``, ``arkflow_shed_total{reason=deadline|queue|
priority|quota}``, ``arkflow_overload_paused_seconds_total``, tenant-labeled
``arkflow_tenant_admitted_total`` / ``arkflow_tenant_shed_total`` /
``arkflow_tenant_e2e_seconds``; the engine's ``/health`` embeds
:meth:`OverloadController.report` per stream (tenant shares included).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.obs import global_registry

#: ``arkflow_overload_state`` gauge values
STATE_ADMIT = 0  #: window at max, queue wait within budget
STATE_THROTTLE = 1  #: window shrunk, recovering additively
STATE_SHED = 2  #: queue wait over budget; admission actively shedding

_STATE_NAMES = {STATE_ADMIT: "admit", STATE_THROTTLE: "throttle", STATE_SHED: "shed"}

SHED_REASONS = ("deadline", "queue", "priority", "quota", "retry_budget")

#: label every tenant past the cardinality cap collapses into — one shared
#: state/metric series for the long tail, so a tenant-id enumeration attack
#: cannot balloon the metric registry
OVERFLOW_TENANT = "__other__"
#: label (and accounting identity) for batches with no tenant column
DEFAULT_TENANT = "default"
#: default bound on distinct tracked tenant ids — the ONE definition the
#: controller (``tenants.max_tracked`` overrides it), the response cache's
#: tenant-hit labels, and the memory buffer's coalescer lanes all share
MAX_TENANT_LABELS = 64


def cap_tenant_label(tenant: Optional[str], tracked, *, reserved=(),
                     cap: int = MAX_TENANT_LABELS) -> str:
    """Raw tenant id -> bounded accounting label: the ONE capping rule the
    controller, the response cache's tenant-hit counters, and the memory
    buffer's coalescer lanes all share. Untagged/empty ids map to
    DEFAULT_TENANT; ids already ``tracked`` (or explicitly ``reserved``,
    e.g. configured tenants) keep their own slot; past ``cap`` distinct
    tracked ids the long tail collapses into OVERFLOW_TENANT."""
    label = tenant if tenant else DEFAULT_TENANT
    if label in tracked or label in reserved:
        return label
    if len(tracked) >= cap:
        return OVERFLOW_TENANT
    return label


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant rate contract. ``None`` = unmetered on that axis.
    Bucket capacity is ``rate * burst_s`` (min 1 token), so a tenant may
    burst one ``burst_s`` worth of its rate before the refill gates it."""

    rows_per_sec: Optional[float] = None
    #: estimated tokens/s — per-row estimates come from the payload Arrow
    #: offsets (``extract.payload_token_estimates``, the PR-6 coalescer
    #: estimator), so metering matches what the packed device path will pay
    tokens_per_sec: Optional[float] = None

    @classmethod
    def from_config(cls, m: Any, where: str) -> Optional["TenantQuota"]:
        if m is None:
            return None
        if not isinstance(m, Mapping):
            raise ConfigError(f"{where} must be a mapping")

        def _rate(key: str) -> Optional[float]:
            v = m.get(key)
            if v is None:
                return None
            if isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0:
                raise ConfigError(f"{where}.{key} must be a positive number, got {v!r}")
            return float(v)

        rows = _rate("rows_per_sec")
        tokens = _rate("tokens_per_sec")
        if rows is None and tokens is None:
            return None
        return cls(rows_per_sec=rows, tokens_per_sec=tokens)


@dataclass
class TenantPolicy:
    """``overload.tenants``: weighted-fair shares + quotas keyed on the
    ``__meta_ext_tenant`` column.

    ::

        overload:
          tenants:
            default_weight: 1
            burst: 2s              # quota bucket capacity = rate x burst
            max_tracked: 64        # label-cardinality cap (then __other__)
            default_quota: {rows_per_sec: 200}
            per_tenant:
              premium: {weight: 8, rows_per_sec: 2000, tokens_per_sec: 50000}
              batch:   {weight: 1}
    """

    default_weight: float = 1.0
    burst_s: float = 1.0
    #: distinct tenant ids tracked with their own state/labels; the rest
    #: collapse into OVERFLOW_TENANT (explicitly-configured tenants always
    #: keep their own slot)
    max_tracked: int = MAX_TENANT_LABELS
    #: floor on any tenant's admission share (batches) so a low-weight
    #: tenant is never starved to zero while others are backlogged
    min_share: int = 1
    #: payload column the tokens/s estimator reads (default ``__value__``)
    #: — MUST match the inference stage's ``text_field`` or token-heavy
    #: rows meter as 1 token each (same knob as the coalescer's
    #: ``token_field``)
    token_field: Optional[str] = None
    #: bytes-per-token divisor for subword (HF/BPE) tokenizers; default:
    #: exact word/punct counting matching the hash tokenizer
    token_bytes: Optional[float] = None
    default_quota: Optional[TenantQuota] = None
    #: tenant id -> (weight, quota); parsed from ``per_tenant``
    weights: dict[str, float] = field(default_factory=dict)
    quotas: dict[str, TenantQuota] = field(default_factory=dict)

    @classmethod
    def from_config(cls, m: Any) -> Optional["TenantPolicy"]:
        from arkflow_tpu.utils.duration import parse_duration

        if m is None or m is False:
            return None
        if m is True:
            m = {}
        if not isinstance(m, Mapping):
            raise ConfigError("overload.tenants must be a mapping or boolean")

        def _num(key: str, default: float, *, minimum: float) -> float:
            v = m.get(key, default)
            if isinstance(v, bool) or not isinstance(v, (int, float)) or v < minimum:
                raise ConfigError(
                    f"overload.tenants.{key} must be a number >= {minimum}, got {v!r}")
            return float(v)

        max_tracked = m.get("max_tracked", MAX_TENANT_LABELS)
        if isinstance(max_tracked, bool) or not isinstance(max_tracked, int) \
                or max_tracked < 1:
            raise ConfigError(
                f"overload.tenants.max_tracked must be an int >= 1, got {max_tracked!r}")
        min_share = m.get("min_share", 1)
        if isinstance(min_share, bool) or not isinstance(min_share, int) or min_share < 1:
            raise ConfigError(
                f"overload.tenants.min_share must be an int >= 1, got {min_share!r}")
        token_field = m.get("token_field")
        if token_field is not None and (not isinstance(token_field, str)
                                        or not token_field):
            raise ConfigError(
                f"overload.tenants.token_field must be a column name, "
                f"got {token_field!r}")
        token_bytes = m.get("token_bytes")
        if token_bytes is not None:
            if isinstance(token_bytes, bool) \
                    or not isinstance(token_bytes, (int, float)) or token_bytes <= 0:
                raise ConfigError(
                    f"overload.tenants.token_bytes must be a positive number, "
                    f"got {token_bytes!r}")
            token_bytes = float(token_bytes)
        policy = cls(
            default_weight=_num("default_weight", 1.0, minimum=0.01),
            burst_s=(parse_duration(m["burst"]) if m.get("burst") is not None else 1.0),
            max_tracked=max_tracked,
            min_share=min_share,
            token_field=token_field,
            token_bytes=token_bytes,
            default_quota=TenantQuota.from_config(
                m.get("default_quota"), "overload.tenants.default_quota"),
        )
        if policy.burst_s <= 0:
            raise ConfigError("overload.tenants.burst must be > 0")
        per = m.get("per_tenant") or {}
        if not isinstance(per, Mapping):
            raise ConfigError("overload.tenants.per_tenant must be a mapping")
        for name, spec in per.items():
            if not isinstance(spec, Mapping):
                raise ConfigError(
                    f"overload.tenants.per_tenant.{name} must be a mapping")
            w = spec.get("weight", policy.default_weight)
            if isinstance(w, bool) or not isinstance(w, (int, float)) or w < 0.01:
                raise ConfigError(
                    f"overload.tenants.per_tenant.{name}.weight must be a "
                    f"number >= 0.01, got {w!r}")
            policy.weights[str(name)] = float(w)
            quota = TenantQuota.from_config(
                spec, f"overload.tenants.per_tenant.{name}")
            if quota is not None:
                policy.quotas[str(name)] = quota
        return policy

    def weight_of(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)

    def quota_of(self, tenant: str) -> Optional[TenantQuota]:
        return self.quotas.get(tenant, self.default_quota)

    def meters_tokens(self) -> bool:
        """Whether ANY tenant has a tokens/s quota — the stream only pays
        the per-batch token-estimate pass when one does."""
        return any(q.tokens_per_sec is not None
                   for q in (*self.quotas.values(),
                             *((self.default_quota,) if self.default_quota else ())))

    def without_quotas(self) -> "TenantPolicy":
        """This policy with every quota stripped (weights/fairness kept).

        The sharded-ingest plane hands each shard process this view: tenant
        TokenBuckets are granted exactly ONCE, in the parent's shared quota
        plane, while the WDRR fairness lanes and weighted admission shares
        still operate per shard — N shards each holding the full quota
        would over-grant every tenant's contract N times."""
        import dataclasses

        return dataclasses.replace(self, default_quota=None, quotas={})


class _TenantState:
    """Per-tenant admission accounting inside one controller."""

    __slots__ = ("label", "weight", "queued", "rows_bucket", "tokens_bucket",
                 "m_admitted", "m_shed", "m_e2e", "_labels")

    def __init__(self, label: str, weight: float, quota: Optional[TenantQuota],
                 burst_s: float, stream: str):
        from arkflow_tpu.utils.rate_limiter import TokenBucket

        self.label = label
        self.weight = weight
        self.queued = 0
        self.rows_bucket = self.tokens_bucket = None
        if quota is not None and quota.rows_per_sec is not None:
            self.rows_bucket = TokenBucket(
                max(1.0, quota.rows_per_sec * burst_s), quota.rows_per_sec)
        if quota is not None and quota.tokens_per_sec is not None:
            self.tokens_bucket = TokenBucket(
                max(1.0, quota.tokens_per_sec * burst_s), quota.tokens_per_sec)
        reg = global_registry()
        self._labels = {"stream": stream, "tenant": label}
        self.m_admitted = reg.counter(
            "arkflow_tenant_admitted_total",
            "batches admitted to the worker queue, by tenant", self._labels)
        #: reason -> counter, created lazily on first shed of that reason
        self.m_shed: dict[str, Any] = {}
        self.m_e2e = reg.histogram(
            "arkflow_tenant_e2e_seconds",
            "read-to-written latency of delivered batches, by tenant",
            self._labels)

    def count_shed(self, reason: str) -> None:
        c = self.m_shed.get(reason)
        if c is None:
            c = self.m_shed[reason] = global_registry().counter(
                "arkflow_tenant_shed_total",
                "batches shed before the worker queue, by tenant",
                {**self._labels, "reason": reason})
        c.inc()

    def report(self) -> dict:
        out = {"weight": self.weight, "queued": self.queued,
               "admitted": int(self.m_admitted.value),
               "shed": {r: int(c.value) for r, c in self.m_shed.items()}}
        if self.rows_bucket is not None:
            out["rows_per_sec"] = self.rows_bucket.refill_per_sec
        if self.tokens_bucket is not None:
            out["tokens_per_sec"] = self.tokens_bucket.refill_per_sec
        return out


@dataclass
class OverloadConfig:
    """Knobs for the per-stream overload controller (``pipeline.overload``).

    ``enabled`` defaults to True whenever ``pipeline.deadline_ms`` is set —
    configuring a deadline without admission control would only measure the
    overload, not prevent it. ``max_window`` is filled by the stream from
    the effective worker-queue size when left at 0.
    """

    enabled: bool = False
    #: per-batch TTL measured from ingest time; None = only absolute
    #: ``__meta_ext_deadline_ms`` columns are deadline-enforced
    deadline_ms: Optional[float] = None
    #: default priority band for batches without a priority column
    priority: int = 0
    #: bands >= this are never queue-shed (strict-priority protection)
    protect_priority: int = 1
    max_window: int = 0  # 0 -> stream fills with its queue size
    min_window: int = 1
    #: fraction of the deadline budget the p50 queue wait may consume before
    #: the AIMD controller starts shrinking the window
    headroom: float = 0.5
    #: absolute queue-wait target (seconds) when no deadline is configured
    target_wait_s: float = 0.1
    decrease: float = 0.5  # multiplicative window shrink factor
    increase: float = 1.0  # additive window re-growth per healthy interval
    interval_s: float = 0.1  # min spacing between AIMD adjustments
    #: consecutive over-budget intervals at min_window before the admit
    #: floor escalates one priority band (brownout); 0 disables escalation
    escalate_after: int = 3
    #: multi-tenant fairness/quotas (``overload.tenants``); None = the
    #: single-tenant behavior (no per-tenant shares, no quota metering)
    tenants: Optional[TenantPolicy] = None

    @classmethod
    def from_config(cls, m: Any, *, deadline_ms: Optional[float] = None,
                    priority: int = 0) -> Optional["OverloadConfig"]:
        """Parse ``pipeline.overload`` (+ the flat ``deadline_ms``/``priority``
        keys the issue names). Returns None when overload control is fully
        disabled — no mapping, no deadline, and no explicit enable."""
        from arkflow_tpu.utils.duration import parse_duration

        if m is None:
            m = {}
        elif isinstance(m, bool):
            # `overload: false` is an explicit opt-out that beats the
            # deadline_ms auto-enable (the deadline still tags batches)
            m = {"enabled": m}
        elif not isinstance(m, Mapping):
            raise ConfigError("pipeline.overload must be a mapping or boolean")

        # same validation discipline as config.py: a wrong type raises
        # ConfigError naming the key, and bools never pass as numbers
        def _int(key: str, default: int) -> int:
            v = m.get(key, default)
            if isinstance(v, bool) or not isinstance(v, int):
                raise ConfigError(f"overload.{key} must be an int, got {v!r}")
            return v

        def _num(key: str, default: float) -> float:
            v = m.get(key, default)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ConfigError(f"overload.{key} must be a number, got {v!r}")
            return float(v)

        enabled = bool(m.get("enabled", True)) if (m or deadline_ms is not None) else False
        cfg = cls(
            enabled=enabled,
            deadline_ms=deadline_ms,
            priority=priority,
            protect_priority=_int("protect_priority", 1),
            max_window=_int("max_window", 0),
            min_window=_int("min_window", 1),
            headroom=_num("headroom", 0.5),
            target_wait_s=(parse_duration(m["target_wait"])
                           if m.get("target_wait") is not None else 0.1),
            decrease=_num("decrease", 0.5),
            increase=_num("increase", 1.0),
            # None-checked, not truthiness: `interval: 0` legitimately means
            # adjust on every dequeue (and `target_wait: 0` must reach
            # validate() to be rejected, not silently swapped for 0.1)
            interval_s=(parse_duration(m["interval"])
                        if m.get("interval") is not None else 0.1),
            escalate_after=_int("escalate_after", 3),
            tenants=TenantPolicy.from_config(m.get("tenants")),
        )
        cfg.validate()
        return cfg if (cfg.enabled or m) else None

    def validate(self) -> None:
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ConfigError("pipeline.deadline_ms must be > 0")
        if self.min_window < 1:
            raise ConfigError("overload.min_window must be >= 1")
        if self.max_window < 0:
            raise ConfigError("overload.max_window must be >= 0")
        if not (0.0 < self.headroom <= 1.0):
            raise ConfigError("overload.headroom must be in (0, 1]")
        if not (0.0 < self.decrease < 1.0):
            raise ConfigError("overload.decrease must be in (0, 1)")
        if self.increase <= 0:
            raise ConfigError("overload.increase must be > 0")
        if self.target_wait_s <= 0:
            raise ConfigError("overload.target_wait must be > 0")
        if self.interval_s < 0:
            raise ConfigError("overload.interval must be >= 0")
        if self.escalate_after < 0:
            raise ConfigError("overload.escalate_after must be >= 0")
        if self.enabled and self.priority >= self.protect_priority:
            # with the default band protected, admit() never queue-sheds and
            # the brownout floor caps below it — the AIMD window silently
            # becomes a no-op and overload reproduces the latency cliff the
            # controller exists to prevent; refuse rather than no-op
            raise ConfigError(
                f"overload.protect_priority ({self.protect_priority}) must be "
                f"> pipeline.priority ({self.priority}): protecting the "
                "default band disables queue shedding entirely")

    def shard_local(self) -> "OverloadConfig":
        """The view of this config an ingest SHARD process runs: identical
        AIMD window / deadline / priority / fairness knobs, tenant quotas
        stripped (``TenantPolicy.without_quotas``). The parent keeps the
        original config and grants quota tokens exactly once in its shared
        quota plane (:meth:`OverloadController.admit_quota`); per-shard
        windows stay independent — each shard adapts to its own backlog."""
        import dataclasses

        return dataclasses.replace(
            self, tenants=(self.tenants.without_quotas()
                           if self.tenants is not None else None))


class OverloadController:
    """Per-stream admission controller: AIMD window + deadline + priority.

    The stream feeds it observations from the hot loop (queue waits at
    dequeue, pipeline latency after process) and consults :meth:`admit`
    once per batch *before* the worker queue. asyncio runs the stages on
    one thread, so plain arithmetic is race-free (same argument as
    obs/metrics.py).
    """

    #: samples kept for the queue-wait p50 (small: sorting 64 floats per
    #: adjustment interval is noise next to a single Arrow slice)
    SAMPLES = 64

    def __init__(self, cfg: OverloadConfig, name: str = "stream",
                 workers: int = 1, max_window: Optional[int] = None):
        self.cfg = cfg
        self.name = name
        self.workers = max(1, workers)
        # resolve the window bounds onto SELF — cfg is caller-owned (e.g.
        # PipelineConfig.overload, shared across engine restarts) and must
        # keep reading back exactly what the user wrote
        resolved = cfg.max_window
        if resolved <= 0:
            resolved = max_window if max_window is not None else 0
        if resolved <= 0:
            resolved = self.workers * 4
        self.max_window = resolved
        self.min_window = min(cfg.min_window, resolved)

        reg = global_registry()
        labels = {"stream": name}
        self.m_state = reg.gauge(
            "arkflow_overload_state",
            "overload controller state (0 admit, 1 throttle, 2 shed)", labels)
        self.m_window = reg.gauge(
            "arkflow_overload_window", "effective admission window (batches)", labels)
        self.m_shed = {
            r: reg.counter("arkflow_shed_total", "batches shed before the worker queue",
                           {**labels, "reason": r})
            for r in SHED_REASONS
        }
        self.m_paused_s = reg.counter(
            "arkflow_overload_paused_seconds_total",
            "seconds pull-based inputs spent paused by the controller", labels)

        self.window: float = float(self.max_window)
        self.queued = 0  # batches currently in the worker queue
        self.state = STATE_ADMIT
        self._waits: deque[float] = deque(maxlen=self.SAMPLES)
        self._wait_p50 = 0.0  # cached: recomputed once per adjustment interval
        self._step_ewma: Optional[float] = None
        self._last_adjust = 0.0
        self._last_activity = 0.0  # monotonic time of the last enqueue/dequeue
        # (sheds deliberately do NOT count: _idle_recover must fire while
        # a brownout floor is rejecting every batch at admission)
        self._over_intervals = 0  # consecutive over-budget adjustments at min window
        #: admit floor: batches with priority < floor are shed (None = admit all)
        self.admit_floor: Optional[int] = None
        self._capacity_waiters: list = []
        #: tenant label -> _TenantState (lazily populated; bounded by the
        #: policy's max_tracked + configured tenants + the overflow bucket)
        self.tenants: dict[str, _TenantState] = {}
        self.m_window.set(self.window)
        self.m_state.set(self.state)

    # -- tenants -----------------------------------------------------------

    def tenant_label(self, tenant: Optional[str]) -> str:
        """Metric/accounting label for a raw tenant id: untagged batches
        share DEFAULT_TENANT; ids past the cardinality cap collapse into
        OVERFLOW_TENANT (explicitly-configured tenants always keep their
        own slot — the cap protects against unbounded *unknown* ids)."""
        policy = self.cfg.tenants
        if policy is None:
            return DEFAULT_TENANT
        return cap_tenant_label(tenant, self.tenants,
                                reserved=policy.weights,
                                cap=policy.max_tracked)

    def tenant_state(self, tenant: Optional[str]) -> Optional[_TenantState]:
        """State for a (pre- or post-label) tenant id; None when tenant
        accounting is off."""
        policy = self.cfg.tenants
        if policy is None:
            return None
        label = self.tenant_label(tenant)
        ts = self.tenants.get(label)
        if ts is None:
            # the overflow bucket meters at default weight/quota (both
            # fall through weight_of/quota_of for the "__other__" key):
            # the long tail shares one contract rather than each id
            # minting a fresh burst allowance
            ts = self.tenants[label] = _TenantState(
                label, policy.weight_of(label), policy.quota_of(label),
                policy.burst_s, self.name)
        return ts

    def tenant_weight(self, label: str) -> float:
        """Weight for the WDRR queue (label is already capped)."""
        ts = self.tenants.get(label)
        if ts is not None:
            return ts.weight
        policy = self.cfg.tenants
        return policy.weight_of(label) if policy is not None else 1.0

    def meters_tokens(self) -> bool:
        return self.cfg.tenants is not None and self.cfg.tenants.meters_tokens()

    def _fair_share(self, ts: _TenantState) -> int:
        """This tenant's slice of the admission window: window x weight /
        total weight of BACKLOGGED tenants (plus the candidate). A lone
        tenant gets the whole window; contention divides it by weight."""
        total_w = ts.weight if ts.queued == 0 else 0.0
        for s in self.tenants.values():
            if s.queued > 0:
                total_w += s.weight
        share = int(self.window * ts.weight / max(total_w, ts.weight))
        return max(self.cfg.tenants.min_share, share)

    def quota_retry_after_s(self, tenant: Optional[str], rows: float = 1.0,
                            tokens: float = 0.0) -> float:
        """Seconds until the tenant's quota can cover (rows, tokens); 0.0 =
        within quota right now. Does NOT consume — push transports (HTTP)
        use this for 429 + ``Retry-After`` at the socket, and the batch
        consumes at admission."""
        ts = self.tenant_state(tenant)
        if ts is None:
            return 0.0
        wait = 0.0
        if ts.rows_bucket is not None:
            # same capacity-clamped gate as admit(): an over-burst ask is
            # admittable once the bucket fills, so the estimate is finite
            wait = max(wait, ts.rows_bucket.time_until(
                min(rows, ts.rows_bucket.capacity)))
        if ts.tokens_bucket is not None:
            # a tokens-ONLY quota must still gate the socket: callers that
            # can't estimate tokens pre-decode (HTTP) ask for at least one,
            # so a bucket deep in debt answers 429 instead of accepting
            # work that admission will immediately quota-shed
            ask = max(tokens, 1.0)
            wait = max(wait, ts.tokens_bucket.time_until(
                min(ask, ts.tokens_bucket.capacity)))
        return wait

    def observe_tenant_latency(self, tenant: Optional[str], seconds: float) -> None:
        """Delivered-batch e2e latency, tenant-labeled (the soak's per-tenant
        p99 SLO assertion reads this histogram)."""
        ts = self.tenant_state(tenant)
        if ts is not None:
            ts.m_e2e.observe(seconds)

    # -- observations (hot loop) ------------------------------------------

    def on_enqueue(self, tenant: Optional[str] = None) -> None:
        self.queued += 1
        ts = self.tenant_state(tenant)
        if ts is not None:
            ts.queued += 1
            ts.m_admitted.inc()
        self._last_activity = time.monotonic()

    def on_dequeue(self, wait_s: float, now: Optional[float] = None,
                   tenant: Optional[str] = None) -> None:
        """A worker picked a batch up after ``wait_s`` in the queue."""
        if now is None:
            now = time.monotonic()
        self.queued = max(0, self.queued - 1)
        ts = self.tenant_state(tenant)
        if ts is not None:
            ts.queued = max(0, ts.queued - 1)
        self._waits.append(wait_s)
        self._last_activity = time.monotonic()
        self._maybe_adjust(now)
        if self.queued < self.window:
            self._wake_capacity_waiters()

    def observe_step(self, dt_s: float) -> None:
        """Pipeline latency of one batch (the service-time estimate)."""
        if self._step_ewma is None:
            self._step_ewma = dt_s
        else:
            self._step_ewma += 0.2 * (dt_s - self._step_ewma)

    # -- estimates ---------------------------------------------------------

    def queue_wait_p50_s(self) -> float:
        """Cached p50 — recomputed once per adjustment interval
        (_maybe_adjust), NOT per admitted batch; between adjustments the
        Little's-law depth model carries the responsiveness."""
        return self._wait_p50

    def _compute_wait_p50(self) -> float:
        if not self._waits:
            return 0.0
        s = sorted(self._waits)
        return s[len(s) // 2]

    def step_s(self) -> float:
        return self._step_ewma or 0.0

    def predicted_wait_s(self) -> float:
        """Expected queue wait for a batch admitted NOW: the larger of the
        recent p50 (what batches actually waited) and the Little's-law
        estimate from current depth (reacts to a building queue before any
        slow dequeue has been observed)."""
        model = self.queued * self.step_s() / self.workers
        return max(self.queue_wait_p50_s(), model)

    def estimated_drain_s(self) -> float:
        """Time for the current queue to drain at the observed service rate
        — what a 429's ``Retry-After`` promises a well-behaved client."""
        step = self.step_s() or self.cfg.target_wait_s
        return max(0.05, min(60.0, self.queued * step / self.workers))

    def _budget_s(self) -> float:
        if self.cfg.deadline_ms is not None:
            return self.cfg.deadline_ms / 1000.0 * self.cfg.headroom
        return self.cfg.target_wait_s

    # -- AIMD --------------------------------------------------------------

    def _maybe_adjust(self, now: float) -> None:
        if now - self._last_adjust < self.cfg.interval_s:
            return
        self._last_adjust = now
        wait = self._wait_p50 = self._compute_wait_p50()
        budget = self._budget_s()
        if wait > budget:
            at_min = self.window <= self.min_window
            self.window = max(float(self.min_window),
                              self.window * self.cfg.decrease)
            self.state = STATE_SHED
            if at_min and self.cfg.escalate_after:
                # persistent overload the window alone can't absorb:
                # brown out one priority band at a time (strict bands —
                # never past protect_priority, which queue-shedding already
                # exempts and deadline-shedding intentionally does not)
                self._over_intervals += 1
                if self._over_intervals >= self.cfg.escalate_after:
                    self._over_intervals = 0
                    floor = (self.admit_floor if self.admit_floor is not None
                             else self.cfg.priority)
                    self.admit_floor = min(floor + 1, self.cfg.protect_priority)
        else:
            self._over_intervals = 0
            if wait <= budget * 0.5:
                if self.admit_floor is not None:
                    # relax the brownout before re-growing the window: the
                    # shed band gets readmitted at the smallest safe rate
                    floor = self.admit_floor - 1
                    self.admit_floor = None if floor <= self.cfg.priority else floor
                else:
                    self.window = min(float(self.max_window),
                                      self.window + self.cfg.increase)
            self.state = (STATE_ADMIT if self.window >= self.max_window
                          and self.admit_floor is None else STATE_THROTTLE)
        self.m_window.set(self.window)
        self.m_state.set(self.state)
        if self.queued < self.window:
            self._wake_capacity_waiters()

    def _idle_recover(self) -> None:
        """Adjustments are driven by dequeues, so a drained stream would
        otherwise report SHED forever. When the queue has been empty with no
        enqueue/dequeue for a few intervals, the burst's wait samples
        predict nothing about a batch entering an empty queue: drop them
        and let the state reflect the present. Crucially this also steps a
        brownout ``admit_floor`` down one band per idle period — admission
        sheds are NOT activity, so a floor that sheds 100% of traffic at
        admission (queue permanently empty, no dequeues to drive
        ``_maybe_adjust``) relaxes here instead of sticking forever; if the
        readmitted band re-overloads, escalation re-engages. Consulted
        lazily from admit()/should_pause()/report()."""
        if self.queued != 0 or self.state != STATE_SHED:
            return
        now = time.monotonic()
        if now - self._last_activity < max(3 * self.cfg.interval_s, 0.5):
            return
        self._waits.clear()
        self._wait_p50 = 0.0
        self._over_intervals = 0
        if self.admit_floor is not None:
            floor = self.admit_floor - 1
            self.admit_floor = None if floor <= self.cfg.priority else floor
        # refreshing the idle clock paces successive relax steps: the next
        # band readmits only after another full idle period
        self._last_activity = now
        self.state = (STATE_ADMIT if self.window >= self.max_window
                      and self.admit_floor is None else STATE_THROTTLE)
        self.m_state.set(self.state)

    # -- admission ---------------------------------------------------------

    def admit(self, priority: int, remaining_ms: Optional[float],
              tenant: Optional[str] = None, rows: float = 1.0,
              tokens: float = 0.0) -> Optional[str]:
        """Admission verdict for one batch: None to admit, else the shed
        reason (already counted in ``arkflow_shed_total``).

        Order matters: a stale batch is shed on deadline even in a
        protected band (finishing it is strictly worse than dropping —
        the caller already gave up); quota sheds apply regardless of
        priority (the quota is the tenant's *contract*, not a congestion
        response); the brownout floor and the queue window/fair-share
        only apply below ``protect_priority``.
        """
        if not self.cfg.enabled:
            return None
        self._idle_recover()
        ts = self.tenant_state(tenant)
        if remaining_ms is not None:
            need_ms = (self.predicted_wait_s() + self.step_s()) * 1000.0
            if remaining_ms <= need_ms:
                return self._shed("deadline", ts)
        if self.admit_floor is not None and priority < self.admit_floor:
            return self._shed("priority", ts)
        if priority < self.cfg.protect_priority:
            if self.queued >= int(self.window):
                return self._shed("queue", ts)
            if ts is not None and ts.queued >= self._fair_share(ts):
                # over its weighted share of the window while others are
                # backlogged: this tenant queues behind its OWN backlog
                # (nack -> broker redelivery) instead of everyone else's
                return self._shed("queue", ts)
        if ts is not None:
            # quota LAST, so a batch shed on queue/priority (which will be
            # redelivered and re-offered) never burns quota tokens it
            # didn't use — a tenant at its fair-share ceiling must still
            # achieve its contracted rate once capacity frees up. Both
            # axes checked before either consumes, so a tokens-only
            # rejection doesn't silently burn the row allowance either.
            # The admission GATE clamps at bucket capacity — a batch larger
            # than the burst allowance (big broker fetch, tiny quota) waits
            # for a full bucket instead of time_until() returning inf and
            # the batch nack-looping forever as an unadmittable poison
            # pill — but the CHARGE is the real cost, taken as debt
            # (negative balance): the refill must pay the whole batch off
            # before the tenant admits again, so batching can't ride the
            # clamp past the contracted rate.
            reason = self._check_quota(ts, rows, tokens)
            if reason is not None:
                return reason
        return None

    def _check_quota(self, ts: _TenantState, rows: float,
                     tokens: float) -> Optional[str]:
        """The quota gate + charge, shared by :meth:`admit` and the sharded
        plane's :meth:`admit_quota`: both axes gated (capacity-clamped)
        before either drains, then the REAL cost is charged as debt."""
        if ts.rows_bucket is not None and ts.rows_bucket.time_until(
                min(rows, ts.rows_bucket.capacity)) > 0:
            return self._shed("quota", ts)
        if (tokens > 0 and ts.tokens_bucket is not None
                and ts.tokens_bucket.time_until(
                    min(tokens, ts.tokens_bucket.capacity)) > 0):
            return self._shed("quota", ts)
        if rows > 0 and ts.rows_bucket is not None:
            ts.rows_bucket.drain(rows)
        if tokens > 0 and ts.tokens_bucket is not None:
            ts.tokens_bucket.drain(tokens)
        return None

    def admit_quota(self, tenant: Optional[str] = None, rows: float = 1.0,
                    tokens: float = 0.0) -> Optional[str]:
        """Quota-ONLY admission: the parent side of the sharded-ingest
        split. The parent process owns every tenant's TokenBuckets (the
        shared quota plane — granted exactly once, never N-times across N
        shards) and consults this before routing a batch to its shard;
        window/deadline/priority/fair-share admission then runs INSIDE the
        owning shard against its local backlog, quota-stripped
        (:meth:`OverloadConfig.shard_local`). Returns None to admit, else
        ``"quota"`` (already counted in ``arkflow_shed_total``)."""
        if not self.cfg.enabled:
            return None
        ts = self.tenant_state(tenant)
        if ts is None:
            return None
        return self._check_quota(ts, rows, tokens)

    def expire(self, tenant: Optional[str] = None) -> str:
        """Count a batch that went stale WHILE queued (the worker's
        dequeue-side deadline check). Admission bounds the *predicted* wait;
        this bounds the actual one — together they guarantee every processed
        batch still had budget when its step started, which is what makes
        the soak's delivered-p99 <= 2x deadline bound provable."""
        return self._shed("deadline", self.tenant_state(tenant))

    def _shed(self, reason: str, ts: Optional[_TenantState] = None) -> str:
        self.m_shed[reason].inc()
        if ts is not None:
            ts.count_shed(reason)
        self.state = STATE_SHED
        self.m_state.set(self.state)
        return reason

    # -- cooperative backpressure -----------------------------------------

    def should_pause(self) -> bool:
        """Pull-based sources consult this before fetching: True while the
        controller is shedding AND the queue is at/over the window —
        pausing consumption beats fetch-then-nack (the broker keeps the
        backlog; nothing churns through the requeue path)."""
        self._idle_recover()
        return (self.cfg.enabled and self.state == STATE_SHED
                and self.queued >= int(self.window))

    def should_reject(self) -> bool:
        """Push-based servers (HTTP) consult this per request: they cannot
        pause remote clients, so they reject with 429 + Retry-After."""
        return self.should_pause()

    def retry_after_s(self) -> float:
        return self.estimated_drain_s()

    async def wait_capacity(self, timeout: float = 0.25) -> None:
        """Bounded wait for the queue to drain below the window (pause
        loop); wakes early the moment a dequeue frees capacity."""
        import asyncio

        ev = asyncio.Event()
        self._capacity_waiters.append(ev)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            try:
                self._capacity_waiters.remove(ev)
            except ValueError:
                pass

    def _wake_capacity_waiters(self) -> None:
        for ev in self._capacity_waiters:
            ev.set()

    # -- introspection -----------------------------------------------------

    def signals(self) -> dict:
        """The compact observation bundle the shape tuner samples (a strict
        subset of :meth:`report`, cheap enough for every cycle): the step
        EWMA the deadline math rides on, the live AIMD window, and the
        queue-wait p50 the window adapts against."""
        return {
            "step_ewma_ms": round(self.step_s() * 1000.0, 3),
            "window": int(self.window),
            "max_window": self.max_window,
            "queued": self.queued,
            "queue_wait_p50_ms": round(self.queue_wait_p50_s() * 1000.0, 3),
        }

    def report(self) -> dict:
        """Controller snapshot for the engine's ``/health`` payload."""
        self._idle_recover()
        out = {
            "state": _STATE_NAMES.get(self.state, str(self.state)),
            "window": int(self.window),
            "max_window": self.max_window,
            "queued": self.queued,
            "admit_floor": self.admit_floor,
            "deadline_ms": self.cfg.deadline_ms,
            "queue_wait_p50_ms": round(self.queue_wait_p50_s() * 1000.0, 3),
            "step_ewma_ms": round(self.step_s() * 1000.0, 3),
            "estimated_drain_s": round(self.estimated_drain_s(), 3),
            "shed": {r: c.value for r, c in self.m_shed.items()},
            "paused_s": round(self.m_paused_s.value, 3),
        }
        if self.tenants:
            out["tenants"] = {label: ts.report()
                              for label, ts in sorted(self.tenants.items())}
        return out


class FairQueue:
    """Weighted deficit-round-robin stage queue keyed by work-item tenant.

    Drop-in for the ``asyncio.Queue`` between input/buffer and the workers
    (coroutine ``put``/``get``): items carrying a ``tenant`` attribute land
    in that tenant's FIFO lane; ``get`` serves lanes by deficit round robin
    with quantum = tenant weight (``OverloadController.tenant_weight``), so
    a premium tenant drains proportionally faster and a backlogged tenant's
    admitted batches cannot delay anyone else's dequeues. Items WITHOUT a
    tenant attribute (the stream's ``_Done`` sentinels) ride a control lane
    served only when every tenant lane is empty — exactly the FIFO ordering
    guarantee the drain path relies on. ``maxsize`` bounds tenant items
    (puts block, like the queue it replaces); control items are exempt so
    shutdown can never deadlock on a full queue.

    Single-event-loop discipline like the rest of the stream runtime: one
    ``asyncio.Condition`` guards all state; no thread-safety is claimed.
    """

    def __init__(self, controller: "OverloadController", maxsize: int):
        import asyncio

        self._ctrl = controller
        self._maxsize = max(1, maxsize)
        self._lanes: dict[str, deque] = {}
        self._ring: deque[str] = deque()  # backlogged lanes, service order
        self._deficit: dict[str, float] = {}
        self._control: deque = deque()
        self._size = 0
        self._cond = asyncio.Condition()

    def qsize(self) -> int:
        return self._size + len(self._control)

    async def put(self, item: Any) -> None:
        tenant = getattr(item, "tenant", None)
        async with self._cond:
            if tenant is None:
                self._control.append(item)
                self._cond.notify_all()
                return
            while self._size >= self._maxsize:
                await self._cond.wait()
            lane = self._lanes.get(tenant)
            if lane is None:
                lane = self._lanes[tenant] = deque()
            if not lane:
                self._ring.append(tenant)
                self._deficit.setdefault(tenant, 0.0)
            lane.append(item)
            self._size += 1
            self._cond.notify_all()

    async def get(self) -> Any:
        async with self._cond:
            while True:
                item = self._pop_locked()
                if item is not None:
                    self._cond.notify_all()  # wake writers blocked on maxsize
                    return item
                await self._cond.wait()

    def _pop_locked(self) -> Any:
        while self._ring:
            t = self._ring[0]
            lane = self._lanes.get(t)
            if not lane:
                self._ring.popleft()
                self._deficit[t] = 0.0
                continue
            if self._deficit[t] < 1.0:
                # one quantum per visit; a sub-1.0 weight accumulates over
                # rotations (every full ring pass adds >= 0.01, so the scan
                # is bounded), a weight-8 tenant serves 8 items per visit
                self._deficit[t] += max(0.01, self._ctrl.tenant_weight(t))
                if self._deficit[t] < 1.0:
                    self._ring.rotate(-1)
                    continue
            self._deficit[t] -= 1.0
            item = lane.popleft()
            self._size -= 1
            if not lane:
                self._ring.popleft()
                self._deficit[t] = 0.0
            elif self._deficit[t] < 1.0:
                self._ring.rotate(-1)
            return item
        if self._control:
            return self._control.popleft()
        return None


def attach_overload(component: Any, controller: Optional[OverloadController]) -> None:
    """Hand the controller to an input that can use it (HTTP's 429 path,
    websocket's control frames), walking fault/decorator wrappers via their
    ``_inner`` chain so chaos wrapping doesn't hide the real source."""
    if controller is None:
        return
    seen = set()
    node = component
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        hook = getattr(node, "attach_overload_controller", None)
        if hook is not None:
            hook(controller)
        node = getattr(node, "_inner", None)


def input_pauses_on_overload(component: Any) -> bool:
    """Whether the (possibly wrapper-nested) input opts into cooperative
    pause — pull-based brokers do; push servers and the unit-test memory
    source (unless opted in) do not."""
    seen = set()
    node = component
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        flag = getattr(node, "pause_on_overload", None)
        if flag is not None and not callable(flag):
            if flag:
                return True
        node = getattr(node, "_inner", None)
    return False
