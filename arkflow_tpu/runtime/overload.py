"""Overload control: deadline-aware admission, AIMD queue windows, shedding.

Once offered load exceeds device throughput, an engine that admits every
batch turns a traffic burst into unbounded queue wait (BENCH_r05's
``saturated_queueing_p99_ms`` ≈ 10.7s) and eventual memory pressure. In the
latency-bound serving regime (Answer Fast / TSP, PAPERS.md) finishing a
stale request is strictly worse than shedding it up front, so the engine
protects itself from its own traffic with three cooperating mechanisms, all
owned by the per-stream :class:`OverloadController`:

1. **Deadline-aware admission** — each batch carries a remaining TTL
   (``pipeline.deadline_ms`` measured from ``__meta_ingest_time``, or an
   absolute ``__meta_ext_deadline_ms`` column stamped upstream). A batch
   whose remaining budget cannot cover the *predicted* queue wait + step
   time is shed before the worker queue — nacked for redelivery or routed
   to ``error_output`` tagged ``overloaded``, never silently dropped.
2. **Adaptive admission window (AIMD)** — the effective worker-queue window
   shrinks multiplicatively when observed queue wait trends above the
   deadline budget and re-grows additively on recovery, replacing the fixed
   ``thread_num * 4`` depth as the only limit. Batches beyond the window are
   shed (``reason=queue``) instead of queued into the latency cliff.
3. **Strict-priority bands** — ``pipeline.priority`` (or a per-batch
   ``__meta_ext_priority`` column) splits traffic into integer bands.
   Bands at/above ``protect_priority`` are never queue-shed (health probes
   and premium traffic survive brownouts); under *persistent* overload at
   the minimum window the admit floor escalates one band at a time
   (``reason=priority``) and relaxes on recovery.

Cooperative backpressure rides on the controller's state: pull-based inputs
(kafka/redis/nats — anything marked ``pause_on_overload``) pause consumption
instead of fetching-then-nacking, and the HTTP input rejects with 429 +
``Retry-After`` computed from the controller's estimated drain time.

Observability: ``arkflow_overload_state`` (0 admit / 1 throttle / 2 shed),
``arkflow_overload_window``, ``arkflow_shed_total{reason=deadline|queue|
priority}``, ``arkflow_overload_paused_seconds_total``; the engine's
``/health`` embeds :meth:`OverloadController.report` per stream.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Mapping, Optional

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.obs import global_registry

#: ``arkflow_overload_state`` gauge values
STATE_ADMIT = 0  #: window at max, queue wait within budget
STATE_THROTTLE = 1  #: window shrunk, recovering additively
STATE_SHED = 2  #: queue wait over budget; admission actively shedding

_STATE_NAMES = {STATE_ADMIT: "admit", STATE_THROTTLE: "throttle", STATE_SHED: "shed"}

SHED_REASONS = ("deadline", "queue", "priority")


@dataclass
class OverloadConfig:
    """Knobs for the per-stream overload controller (``pipeline.overload``).

    ``enabled`` defaults to True whenever ``pipeline.deadline_ms`` is set —
    configuring a deadline without admission control would only measure the
    overload, not prevent it. ``max_window`` is filled by the stream from
    the effective worker-queue size when left at 0.
    """

    enabled: bool = False
    #: per-batch TTL measured from ingest time; None = only absolute
    #: ``__meta_ext_deadline_ms`` columns are deadline-enforced
    deadline_ms: Optional[float] = None
    #: default priority band for batches without a priority column
    priority: int = 0
    #: bands >= this are never queue-shed (strict-priority protection)
    protect_priority: int = 1
    max_window: int = 0  # 0 -> stream fills with its queue size
    min_window: int = 1
    #: fraction of the deadline budget the p50 queue wait may consume before
    #: the AIMD controller starts shrinking the window
    headroom: float = 0.5
    #: absolute queue-wait target (seconds) when no deadline is configured
    target_wait_s: float = 0.1
    decrease: float = 0.5  # multiplicative window shrink factor
    increase: float = 1.0  # additive window re-growth per healthy interval
    interval_s: float = 0.1  # min spacing between AIMD adjustments
    #: consecutive over-budget intervals at min_window before the admit
    #: floor escalates one priority band (brownout); 0 disables escalation
    escalate_after: int = 3

    @classmethod
    def from_config(cls, m: Any, *, deadline_ms: Optional[float] = None,
                    priority: int = 0) -> Optional["OverloadConfig"]:
        """Parse ``pipeline.overload`` (+ the flat ``deadline_ms``/``priority``
        keys the issue names). Returns None when overload control is fully
        disabled — no mapping, no deadline, and no explicit enable."""
        from arkflow_tpu.utils.duration import parse_duration

        if m is None:
            m = {}
        elif isinstance(m, bool):
            # `overload: false` is an explicit opt-out that beats the
            # deadline_ms auto-enable (the deadline still tags batches)
            m = {"enabled": m}
        elif not isinstance(m, Mapping):
            raise ConfigError("pipeline.overload must be a mapping or boolean")

        # same validation discipline as config.py: a wrong type raises
        # ConfigError naming the key, and bools never pass as numbers
        def _int(key: str, default: int) -> int:
            v = m.get(key, default)
            if isinstance(v, bool) or not isinstance(v, int):
                raise ConfigError(f"overload.{key} must be an int, got {v!r}")
            return v

        def _num(key: str, default: float) -> float:
            v = m.get(key, default)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ConfigError(f"overload.{key} must be a number, got {v!r}")
            return float(v)

        enabled = bool(m.get("enabled", True)) if (m or deadline_ms is not None) else False
        cfg = cls(
            enabled=enabled,
            deadline_ms=deadline_ms,
            priority=priority,
            protect_priority=_int("protect_priority", 1),
            max_window=_int("max_window", 0),
            min_window=_int("min_window", 1),
            headroom=_num("headroom", 0.5),
            target_wait_s=(parse_duration(m["target_wait"])
                           if m.get("target_wait") is not None else 0.1),
            decrease=_num("decrease", 0.5),
            increase=_num("increase", 1.0),
            # None-checked, not truthiness: `interval: 0` legitimately means
            # adjust on every dequeue (and `target_wait: 0` must reach
            # validate() to be rejected, not silently swapped for 0.1)
            interval_s=(parse_duration(m["interval"])
                        if m.get("interval") is not None else 0.1),
            escalate_after=_int("escalate_after", 3),
        )
        cfg.validate()
        return cfg if (cfg.enabled or m) else None

    def validate(self) -> None:
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ConfigError("pipeline.deadline_ms must be > 0")
        if self.min_window < 1:
            raise ConfigError("overload.min_window must be >= 1")
        if self.max_window < 0:
            raise ConfigError("overload.max_window must be >= 0")
        if not (0.0 < self.headroom <= 1.0):
            raise ConfigError("overload.headroom must be in (0, 1]")
        if not (0.0 < self.decrease < 1.0):
            raise ConfigError("overload.decrease must be in (0, 1)")
        if self.increase <= 0:
            raise ConfigError("overload.increase must be > 0")
        if self.target_wait_s <= 0:
            raise ConfigError("overload.target_wait must be > 0")
        if self.interval_s < 0:
            raise ConfigError("overload.interval must be >= 0")
        if self.escalate_after < 0:
            raise ConfigError("overload.escalate_after must be >= 0")
        if self.enabled and self.priority >= self.protect_priority:
            # with the default band protected, admit() never queue-sheds and
            # the brownout floor caps below it — the AIMD window silently
            # becomes a no-op and overload reproduces the latency cliff the
            # controller exists to prevent; refuse rather than no-op
            raise ConfigError(
                f"overload.protect_priority ({self.protect_priority}) must be "
                f"> pipeline.priority ({self.priority}): protecting the "
                "default band disables queue shedding entirely")


class OverloadController:
    """Per-stream admission controller: AIMD window + deadline + priority.

    The stream feeds it observations from the hot loop (queue waits at
    dequeue, pipeline latency after process) and consults :meth:`admit`
    once per batch *before* the worker queue. asyncio runs the stages on
    one thread, so plain arithmetic is race-free (same argument as
    obs/metrics.py).
    """

    #: samples kept for the queue-wait p50 (small: sorting 64 floats per
    #: adjustment interval is noise next to a single Arrow slice)
    SAMPLES = 64

    def __init__(self, cfg: OverloadConfig, name: str = "stream",
                 workers: int = 1, max_window: Optional[int] = None):
        self.cfg = cfg
        self.name = name
        self.workers = max(1, workers)
        # resolve the window bounds onto SELF — cfg is caller-owned (e.g.
        # PipelineConfig.overload, shared across engine restarts) and must
        # keep reading back exactly what the user wrote
        resolved = cfg.max_window
        if resolved <= 0:
            resolved = max_window if max_window is not None else 0
        if resolved <= 0:
            resolved = self.workers * 4
        self.max_window = resolved
        self.min_window = min(cfg.min_window, resolved)

        reg = global_registry()
        labels = {"stream": name}
        self.m_state = reg.gauge(
            "arkflow_overload_state",
            "overload controller state (0 admit, 1 throttle, 2 shed)", labels)
        self.m_window = reg.gauge(
            "arkflow_overload_window", "effective admission window (batches)", labels)
        self.m_shed = {
            r: reg.counter("arkflow_shed_total", "batches shed before the worker queue",
                           {**labels, "reason": r})
            for r in SHED_REASONS
        }
        self.m_paused_s = reg.counter(
            "arkflow_overload_paused_seconds_total",
            "seconds pull-based inputs spent paused by the controller", labels)

        self.window: float = float(self.max_window)
        self.queued = 0  # batches currently in the worker queue
        self.state = STATE_ADMIT
        self._waits: deque[float] = deque(maxlen=self.SAMPLES)
        self._wait_p50 = 0.0  # cached: recomputed once per adjustment interval
        self._step_ewma: Optional[float] = None
        self._last_adjust = 0.0
        self._last_activity = 0.0  # monotonic time of the last enqueue/dequeue
        # (sheds deliberately do NOT count: _idle_recover must fire while
        # a brownout floor is rejecting every batch at admission)
        self._over_intervals = 0  # consecutive over-budget adjustments at min window
        #: admit floor: batches with priority < floor are shed (None = admit all)
        self.admit_floor: Optional[int] = None
        self._capacity_waiters: list = []
        self.m_window.set(self.window)
        self.m_state.set(self.state)

    # -- observations (hot loop) ------------------------------------------

    def on_enqueue(self) -> None:
        self.queued += 1
        self._last_activity = time.monotonic()

    def on_dequeue(self, wait_s: float, now: Optional[float] = None) -> None:
        """A worker picked a batch up after ``wait_s`` in the queue."""
        if now is None:
            now = time.monotonic()
        self.queued = max(0, self.queued - 1)
        self._waits.append(wait_s)
        self._last_activity = time.monotonic()
        self._maybe_adjust(now)
        if self.queued < self.window:
            self._wake_capacity_waiters()

    def observe_step(self, dt_s: float) -> None:
        """Pipeline latency of one batch (the service-time estimate)."""
        if self._step_ewma is None:
            self._step_ewma = dt_s
        else:
            self._step_ewma += 0.2 * (dt_s - self._step_ewma)

    # -- estimates ---------------------------------------------------------

    def queue_wait_p50_s(self) -> float:
        """Cached p50 — recomputed once per adjustment interval
        (_maybe_adjust), NOT per admitted batch; between adjustments the
        Little's-law depth model carries the responsiveness."""
        return self._wait_p50

    def _compute_wait_p50(self) -> float:
        if not self._waits:
            return 0.0
        s = sorted(self._waits)
        return s[len(s) // 2]

    def step_s(self) -> float:
        return self._step_ewma or 0.0

    def predicted_wait_s(self) -> float:
        """Expected queue wait for a batch admitted NOW: the larger of the
        recent p50 (what batches actually waited) and the Little's-law
        estimate from current depth (reacts to a building queue before any
        slow dequeue has been observed)."""
        model = self.queued * self.step_s() / self.workers
        return max(self.queue_wait_p50_s(), model)

    def estimated_drain_s(self) -> float:
        """Time for the current queue to drain at the observed service rate
        — what a 429's ``Retry-After`` promises a well-behaved client."""
        step = self.step_s() or self.cfg.target_wait_s
        return max(0.05, min(60.0, self.queued * step / self.workers))

    def _budget_s(self) -> float:
        if self.cfg.deadline_ms is not None:
            return self.cfg.deadline_ms / 1000.0 * self.cfg.headroom
        return self.cfg.target_wait_s

    # -- AIMD --------------------------------------------------------------

    def _maybe_adjust(self, now: float) -> None:
        if now - self._last_adjust < self.cfg.interval_s:
            return
        self._last_adjust = now
        wait = self._wait_p50 = self._compute_wait_p50()
        budget = self._budget_s()
        if wait > budget:
            at_min = self.window <= self.min_window
            self.window = max(float(self.min_window),
                              self.window * self.cfg.decrease)
            self.state = STATE_SHED
            if at_min and self.cfg.escalate_after:
                # persistent overload the window alone can't absorb:
                # brown out one priority band at a time (strict bands —
                # never past protect_priority, which queue-shedding already
                # exempts and deadline-shedding intentionally does not)
                self._over_intervals += 1
                if self._over_intervals >= self.cfg.escalate_after:
                    self._over_intervals = 0
                    floor = (self.admit_floor if self.admit_floor is not None
                             else self.cfg.priority)
                    self.admit_floor = min(floor + 1, self.cfg.protect_priority)
        else:
            self._over_intervals = 0
            if wait <= budget * 0.5:
                if self.admit_floor is not None:
                    # relax the brownout before re-growing the window: the
                    # shed band gets readmitted at the smallest safe rate
                    floor = self.admit_floor - 1
                    self.admit_floor = None if floor <= self.cfg.priority else floor
                else:
                    self.window = min(float(self.max_window),
                                      self.window + self.cfg.increase)
            self.state = (STATE_ADMIT if self.window >= self.max_window
                          and self.admit_floor is None else STATE_THROTTLE)
        self.m_window.set(self.window)
        self.m_state.set(self.state)
        if self.queued < self.window:
            self._wake_capacity_waiters()

    def _idle_recover(self) -> None:
        """Adjustments are driven by dequeues, so a drained stream would
        otherwise report SHED forever. When the queue has been empty with no
        enqueue/dequeue for a few intervals, the burst's wait samples
        predict nothing about a batch entering an empty queue: drop them
        and let the state reflect the present. Crucially this also steps a
        brownout ``admit_floor`` down one band per idle period — admission
        sheds are NOT activity, so a floor that sheds 100% of traffic at
        admission (queue permanently empty, no dequeues to drive
        ``_maybe_adjust``) relaxes here instead of sticking forever; if the
        readmitted band re-overloads, escalation re-engages. Consulted
        lazily from admit()/should_pause()/report()."""
        if self.queued != 0 or self.state != STATE_SHED:
            return
        now = time.monotonic()
        if now - self._last_activity < max(3 * self.cfg.interval_s, 0.5):
            return
        self._waits.clear()
        self._wait_p50 = 0.0
        self._over_intervals = 0
        if self.admit_floor is not None:
            floor = self.admit_floor - 1
            self.admit_floor = None if floor <= self.cfg.priority else floor
        # refreshing the idle clock paces successive relax steps: the next
        # band readmits only after another full idle period
        self._last_activity = now
        self.state = (STATE_ADMIT if self.window >= self.max_window
                      and self.admit_floor is None else STATE_THROTTLE)
        self.m_state.set(self.state)

    # -- admission ---------------------------------------------------------

    def admit(self, priority: int, remaining_ms: Optional[float]) -> Optional[str]:
        """Admission verdict for one batch: None to admit, else the shed
        reason (already counted in ``arkflow_shed_total``).

        Order matters: a stale batch is shed on deadline even in a
        protected band (finishing it is strictly worse than dropping —
        the caller already gave up); the brownout floor and the queue
        window only apply below ``protect_priority``.
        """
        if not self.cfg.enabled:
            return None
        self._idle_recover()
        if remaining_ms is not None:
            need_ms = (self.predicted_wait_s() + self.step_s()) * 1000.0
            if remaining_ms <= need_ms:
                return self._shed("deadline")
        if self.admit_floor is not None and priority < self.admit_floor:
            return self._shed("priority")
        if self.queued >= int(self.window) and priority < self.cfg.protect_priority:
            return self._shed("queue")
        return None

    def expire(self) -> str:
        """Count a batch that went stale WHILE queued (the worker's
        dequeue-side deadline check). Admission bounds the *predicted* wait;
        this bounds the actual one — together they guarantee every processed
        batch still had budget when its step started, which is what makes
        the soak's delivered-p99 <= 2x deadline bound provable."""
        return self._shed("deadline")

    def _shed(self, reason: str) -> str:
        self.m_shed[reason].inc()
        self.state = STATE_SHED
        self.m_state.set(self.state)
        return reason

    # -- cooperative backpressure -----------------------------------------

    def should_pause(self) -> bool:
        """Pull-based sources consult this before fetching: True while the
        controller is shedding AND the queue is at/over the window —
        pausing consumption beats fetch-then-nack (the broker keeps the
        backlog; nothing churns through the requeue path)."""
        self._idle_recover()
        return (self.cfg.enabled and self.state == STATE_SHED
                and self.queued >= int(self.window))

    def should_reject(self) -> bool:
        """Push-based servers (HTTP) consult this per request: they cannot
        pause remote clients, so they reject with 429 + Retry-After."""
        return self.should_pause()

    def retry_after_s(self) -> float:
        return self.estimated_drain_s()

    async def wait_capacity(self, timeout: float = 0.25) -> None:
        """Bounded wait for the queue to drain below the window (pause
        loop); wakes early the moment a dequeue frees capacity."""
        import asyncio

        ev = asyncio.Event()
        self._capacity_waiters.append(ev)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            try:
                self._capacity_waiters.remove(ev)
            except ValueError:
                pass

    def _wake_capacity_waiters(self) -> None:
        for ev in self._capacity_waiters:
            ev.set()

    # -- introspection -----------------------------------------------------

    def report(self) -> dict:
        """Controller snapshot for the engine's ``/health`` payload."""
        self._idle_recover()
        return {
            "state": _STATE_NAMES.get(self.state, str(self.state)),
            "window": int(self.window),
            "max_window": self.max_window,
            "queued": self.queued,
            "admit_floor": self.admit_floor,
            "deadline_ms": self.cfg.deadline_ms,
            "queue_wait_p50_ms": round(self.queue_wait_p50_s() * 1000.0, 3),
            "step_ewma_ms": round(self.step_s() * 1000.0, 3),
            "estimated_drain_s": round(self.estimated_drain_s(), 3),
            "shed": {r: c.value for r, c in self.m_shed.items()},
            "paused_s": round(self.m_paused_s.value, 3),
        }


def attach_overload(component: Any, controller: Optional[OverloadController]) -> None:
    """Hand the controller to an input that can use it (HTTP's 429 path,
    websocket's control frames), walking fault/decorator wrappers via their
    ``_inner`` chain so chaos wrapping doesn't hide the real source."""
    if controller is None:
        return
    seen = set()
    node = component
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        hook = getattr(node, "attach_overload_controller", None)
        if hook is not None:
            hook(controller)
        node = getattr(node, "_inner", None)


def input_pauses_on_overload(component: Any) -> bool:
    """Whether the (possibly wrapper-nested) input opts into cooperative
    pause — pull-based brokers do; push servers and the unit-test memory
    source (unless opted in) do not."""
    seen = set()
    node = component
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        flag = getattr(node, "pause_on_overload", None)
        if flag is not None and not callable(flag):
            if flag:
                return True
        node = getattr(node, "_inner", None)
    return False
