from arkflow_tpu.runtime.pipeline import Pipeline  # noqa: F401
from arkflow_tpu.runtime.overload import OverloadConfig, OverloadController  # noqa: F401
from arkflow_tpu.runtime.stream import Stream, build_stream  # noqa: F401
from arkflow_tpu.runtime.engine import Engine  # noqa: F401
