"""CLI: ``python -m arkflow_tpu --config pipeline.yaml [--validate]``.

Mirrors the reference CLI (ref: crates/arkflow-core/src/cli/mod.rs:22-147):
``--config`` + ``--validate`` flags and logging initialisation with
level / optional file / JSON-or-plain format from the ``logging`` config
section.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import time
from typing import Optional, Sequence

from arkflow_tpu.config import EngineConfig, LoggingConfig
from arkflow_tpu.errors import ConfigError

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        body = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname.lower(),
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            body["exception"] = self.formatException(record.exc_info)
        return json.dumps(body)


def init_logging(cfg: LoggingConfig) -> None:
    level = _LEVELS.get(cfg.level, logging.INFO)
    root = logging.getLogger()
    root.setLevel(level)
    root.handlers.clear()
    handler: logging.Handler
    handler = logging.FileHandler(cfg.file_path) if cfg.file_path else logging.StreamHandler(sys.stderr)
    if cfg.format == "json":
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S")
        )
    root.addHandler(handler)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="arkflow-tpu", description="TPU-native streaming dataflow engine"
    )
    parser.add_argument("-c", "--config", help="path to YAML/JSON/TOML config")
    parser.add_argument(
        "-v", "--validate", action="store_true", help="validate the config and exit"
    )
    parser.add_argument(
        "--worker", action="store_true",
        help="run a remote-execution flight worker instead of an engine "
             "(the distributed scan/SQL tier; see connect/flight.py)")
    parser.add_argument(
        "--cluster-worker", action="store_true",
        help="run a device-tier serving worker instead of an engine: hosts "
             "the processor chain of --config behind the cluster 'infer' "
             "action (the disaggregated serving tier; see runtime/cluster.py)")
    parser.add_argument(
        "--host", default="127.0.0.1",
        help="worker bind host (default loopback; binding wider exposes "
             "file reads — pair with --allow-path)")
    parser.add_argument("--port", type=int, default=50051, help="worker port")
    parser.add_argument(
        "--allow-path", action="append", default=None,
        help="restrict worker scans to these path prefixes (repeatable)")
    parser.add_argument(
        "--worker-id", default=None,
        help="cluster worker: stable identity reported to the ingest tier "
             "(default hostname-pid)")
    parser.add_argument(
        "--max-frame", type=int, default=None,
        help="cap in bytes on a single wire frame (both worker kinds; "
             "default 1 GiB — an oversized length header fails loudly "
             "instead of buffering gigabytes)")
    args = parser.parse_args(argv)

    if args.worker and args.cluster_worker:
        parser.error("--worker and --cluster-worker are mutually exclusive")
    if args.max_frame is not None and args.max_frame < 1024:
        # same floor the yaml `worker.max_frame` key enforces — a cap below
        # the smallest request frame would refuse every call
        parser.error("--max-frame must be >= 1024 bytes")
    if args.worker:
        from arkflow_tpu.connect.flight import DEFAULT_MAX_FRAME, FlightWorker

        init_logging(LoggingConfig())
        if args.host not in ("127.0.0.1", "localhost") and not args.allow_path:
            print("refusing to bind a worker beyond loopback without "
                  "--allow-path (it would serve arbitrary readable files)",
                  file=sys.stderr)
            return 2
        worker = FlightWorker(args.host, args.port, allow_paths=args.allow_path,
                              max_frame=args.max_frame or DEFAULT_MAX_FRAME)
        try:
            asyncio.run(worker.serve_forever())
        except KeyboardInterrupt:
            pass
        return 0
    if args.cluster_worker:
        import yaml

        from arkflow_tpu.runtime.cluster import run_worker

        if not args.config:
            parser.error("--cluster-worker requires --config (the worker's "
                         "processor chain)")
        try:
            from pathlib import Path

            raw = yaml.safe_load(Path(args.config).read_text()) or {}
            logging_cfg = LoggingConfig.from_mapping(raw.get("logging", {}) or {}) \
                if isinstance(raw, dict) else LoggingConfig()
            init_logging(logging_cfg)
            asyncio.run(run_worker(raw, host=args.host, port=args.port,
                                   worker_id=args.worker_id,
                                   max_frame=args.max_frame))
        except KeyboardInterrupt:
            pass
        except (OSError, yaml.YAMLError, ConfigError) as e:
            # missing/unreadable/malformed config gets the same clean exit-2
            # path the engine mode provides, not a raw traceback
            print(f"config error: {e}", file=sys.stderr)
            return 2
        return 0
    if not args.config:
        parser.error("--config is required (or use --worker / --cluster-worker)")

    try:
        cfg = EngineConfig.from_file(args.config)
    except ConfigError as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2

    if args.validate:
        problems = cfg.validate_components()
        if problems:
            print("\n".join(problems), file=sys.stderr)
            return 2
        print(f"config OK: {len(cfg.streams)} stream(s)")
        return 0

    init_logging(cfg.logging)
    from arkflow_tpu.runtime.engine import Engine

    engine = Engine(cfg)
    try:
        asyncio.run(engine.run())
    except KeyboardInterrupt:
        pass
    except ConfigError as e:  # component build errors surface cleanly
        print(f"config error: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
