"""Stream runtime: the 4-stage hot loop.

Functional clone of the reference's ``Stream::run`` (ref:
crates/arkflow-core/src/stream/mod.rs:79-398), re-expressed for asyncio:

    do_input -> [buffer] -> do_processor x N workers -> do_output

- Bounded queues of ``thread_num * 4`` between stages (ref :90-93).
- Workers stamp a sequence number at dequeue; the output task restores global
  order with a reorder map before writing (ref :280,319-353).
- Backpressure: when ``assigned - emitted > MAX_PENDING`` the workers pause
  (ref :34,263-273).
- Acks fire only after every produced batch was written (at-least-once,
  ref :379-396). A processor chain returning nothing acks immediately
  (ref :301-303).
- ``EndOfInput`` drains and shuts the stream down; ``Disconnection`` puts the
  input into a reconnect-forever loop with capped exponential backoff (the
  reference sleeps a fixed 5s, ref :176-203).
- Errors during processing route the original batch to ``error_output``:
  below ``max_delivery_attempts`` the batch is left unacked (nack) so the
  broker redelivers and the failure can heal; at the budget it is quarantined
  with attempt-count metadata. Output writes are retried with backoff behind
  an optional per-output circuit breaker; an ``error_output`` write failure
  falls back to retry-then-log instead of silently dropping the ack.
- Ordered close: input -> buffer -> pipeline -> output (ref :400-437).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Optional

from arkflow_tpu.batch import META_INGEST_TIME, MessageBatch, batch_fingerprint
from arkflow_tpu.components.base import Ack, Buffer, Input, Output, Resource, Temporary
from arkflow_tpu.components.registry import build_component
from arkflow_tpu.config import StreamConfig
from arkflow_tpu.errors import ArkError, Disconnection, EndOfInput
from arkflow_tpu.obs import global_registry
from arkflow_tpu.obs.trace import activate, global_tracer, stage_span
from arkflow_tpu.runtime.overload import (
    FairQueue,
    OverloadConfig,
    OverloadController,
    attach_overload,
    input_pauses_on_overload,
)
from arkflow_tpu.runtime.pipeline import Pipeline
from arkflow_tpu.utils.circuit_breaker import CircuitBreaker, CircuitBreakerConfig
from arkflow_tpu.utils.retry import RetryConfig, retry_with_backoff

logger = logging.getLogger("arkflow.stream")

MAX_PENDING = 1024  # ref stream/mod.rs:34
RECONNECT_DELAY_S = 5.0  # cap of the reconnect backoff (the reference's fixed delay, ref stream/mod.rs:190)
#: bound on the delivery-attempt tracking table; entries clear on success,
#: so this only matters with thousands of concurrently failing batches
MAX_TRACKED_ATTEMPTS = 8192


@dataclass
class _WorkItem:
    batch: MessageBatch
    ack: Ack
    enqueued_at: float = 0.0  # loop-clock time it entered the worker queue
    #: capped tenant label (set at admission when tenant accounting is on);
    #: None routes FairQueue items to the control lane, so admission MUST
    #: stamp it before putting — the default only applies pre-admission
    tenant: Optional[str] = None
    #: the batch's parsed TraceContext (obs/trace.py), cached at creation so
    #: later stages never re-parse the metadata column; None = untraced
    trace: Optional[object] = None


class _Done:
    """Queue sentinel: upstream stage finished."""


_DONE = _Done()


class Stream:
    def __init__(
        self,
        input_: Input,
        pipeline: Pipeline,
        output: Output,
        error_output: Optional[Output] = None,
        buffer: Optional[Buffer] = None,
        temporaries: Optional[dict[str, Temporary]] = None,
        thread_num: int = 1,
        name: str = "stream",
        output_retry: Optional[RetryConfig] = None,
        output_breaker: Optional[CircuitBreakerConfig] = None,
        error_output_retry: Optional[RetryConfig] = None,
        error_output_breaker: Optional[CircuitBreakerConfig] = None,
        max_delivery_attempts: int = 1,
        reconnect_retry: Optional[RetryConfig] = None,
        queue_size: int = 0,
        overload: Optional[OverloadConfig] = None,
    ):
        self.input = input_
        self.pipeline = pipeline
        self.output = output
        self.error_output = error_output
        self.buffer = buffer
        self.temporaries = temporaries or {}
        self.thread_num = max(1, thread_num)
        self.name = name
        self.output_retry = output_retry or RetryConfig()
        self.error_output_retry = error_output_retry or self.output_retry
        self.max_delivery_attempts = max(1, max_delivery_attempts)
        self.reconnect_retry = reconnect_retry  # None -> default derived at run time
        #: stage-queue depth; 0 keeps the historical thread_num * 4
        self.queue_size = queue_size if queue_size > 0 else self.thread_num * 4
        #: overload controller (deadline admission / AIMD window / priority
        #: shedding); None = admit everything, the pre-overload behavior
        self.overload: Optional[OverloadController] = (
            OverloadController(overload, name=name, workers=self.thread_num,
                               max_window=self.queue_size)
            if overload is not None and overload.enabled else None)

        reg = global_registry()
        labels = {"stream": name}
        self.m_rows_in = reg.counter("arkflow_rows_in_total", "rows read from input", labels)
        self.m_rows_out = reg.counter("arkflow_rows_out_total", "rows written to output", labels)
        self.m_batches_in = reg.counter("arkflow_batches_in_total", "batches read from input", labels)
        self.m_batches_out = reg.counter("arkflow_batches_out_total", "batches written", labels)
        self.m_errors = reg.counter("arkflow_process_errors_total", "processor errors", labels)
        self.m_write_errors = reg.counter("arkflow_write_errors_total", "output write errors", labels)
        self.m_proc_latency = reg.histogram("arkflow_process_seconds", "pipeline latency", labels)
        self.m_e2e_latency = reg.histogram("arkflow_e2e_seconds", "read-to-written latency", labels)
        self.m_pending = reg.gauge("arkflow_pending_batches", "in-flight batches", labels)
        self.m_read_latency = reg.histogram(
            "arkflow_input_read_seconds", "time blocked in input.read()", labels)
        self.m_queue_wait = reg.histogram(
            "arkflow_queue_wait_seconds", "work-item wait between input and worker", labels)
        self.m_write_latency = reg.histogram(
            "arkflow_output_write_seconds", "output.write() latency per batch", labels)
        self.m_backpressure_s = reg.counter(
            "arkflow_backpressure_seconds_total",
            "worker seconds stalled on the reorder window", labels)
        self.m_out_retries = reg.counter(
            "arkflow_output_retries_total", "output write retry attempts", labels)
        self.m_quarantined = reg.counter(
            "arkflow_quarantined_batches_total",
            "batches quarantined to error_output after exhausting delivery attempts", labels)
        self.m_quarantine_drops = reg.counter(
            "arkflow_quarantine_drops_total",
            "batches dropped because the error_output write itself kept failing", labels)
        self.m_ack_failures = reg.counter(
            "arkflow_ack_failures_total", "ack callbacks that raised", labels)
        self._out_breaker = (
            CircuitBreaker(
                output_breaker,
                gauge=reg.gauge("arkflow_circuit_state",
                                "output circuit breaker state (0 closed, 1 open, 2 half-open)",
                                {**labels, "output": "main"}),
                trip_counter=reg.counter("arkflow_circuit_trips_total",
                                         "circuit breaker open transitions",
                                         {**labels, "output": "main"}),
            ) if output_breaker else None
        )
        self._err_breaker = (
            CircuitBreaker(
                error_output_breaker,
                gauge=reg.gauge("arkflow_circuit_state",
                                "output circuit breaker state (0 closed, 1 open, 2 half-open)",
                                {**labels, "output": "error"}),
                trip_counter=reg.counter("arkflow_circuit_trips_total",
                                         "circuit breaker open transitions",
                                         {**labels, "output": "error"}),
            ) if error_output_breaker else None
        )

        #: per-batch tracing (obs/trace.py): the process-global tracer — the
        #: engine configured it from the `tracing:` block before streams run
        self.tracer = global_tracer()

        # runtime state
        self._pause_source = False  # resolved at run() from the input chain
        self._seq_assigned = 0
        self._seq_emitted = 0
        #: delivery attempts per failing batch fingerprint; cleared on success
        self._attempts: dict[bytes, int] = {}
        #: trace identity of failing batches, keyed like _attempts: a broker
        #: redelivery re-reads the raw record (no metadata columns), so the
        #: retry re-enters the SAME trace via this table instead. Populated
        #: only on failure paths — the all-healthy hot path never hashes.
        self._trace_ids: dict[bytes, tuple[str, bool]] = {}
        #: set by the output stage when the reorder window drains below
        #: MAX_PENDING — backpressured workers wake on it instead of polling
        self._drained = asyncio.Event()

    # -- lifecycle ---------------------------------------------------------

    async def run(self, cancel: asyncio.Event) -> None:
        """Run until the input ends or ``cancel`` is set; drains before returning."""
        # processors first: model warmup compiles must finish before the
        # input starts producing, or the first batches queue behind a
        # multi-second compile and pollute e2e latency
        await self.pipeline.connect()
        await self.input.connect()
        await self.output.connect()
        if self.error_output is not None:
            await self.error_output.connect()
        for t in self.temporaries.values():
            await t.connect()
        # push-based inputs (HTTP) get the controller for their 429 path;
        # pull-based brokers opt into cooperative pause instead. The buffer
        # and processors get it too: tenant-lane capping and cache
        # tenant-hit labels must reserve/cap EXACTLY like admission labels
        attach_overload(self.input, self.overload)
        attach_overload(self.buffer, self.overload)
        for proc in getattr(self.pipeline, "processors", None) or []:
            attach_overload(proc, self.overload)
        # shape-tuner wiring (tpu/tuner.py): bind each adaptive processor's
        # tuner to THIS stream's buffer, so a committed flip retargets
        # exactly this stream's coalescer lanes — never another stream's
        # that merely configured the same grid (walks _inner chaos chains
        # like attach_overload)
        if self.buffer is not None and hasattr(self.buffer, "retarget_shapes"):
            for proc in getattr(self.pipeline, "processors", None) or []:
                node, seen = proc, set()
                while node is not None and id(node) not in seen:
                    seen.add(id(node))
                    tn = getattr(node, "tuner", None)
                    if tn is not None and hasattr(tn, "bind_listener"):
                        tn.bind_listener(self.buffer)
                        break
                    node = getattr(node, "_inner", None)
        self._pause_source = (self.overload is not None
                              and input_pauses_on_overload(self.input))

        qsize = self.queue_size  # pipeline.queue_size; default ref stream/mod.rs:90-93
        if self.overload is not None and self.overload.cfg.tenants is not None:
            # multi-tenant serving: the worker queue itself schedules by
            # weighted deficit round robin, so one tenant's admitted backlog
            # cannot sit in front of everyone else's dequeues
            input_q = FairQueue(self.overload, qsize)
        else:
            input_q = asyncio.Queue(maxsize=qsize)
        output_q: asyncio.Queue = asyncio.Queue(maxsize=qsize)

        tasks = [asyncio.create_task(self._do_input(input_q, cancel), name=f"{self.name}-input")]
        if self.buffer is not None:
            tasks.append(asyncio.create_task(self._do_buffer(input_q), name=f"{self.name}-buffer"))
        for i in range(self.thread_num):
            tasks.append(
                asyncio.create_task(self._do_processor(input_q, output_q), name=f"{self.name}-proc-{i}")
            )
        out_task = asyncio.create_task(self._do_output(output_q), name=f"{self.name}-output")

        try:
            await asyncio.gather(*tasks)
            # each worker sent its sentinel; output drains the reorder map and exits
            await out_task
        except BaseException:
            for t in [*tasks, out_task]:
                t.cancel()
            await asyncio.gather(*tasks, out_task, return_exceptions=True)
            raise
        finally:
            await self._close_all()

    async def _close_all(self) -> None:
        # ordered close: input -> buffer -> pipeline -> output (ref :400-437)
        for stage, closer in (
            ("input", self.input.close),
            *((("buffer", self.buffer.close),) if self.buffer else ()),
            ("pipeline", self.pipeline.close),
            *((f"temporary:{name}", t.close)
              for name, t in self.temporaries.items()),
            *((("error_output", self.error_output.close),)
              if self.error_output else ()),
            ("output", self.output.close),
        ):
            try:
                await closer()
            except Exception:
                comp = type(getattr(closer, "__self__", closer)).__name__
                logger.exception("[%s] error during close of %s (%s)",
                                 self.name, stage, comp)

    # -- stages ------------------------------------------------------------

    async def _do_input(self, input_q: asyncio.Queue, cancel: asyncio.Event) -> None:
        """Read loop; feeds the buffer (if any) or the worker queue directly."""
        cancel_wait = asyncio.ensure_future(cancel.wait())
        loop = asyncio.get_running_loop()
        try:
            while not cancel.is_set():
                if self._pause_source and self.overload.should_pause():
                    # cooperative backpressure: a pull-based broker keeps the
                    # backlog on its side — strictly better than fetching
                    # batches we would immediately shed and nack back
                    t_pause = loop.time()
                    while self.overload.should_pause() and not cancel.is_set():
                        await self.overload.wait_capacity(0.25)
                    self.overload.m_paused_s.inc(loop.time() - t_pause)
                    if cancel.is_set():
                        break
                t_read = loop.time()
                read_f = asyncio.ensure_future(self.input.read())
                done, _ = await asyncio.wait(
                    {read_f, cancel_wait}, return_when=asyncio.FIRST_COMPLETED
                )
                read_dt = loop.time() - t_read
                if read_f in done:
                    # only completed reads count: a cancel while idle must
                    # not record time-until-shutdown as read latency
                    self.m_read_latency.observe(read_dt)
                if read_f not in done:
                    read_f.cancel()
                    try:
                        await read_f
                    except (asyncio.CancelledError, Exception):
                        pass
                    break
                try:
                    batch, ack = read_f.result()
                except EndOfInput:
                    logger.info("[%s] input exhausted (EOF)", self.name)
                    break
                except Disconnection as e:
                    # reconnect-forever loop with capped exponential backoff
                    # (the reference sleeps a fixed 5s, ref :183-194); the cap
                    # defaults to the module-level RECONNECT_DELAY_S so the
                    # old knob still shortens test reconnects
                    schedule = self.reconnect_retry or RetryConfig(
                        max_delay_ms=max(1, int(RECONNECT_DELAY_S * 1000)))
                    attempt = 0
                    logger.warning("[%s] input disconnected (%s); reconnecting in %.2fs",
                                   self.name, e, schedule.delay_s(0))
                    while not cancel.is_set():
                        try:
                            await asyncio.sleep(schedule.delay_s(attempt))
                            await self.input.connect()
                            break
                        except Exception as re:
                            attempt += 1
                            logger.warning("[%s] reconnect failed (attempt %d): %s; backing off",
                                           self.name, attempt, re)
                    continue
                except ArkError as e:
                    logger.error("[%s] input read error: %s", self.name, e)
                    await asyncio.sleep(0.1)
                    continue
                ctx = None
                if self.tracer.enabled:
                    # a trace context already on the batch means redelivery
                    # (or an upstream tier stamped it): the SAME trace
                    # accumulates the retry's spans. First deliveries root a
                    # fresh trace here; input_decode covers read+decode.
                    ctx = batch.trace_context()
                    redelivered = ctx is not None
                    if ctx is None:
                        # a broker redelivery of a failed batch re-enters
                        # its original trace (fingerprint-keyed, failure
                        # paths only); fresh batches root a new one
                        ctx = self._redelivered_trace(batch)
                        redelivered = ctx is not None
                        if ctx is None:
                            ctx = self.tracer.begin()
                        batch = batch.with_trace(ctx)
                    self.tracer.record(
                        ctx, "input_decode", read_dt,
                        attrs=({"redelivered": True} if redelivered else None))
                item = _WorkItem(batch.with_ingest_time(), ack, loop.time(),
                                 trace=ctx)
                self.m_batches_in.inc()
                self.m_rows_in.inc(batch.num_rows)
                if self.buffer is not None:
                    # admission happens at the worker-queue boundary
                    # (_do_buffer), after windowing/coalescing
                    await self.buffer.write(item.batch, item.ack)
                elif await self._admit_or_shed(item):
                    await input_q.put(item)
        finally:
            cancel_wait.cancel()
            if self.buffer is not None:
                await self.buffer.close()  # buffer drains remaining windows, then its reader exits
            else:
                for _ in range(self.thread_num):
                    await input_q.put(_DONE)

    async def _do_buffer(self, input_q: asyncio.Queue) -> None:
        """Move merged window/micro-batches from the buffer into the worker queue."""
        loop_time = asyncio.get_running_loop().time
        while True:
            item = await self.buffer.read()
            if item is None:
                for _ in range(self.thread_num):
                    await input_q.put(_DONE)
                return
            batch, ack = item
            ctx = None
            if self.tracer.enabled:
                batch, ctx = self._trace_emission(batch)
            work = _WorkItem(batch, ack, loop_time(), trace=ctx)
            if await self._admit_or_shed(work):
                await input_q.put(work)

    def _trace_emission(self, batch: MessageBatch):
        """Trace bookkeeping for a buffer emission. A merged emission (rows
        from several source batches) starts a NEW trace whose root span
        records parent links to every source trace; the sources are closed
        with status ``coalesced`` pointing at the merged id. A pass-through
        emission keeps its context. Either way the buffer/coalescer wait is
        recorded — from the buffer's own monotonic measurement when it
        provides one (``last_emission_wait_s``), else from the oldest row's
        ingest time."""
        wait_s = getattr(self.buffer, "last_emission_wait_s", None)
        if wait_s is None:
            ingest = batch.get_meta(META_INGEST_TIME)
            wait_s = (max(0.0, time.time() - float(ingest) / 1000.0)
                      if ingest is not None else 0.0)
        contexts = batch.source_trace_contexts()
        if len(contexts) <= 1:
            # no trace column (e.g. a window buffer's SQL projected the
            # metadata away): trace via the work item only — re-stamping
            # would inject a metadata column into user-shaped query output
            ctx = contexts[0] if contexts else self.tracer.begin()
            self.tracer.record(ctx, "buffer_wait", wait_s)
            return batch, ctx
        # merged emission: fresh trace, parent links both ways
        sources = [c.trace_id for c in contexts]
        ctx = self.tracer.begin()
        self.tracer.record(ctx, "coalesce_wait", wait_s,
                           attrs={"links": sources})
        for src in contexts:
            self.tracer.finish(src, "coalesced",
                               attrs={"merged_into": ctx.trace_id})
        return batch.with_trace(ctx), ctx

    async def _do_processor(self, input_q: asyncio.Queue, output_q: asyncio.Queue) -> None:
        """Worker: pipeline.process with seq stamping + backpressure (THE hot loop).

        Every attribute chased per batch here shows up directly in the
        saturated-ingest headline, so loop-invariant lookups (bound methods,
        the overload controller, the clock) are hoisted once per worker and
        tracing calls are skipped outright for untraced items instead of
        paying the no-op call + context-manager entries per batch."""
        loop_time = asyncio.get_running_loop().time
        # the stage name distinguishes WDRR scheduling waits from plain
        # FIFO queue waits in the breakdown (same measurement point)
        queue_stage = ("fair_queue_wait" if isinstance(input_q, FairQueue)
                       else "queue_wait")
        q_get = input_q.get
        q_put = output_q.put
        process = self.pipeline.process
        tracer = self.tracer
        record = tracer.record
        overload = self.overload
        observe_wait = self.m_queue_wait.observe
        observe_proc = self.m_proc_latency.observe
        set_pending = self.m_pending.set
        while True:
            # backpressure: event-driven wakeup the moment the reorder window
            # drains (the reference sleeps 100-500ms, ref :263-273; a poll
            # adds up to 100ms of latency noise per stall)
            if (self._seq_assigned - self._seq_emitted) > MAX_PENDING:
                t_bp = loop_time()
                while (self._seq_assigned - self._seq_emitted) > MAX_PENDING:
                    self._drained.clear()
                    try:
                        # bounded wait: never deadlocks even if an emit is lost
                        await asyncio.wait_for(self._drained.wait(), 1.0)
                    except asyncio.TimeoutError:
                        pass
                self.m_backpressure_s.inc(loop_time() - t_bp)
            item = await q_get()
            if isinstance(item, _Done):
                await q_put(_DONE)
                return
            now = loop_time()
            wait = now - item.enqueued_at
            observe_wait(wait)
            trace = item.trace
            if trace is not None:
                record(trace, queue_stage, wait)
            if overload is not None:
                overload.on_dequeue(wait, now, tenant=item.tenant)
                remaining = item.batch.remaining_deadline_ms(
                    overload.cfg.deadline_ms)
                if remaining is not None and remaining <= 0:
                    # went stale in the queue: finishing it is strictly worse
                    # than shedding (the caller already gave up) — and the
                    # expiry check is what bounds delivered-batch latency
                    await self._shed_item(item, overload.expire(item.tenant))
                    continue
            seq = self._seq_assigned
            self._seq_assigned += 1
            set_pending(self._seq_assigned - self._seq_emitted)
            t0 = loop_time()
            try:
                if trace is not None:
                    # activate the batch's trace scope: runner/processor spans
                    # (infeed prep, device step, cluster hops) nest under the
                    # process span with zero API plumbing
                    with activate(tracer, trace):
                        with stage_span("process"):
                            results = await process(item.batch)
                else:
                    results = await process(item.batch)
                err = None
            except Exception as e:  # processor failure -> error path
                results = []
                err = e
            dt = loop_time() - t0
            observe_proc(dt)
            if overload is not None:
                overload.observe_step(dt)
            await q_put((seq, item, results, err))

    async def _do_output(self, output_q: asyncio.Queue) -> None:
        """Reorder by seq and write; ack only on full success (ref :319-397)."""
        reorder: dict[int, tuple] = {}
        next_seq = 0
        done_workers = 0
        total_workers = self.thread_num
        q_get = output_q.get
        while True:
            msg = await q_get()
            if isinstance(msg, _Done):
                done_workers += 1
                if done_workers >= total_workers:
                    if reorder:
                        # a seq gap at shutdown (worker died mid-batch):
                        # nack the stuck batches so their sources redeliver
                        # NOW instead of waiting out broker ack timeouts
                        logger.error(
                            "[%s] %d batches stuck in reorder at shutdown; "
                            "nacking for redelivery", self.name, len(reorder))
                        for seq in sorted(reorder):
                            item, _results, _err = reorder.pop(seq)
                            await self._safe_nack(item.ack)
                    return
                continue
            seq, item, results, err = msg
            reorder[seq] = (item, results, err)
            while next_seq in reorder:
                item, results, err = reorder.pop(next_seq)
                next_seq += 1
                self._seq_emitted = next_seq
                if (self._seq_assigned - self._seq_emitted) <= MAX_PENDING:
                    self._drained.set()  # wake backpressured workers now
                await self._emit(item, results, err)

    # -- overload admission (runtime/overload.py) --------------------------

    async def _admit_or_shed(self, item: _WorkItem) -> bool:
        """Admission gate at the worker-queue boundary: True to enqueue,
        False when the controller shed the batch (already dispatched to
        error_output / nack — the caller just skips the put)."""
        ctrl = self.overload
        if ctrl is None:
            return True
        remaining = item.batch.remaining_deadline_ms(ctrl.cfg.deadline_ms)
        tokens = 0.0
        if ctrl.cfg.tenants is not None:
            # capped label computed ONCE here; every later touch (fair
            # queue lane, dequeue accounting, expiry, latency) reuses it
            item.tenant = ctrl.tenant_label(item.batch.tenant())
            if ctrl.meters_tokens():
                tokens = self._estimate_tokens(item.batch, ctrl.cfg.tenants)
        reason = ctrl.admit(item.batch.priority_band(ctrl.cfg.priority), remaining,
                            tenant=item.tenant, rows=float(item.batch.num_rows),
                            tokens=tokens)
        if reason is None:
            ctrl.on_enqueue(item.tenant)
            return True
        await self._shed_item(item, reason)
        return False

    @staticmethod
    def _estimate_tokens(batch: MessageBatch, policy) -> float:
        """Estimated token cost for tokens/s quota metering — the PR-6
        vectorized payload estimator (one pass over the Arrow offsets),
        reading the policy's ``token_field``/``token_bytes`` (which must
        match the serving stage's payload column). Batches without a usable
        payload column meter one token per row, so malformed traffic still
        counts against SOMETHING instead of riding free."""
        from arkflow_tpu.batch import DEFAULT_BINARY_VALUE_FIELD
        from arkflow_tpu.tpu.extract import payload_token_estimates

        try:
            col = batch.column(policy.token_field or DEFAULT_BINARY_VALUE_FIELD)
            return float(payload_token_estimates(
                col, token_bytes=policy.token_bytes).sum())
        except Exception:
            return float(batch.num_rows)

    async def _shed_item(self, item: _WorkItem, reason: str) -> None:
        """Dispose of a shed batch without silent loss: route to
        error_output tagged ``overloaded`` (preferred — terminal, keeps the
        accounting identity), else nack so the broker redelivers after the
        brownout, else log-and-ack (counted in ``arkflow_shed_total``)."""
        # forced sampling: a shed/expired batch is exactly the trace an
        # operator needs — commit it regardless of the head-sampling draw
        self.tracer.finish(item.trace,
                           "deadline" if reason == "deadline" else "shed",
                           attrs={"reason": reason})
        if self.error_output is not None:
            await self._error_route_or_drop(
                item.batch, {"error": "overloaded", "shed_reason": reason},
                f"[{self.name}] shed write",
                "[%s] error_output rejected a shed batch (%s); dropping "
                "WITH ack", self.name, reason)
            # terminal disposition: drop the fingerprint's delivery-attempt
            # count so an identical later payload starts with a fresh budget
            # (the nack path below keeps it — redelivery continues)
            self._clear_attempts(item.batch)
            await self._safe_ack(item.ack)
            return
        # an ABSOLUTE deadline that has already passed can only get MORE
        # expired on redelivery (unlike a TTL, which the re-stamped ingest
        # time resets), so nacking would spin shed->redeliver->shed forever
        expired_abs = (item.batch.deadline_unix_ms() is not None
                       and (item.batch.remaining_deadline_ms() or 0.0) <= 0)
        if getattr(item.ack, "redeliverable", False) and not expired_abs:
            await self._safe_nack(item.ack)
            # in-process brokers requeue instantly; pace the respin so the
            # read loop doesn't spin hot on shed->redeliver->shed
            if self.overload is not None:
                await self.overload.wait_capacity(0.05)
            else:
                await asyncio.sleep(0.05)
            return
        logger.warning("[%s] shed batch (%s) with no error_output and %s; "
                       "dropping WITH ack", self.name, reason,
                       "an expired absolute deadline" if expired_abs
                       else "no redelivery")
        self._clear_attempts(item.batch)
        await self._safe_ack(item.ack)

    # -- delivery path (hardened) -----------------------------------------

    @staticmethod
    def _fingerprint(batch: MessageBatch) -> bytes:
        """Stable batch identity for the delivery-attempt budget — the
        shared ``batch_fingerprint`` definition, which the coalescer's
        poison-suspect table must match exactly. Computed on failure paths,
        plus on successes only while failures are being tracked (the table
        is non-empty); the all-healthy hot path never pays for it."""
        return batch_fingerprint(batch)

    def _bump_attempts(self, batch: MessageBatch, trace=None) -> int:
        key = self._fingerprint(batch)
        n = self._attempts.get(key, 0) + 1
        if key not in self._attempts and len(self._attempts) >= MAX_TRACKED_ATTEMPTS:
            evicted = next(iter(self._attempts))
            self._attempts.pop(evicted)
            self._trace_ids.pop(evicted, None)
        self._attempts[key] = n
        if trace is not None:
            # remember the failing batch's trace identity so its broker
            # redelivery (raw record, no columns) re-enters the same trace
            self._trace_ids[key] = (trace.trace_id, trace.sampled)
        return n

    def _clear_attempts(self, batch: MessageBatch) -> None:
        if self._attempts:
            key = self._fingerprint(batch)
            self._attempts.pop(key, None)
            self._trace_ids.pop(key, None)

    def _redelivered_trace(self, batch: MessageBatch):
        """Trace context of a previously-failed delivery of this batch, or
        None. Hashes only while failures are outstanding (the table is
        non-empty) — same discipline as the attempt budget."""
        if not self._trace_ids:
            return None
        from arkflow_tpu.obs.trace import TraceContext

        hit = self._trace_ids.get(self._fingerprint(batch))
        if hit is None:
            return None
        return TraceContext(trace_id=hit[0], sampled=hit[1])

    async def _safe_ack(self, ack: Ack) -> None:
        """Acks confirm work already durably written; a failing ack must not
        crash the output stage (the broker redelivers and dedup is the
        consumer's concern under at-least-once)."""
        try:
            await ack.ack()
        except Exception as e:
            self.m_ack_failures.inc()
            logger.warning("[%s] ack failed (duplicate delivery possible): %s", self.name, e)

    async def _safe_nack(self, ack: Ack) -> None:
        try:
            await ack.nack()
        except Exception as e:
            logger.warning("[%s] nack failed: %s", self.name, e)

    async def _write_guarded(self, output: Output, breaker: Optional[CircuitBreaker],
                             retry_cfg: RetryConfig, batch: MessageBatch, what: str) -> None:
        """One delivery: retry-with-backoff around write attempts, each
        attempt gated by the output's circuit breaker (when configured)."""

        async def attempt() -> None:
            if breaker is not None:
                await breaker.acquire()
            try:
                await output.write(batch)
            except Exception:
                if breaker is not None:
                    breaker.record_failure()
                raise
            if breaker is not None:
                breaker.record_success()

        await retry_with_backoff(attempt, retry_cfg, what=what,
                                 on_retry=self.m_out_retries.inc)

    async def _error_route_or_drop(self, batch: MessageBatch, meta: dict,
                                   what: str, fail_log: str, *fail_args) -> bool:
        """Shared error_output dispatch for quarantine and overload sheds:
        tag, write with retry + breaker; on persistent failure count a
        quarantine drop and log. The caller always acks afterwards — a batch
        that can no longer go anywhere must not wedge the stream on eternal
        redelivery."""
        tagged = batch.with_ext_metadata(meta)
        try:
            await self._write_guarded(self.error_output, self._err_breaker,
                                      self.error_output_retry, tagged, what)
            return True
        except Exception:
            self.m_quarantine_drops.inc()
            logger.exception(fail_log, *fail_args)
            return False

    async def _quarantine(self, item: _WorkItem, reason: str, attempts: int) -> None:
        """Route a poisoned batch to error_output with attempt-count metadata
        and ack it."""
        if await self._error_route_or_drop(
                item.batch, {"error": reason, "delivery_attempts": str(attempts)},
                f"[{self.name}] error_output write",
                "[%s] error_output write kept failing; DROPPING batch after %d "
                "delivery attempt(s) (reason: %s)", self.name, attempts, reason):
            self.m_quarantined.inc()
        self._clear_attempts(item.batch)
        await self._safe_ack(item.ack)

    async def _emit(self, item: _WorkItem, results: list[MessageBatch], err: Optional[Exception]) -> None:
        if err is not None:
            reason = getattr(err, "shed_reason", None)
            if reason is not None:
                # a load-shed raised from INSIDE the chain (e.g. the cluster
                # dispatcher's retry budget during a brownout): not a
                # processing failure — route through the shed path so the
                # offered == delivered + shed identity holds and the batch
                # doesn't burn delivery attempts toward quarantine
                if self.overload is not None:
                    c = self.overload.m_shed.get(reason)
                    if c is not None:
                        c.inc()
                await self._shed_item(item, reason)
                return
            self.m_errors.inc()
            attempts = self._bump_attempts(item.batch, trace=item.trace)
            # forced sampling: every failed attempt commits its trace (the
            # redelivery re-enters the SAME trace id at _do_input)
            self.tracer.finish(item.trace, "error",
                               attrs={"error": str(err)[:200],
                                      "attempt": attempts})
            if attempts < self.max_delivery_attempts and getattr(
                    item.ack, "redeliverable", False):
                # transient failures (model OOM, lookup table blip) heal via
                # redelivery; only a batch that keeps failing is quarantined.
                # Without in-session redelivery (Ack.redeliverable) leaving
                # the batch unacked would silently drop or strand it — those
                # sources quarantine right away.
                logger.warning("[%s] processing failed (delivery %d/%d); leaving "
                               "unacked for redelivery: %s", self.name, attempts,
                               self.max_delivery_attempts, err)
                await self._safe_nack(item.ack)
                return
            if self.error_output is not None:
                await self._quarantine(item, str(err), attempts)
            else:
                logger.error("[%s] processing error (no error_output): %s", self.name, err)
                self._clear_attempts(item.batch)
                await self._safe_ack(item.ack)
            return
        if not results:
            # ProcessResult::None -> drop + ack (ref :301-303)
            self.tracer.finish(item.trace, "ok", attrs={"results": 0})
            await self._safe_ack(item.ack)
            return
        loop = asyncio.get_running_loop()
        try:
            t_write0 = loop.time()
            for b in results:
                t_w = loop.time()
                await self._write_guarded(self.output, self._out_breaker,
                                          self.output_retry, b,
                                          f"[{self.name}] output write")
                self.m_write_latency.observe(loop.time() - t_w)
                self.m_batches_out.inc()
                self.m_rows_out.inc(b.num_rows)
            self.tracer.record(item.trace, "output_write",
                               loop.time() - t_write0,
                               attrs=({"batches": len(results)}
                                      if len(results) > 1 else None))
        except Exception as e:
            self.m_write_errors.inc()
            attempts = self._bump_attempts(item.batch, trace=item.trace)
            self.tracer.finish(item.trace, "error",
                               attrs={"error": f"output write failed: {e}"[:200],
                                      "attempt": attempts})
            if self.error_output is not None and (
                    attempts >= self.max_delivery_attempts
                    or not getattr(item.ack, "redeliverable", False)):
                logger.error("[%s] output write failed after %d delivery attempt(s); "
                             "quarantining: %s", self.name, attempts, e)
                await self._quarantine(item, f"output write failed: {e}", attempts)
            else:
                logger.error("[%s] output write failed (delivery %d/%d); not acking: %s",
                             self.name, attempts, self.max_delivery_attempts, e)
                await self._safe_nack(item.ack)
            return
        self._clear_attempts(item.batch)
        ingest = item.batch.get_meta("__meta_ingest_time")
        e2e = None
        if ingest is not None:
            e2e = max(0.0, time.time() - ingest / 1000.0)
            self.m_e2e_latency.observe(e2e)
            if self.overload is not None and item.tenant is not None:
                # tenant-labeled delivered latency: what the noisy-tenant
                # soak's per-tenant p99 SLO assertion reads
                self.overload.observe_tenant_latency(item.tenant, e2e)
        self.tracer.finish(item.trace, "ok", e2e_s=e2e)
        await self._safe_ack(item.ack)


def build_stream(cfg: StreamConfig, name: Optional[str] = None) -> Stream:
    """Construct a Stream from config via the builder registries
    (ref StreamConfig::build, stream/mod.rs:453-492)."""
    if cfg.pipeline.ingest_shards > 0:
        # the whole hot path (coalesce -> admission -> chain) runs in shard
        # PROCESSES behind this parent endpoint (runtime/hostshard.py);
        # only input/output/error_output are built in-parent
        from arkflow_tpu.runtime.hostshard import build_sharded_stream

        return build_sharded_stream(cfg, name=name or cfg.name or "stream")
    resource = Resource()
    # temporaries first, so processors can look them up (ref :459-467)
    for tcfg in cfg.temporary:
        resource.temporaries[tcfg.name] = build_component("temporary", tcfg.config, resource)
    input_ = build_component("input", cfg.input, resource)
    if cfg.pipeline.process_pool > 0:
        from arkflow_tpu.runtime.procpool import ProcessPoolPipeline

        # chain lives in the workers; nothing is built in-parent (a parent
        # copy would double-open connections the workers also hold)
        pipeline = ProcessPoolPipeline(
            cfg.pipeline.processors, cfg.pipeline.process_pool,
            temporary_configs=[(t.name, t.config) for t in cfg.temporary])
    else:
        processors = [build_component("processor", p, resource)
                      for p in cfg.pipeline.processors]
        pipeline = Pipeline(processors)
    output = build_component("output", cfg.output, resource)
    error_output = build_component("output", cfg.error_output, resource) if cfg.error_output else None
    buffer = build_component("buffer", cfg.buffer, resource) if cfg.buffer else None
    return Stream(
        input_=input_,
        pipeline=pipeline,
        output=output,
        error_output=error_output,
        buffer=buffer,
        temporaries=resource.temporaries,
        thread_num=cfg.pipeline.effective_threads(),
        name=name or cfg.name or "stream",
        output_retry=cfg.output_retry,
        output_breaker=cfg.output_circuit_breaker,
        error_output_retry=cfg.error_output_retry,
        error_output_breaker=cfg.error_output_circuit_breaker,
        max_delivery_attempts=cfg.pipeline.max_delivery_attempts,
        reconnect_retry=cfg.input_reconnect,
        queue_size=cfg.pipeline.effective_queue_size(),
        overload=cfg.pipeline.overload,
    )
