"""Exact-match response cache with in-flight request collapsing.

Duplicate deliveries are structural in this engine: at-least-once redelivery
replays batches after nacks, the chaos layer's ``burst``/``ack_dup`` faults
mint duplicates on purpose, and client retry storms re-POST identical
payloads. Every duplicate that reaches the device costs a full TPU dispatch
for an answer the engine just computed. The cache short-circuits them in
front of the device:

- **Key**: ``batch_fingerprint`` — the shared stable batch identity (data +
  broker provenance, excluding per-delivery noise like ingest time and ext
  metadata). A redelivered batch and a byte-identical client retry hash to
  the same key, so hits return *bitwise-identical* responses (the cached
  output arrays are attached as-is).
- **Bounds**: LRU over ``capacity`` entries + a per-entry TTL, so a model
  hot-swap or drifting feature table can bound staleness; both are config.
- **In-flight collapsing**: N concurrent duplicates trigger ONE device step
  — the first caller computes while the rest await its future (the thundering
  herd a duplicate-delivery storm would otherwise turn into N dispatches).
  A failed compute propagates to every collapsed waiter and caches nothing,
  so the normal nack/redelivery path stays in charge of retries.

Single-event-loop discipline like the stream runtime: the dict mutations are
plain (no lock); ``compute`` itself may hop to executor threads — only the
bookkeeping runs on the loop.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import Any, Awaitable, Callable, Mapping, Optional

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.obs import global_registry


class ResponseCache:
    def __init__(self, capacity: int, ttl_s: Optional[float] = None,
                 name: str = "model"):
        if capacity < 1:
            raise ConfigError(
                f"response_cache.capacity must be >= 1, got {capacity}")
        if ttl_s is not None and ttl_s <= 0:
            raise ConfigError(
                f"response_cache.ttl must be > 0, got {ttl_s}")
        self.capacity = capacity
        self.ttl_s = ttl_s
        #: model-version epoch folded into every key (``get_or_compute``):
        #: ``batch_fingerprint`` identifies the REQUEST, not the weights that
        #: answered it — after a hot-swap a byte-identical duplicate must
        #: miss, or the cache would serve bitwise pre-swap responses forever
        self._epoch = 0
        #: key -> (expires_at_monotonic | None, value); insertion order = LRU
        self._entries: "OrderedDict[bytes, tuple[Optional[float], Any]]" = OrderedDict()
        self._inflight: dict[bytes, asyncio.Future] = {}
        reg = global_registry()
        labels = {"model": name}
        self.m_hits = reg.counter(
            "arkflow_cache_hits_total",
            "response-cache hits (device step skipped)", labels)
        self.m_misses = reg.counter(
            "arkflow_cache_misses_total",
            "response-cache misses (device step paid)", labels)
        self.m_collapsed = reg.counter(
            "arkflow_cache_collapsed_total",
            "duplicate in-flight requests collapsed onto one device step", labels)
        self.m_evictions = reg.counter(
            "arkflow_cache_evictions_total",
            "entries evicted by LRU capacity or TTL expiry", labels)
        self.m_size = reg.gauge(
            "arkflow_cache_size", "response-cache resident entries", labels)
        self._name = name
        #: tenant label -> hit counter (cardinality-capped like the
        #: controller's tenant metrics; the long tail shares __other__)
        self._tenant_hits: dict[str, Any] = {}
        #: the stream's TenantPolicy (set_tenant_policy) — aligns label
        #: capping with the admission controller; None = default cap only
        self._tenant_policy = None
        #: per-INSTANCE counts for report(): the registry dedupes metric
        #: series on (name, labels), so two streams serving the same model
        #: share the counters above — /health must still report each
        #: cache's own traffic, not the pooled totals
        self.n_hits = self.n_misses = self.n_collapsed = self.n_evictions = 0

    @property
    def epoch(self) -> int:
        return self._epoch

    def bump_epoch(self) -> None:
        """A model swap committed: every cached response was computed by the
        OLD weights. The epoch in the key makes them unreachable (a post-swap
        duplicate misses and recomputes); the flush reclaims their memory
        now instead of waiting for LRU churn. In-flight computes keyed under
        the old epoch complete harmlessly — they store under a key no new
        lookup can form."""
        self._epoch += 1
        flushed = len(self._entries)
        if flushed:
            self._entries.clear()
            self.m_evictions.inc(flushed)
            self.n_evictions += flushed
            self.m_size.set(0)

    def set_tenant_policy(self, policy) -> None:
        """Adopt the stream's tenant policy (stream hook via the serving
        processor) so hit labels reserve configured tenants and honor
        ``max_tracked`` exactly like the admission controller's labels."""
        self._tenant_policy = policy

    def _count_tenant_hit(self, tenant: Optional[str]) -> None:
        """Tenant-labeled hit counter, bounded by the shared capping rule
        (``overload.cap_tenant_label``): past the cap the long tail shares
        one ``__other__`` series."""
        from arkflow_tpu.runtime.overload import MAX_TENANT_LABELS, cap_tenant_label

        policy = self._tenant_policy
        label = cap_tenant_label(
            tenant, self._tenant_hits,
            reserved=(policy.weights if policy is not None else ()),
            cap=(policy.max_tracked if policy is not None
                 else MAX_TENANT_LABELS))
        c = self._tenant_hits.get(label)
        if c is None:
            c = self._tenant_hits[label] = global_registry().counter(
                "arkflow_cache_tenant_hits_total",
                "response-cache hits by tenant",
                {"model": self._name, "tenant": label})
        c.inc()

    def __len__(self) -> int:
        return len(self._entries)

    def lookup(self, key: bytes) -> Optional[Any]:
        """Cached value for ``key`` (refreshing its LRU position), or None.
        Counts neither hit nor miss — ``get_or_compute`` owns the metrics."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        expires_at, value = entry
        if expires_at is not None and time.monotonic() >= expires_at:
            del self._entries[key]
            self.m_evictions.inc()
            self.n_evictions += 1
            self.m_size.set(len(self._entries))
            return None
        self._entries.move_to_end(key)
        return value

    def store(self, key: bytes, value: Any) -> None:
        expires_at = (time.monotonic() + self.ttl_s
                      if self.ttl_s is not None else None)
        self._entries[key] = (expires_at, value)
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self.m_evictions.inc()
            self.n_evictions += 1
        self.m_size.set(len(self._entries))

    async def get_or_compute(self, key: bytes,
                             compute: Callable[[], Awaitable[Any]],
                             tenant: Optional[str] = None) -> Any:
        """The serving-path entry point: cached value, a collapsed wait on
        an identical in-flight compute, or a fresh compute (stored on
        success). Exceptions from ``compute`` reach every collapsed caller
        and leave the cache untouched."""
        # the model-version epoch is part of the identity: the same request
        # against different weights is a different cache entry
        key = self._epoch.to_bytes(8, "big") + key
        hit = self.lookup(key)
        if hit is not None:
            self.m_hits.inc()
            self.n_hits += 1
            self._count_tenant_hit(tenant)
            return hit
        fut = self._inflight.get(key)
        if fut is not None:
            self.m_collapsed.inc()
            self.n_collapsed += 1
            self._count_tenant_hit(tenant)
            return await fut
        self.m_misses.inc()
        self.n_misses += 1
        fut = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        try:
            value = await compute()
        except BaseException as e:
            if isinstance(e, Exception):
                fut.set_exception(e)
                # consume once so a storm with zero collapsed waiters does
                # not log "exception was never retrieved"; real waiters
                # still receive it from their awaits
                fut.exception()
            else:  # CancelledError etc.: wake waiters without caching
                fut.cancel()
            raise
        else:
            self.store(key, value)
            fut.set_result(value)
            return value
        finally:
            self._inflight.pop(key, None)

    def report(self) -> dict:
        """Snapshot for /health."""
        return {
            "entries": len(self._entries),
            "capacity": self.capacity,
            "ttl_s": self.ttl_s,
            "epoch": self._epoch,
            "hits": self.n_hits,
            "misses": self.n_misses,
            "collapsed": self.n_collapsed,
            "evictions": self.n_evictions,
        }


def parse_response_cache_config(config: Any) -> Optional[tuple[int, Optional[float]]]:
    """Validate ``response_cache`` config -> ``(capacity, ttl_s)``, or None
    when disabled. Pure parse: config.py runs this at ``--validate`` time
    without minting a cache (and its metric series) per validation pass."""
    from arkflow_tpu.utils.duration import parse_duration

    if config is None or config is False:
        return None
    if config is True:
        config = {}
    if not isinstance(config, Mapping):
        raise ConfigError("response_cache must be a mapping or boolean")
    capacity = config.get("capacity", 1024)
    if isinstance(capacity, bool) or not isinstance(capacity, int) or capacity < 1:
        raise ConfigError(
            f"response_cache.capacity must be an int >= 1, got {capacity!r}")
    ttl = config.get("ttl")
    ttl_s = parse_duration(ttl) if ttl is not None else None
    if ttl_s is not None and ttl_s <= 0:
        raise ConfigError(f"response_cache.ttl must be > 0, got {ttl!r}")
    return int(capacity), ttl_s


def build_response_cache(config: Any, *, name: str) -> Optional[ResponseCache]:
    """``response_cache: {capacity: 1024, ttl: 30s}`` -> ResponseCache.
    ``None``/``false`` disables; ``true`` takes the defaults."""
    parsed = parse_response_cache_config(config)
    if parsed is None:
        return None
    capacity, ttl_s = parsed
    return ResponseCache(capacity, ttl_s, name=name)
