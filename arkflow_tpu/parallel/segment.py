"""Profiled model segmentation: cut layer stacks into cost-balanced stages.

Implements the planning half of "Improving inference time in multi-TPU
systems with profiled model segmentation" (PAPERS.md): given measured
per-layer costs (``tools/profile_step.py --per-layer``) and a stage count S,
choose S contiguous layer ranges minimizing the MAX stage cost — the
pipeline's tick time is the slowest stage, so minimizing the max is
minimizing steady-state latency AND maximizing throughput at once.

Pure host-side math (no jax): the executor (``parallel/pipeline.py
make_pp_infer_step``) consumes the plan, and the plan rides bench/health
output so stage imbalance is attributable to the profile that produced it.

The planner is exact: dynamic programming over (layer, stage) prefixes,
O(S * L^2) with L = layer count — transformers have tens of layers, so
optimality is cheap and "balanced within one layer of optimal" is a
guarantee, not a heuristic's hope.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional, Sequence

from arkflow_tpu.errors import ConfigError


@dataclass(frozen=True)
class StagePlan:
    """S contiguous layer ranges over an L-layer stack.

    ``bounds[s] = (start, end)`` half-open: stage ``s`` runs layers
    ``start..end-1``. Every layer is covered exactly once and every stage
    holds >= 1 layer.
    """

    bounds: tuple[tuple[int, int], ...]
    #: the per-layer costs the cut was computed from (uniform 1.0 when no
    #: profile was supplied) — kept so reports show WHAT was balanced
    layer_costs: tuple[float, ...]

    @property
    def stages(self) -> int:
        return len(self.bounds)

    @property
    def num_layers(self) -> int:
        return self.bounds[-1][1]

    @property
    def sizes(self) -> tuple[int, ...]:
        return tuple(e - s for s, e in self.bounds)

    @property
    def stage_costs(self) -> tuple[float, ...]:
        return tuple(sum(self.layer_costs[s:e]) for s, e in self.bounds)

    @property
    def max_stage_cost(self) -> float:
        return max(self.stage_costs)

    @property
    def imbalance(self) -> float:
        """max stage cost / mean stage cost — 1.0 is a perfect cut; the
        pipeline's bubble-adjusted efficiency degrades linearly with it."""
        costs = self.stage_costs
        mean = sum(costs) / len(costs)
        return max(costs) / mean if mean > 0 else 1.0

    @property
    def uniform(self) -> bool:
        """Every stage holds the same number of layers (the executor skips
        per-slot activity masking entirely for uniform plans)."""
        return len(set(self.sizes)) == 1

    def report(self) -> dict:
        """JSON-able form for bench detail / the engine's /health."""
        return {
            "stages": self.stages,
            "num_layers": self.num_layers,
            "bounds": [list(b) for b in self.bounds],
            "stage_costs": [round(c, 6) for c in self.stage_costs],
            "max_stage_cost": round(self.max_stage_cost, 6),
            "imbalance": round(self.imbalance, 4),
        }


def plan_stages(layer_costs: Sequence[float], stages: int) -> StagePlan:
    """Optimal contiguous S-way partition of ``layer_costs`` minimizing the
    max stage cost.

    DP over prefixes: ``best[s][i]`` = minimal achievable max-stage cost
    covering layers ``0..i-1`` with ``s`` stages. Ties broken toward LATER
    cut points (earlier stages absorb more layers), which keeps uniform-cost
    vectors cutting into equal-size stages.
    """
    costs = [float(c) for c in layer_costs]
    n = len(costs)
    if n == 0:
        raise ConfigError("plan_stages: layer_costs must be non-empty")
    if any(c < 0 for c in costs):
        raise ConfigError(f"plan_stages: layer costs must be >= 0, got {costs}")
    if not isinstance(stages, int) or isinstance(stages, bool) or stages < 1:
        raise ConfigError(f"plan_stages: stages must be an int >= 1, got {stages!r}")
    if stages > n:
        raise ConfigError(
            f"plan_stages: cannot cut {n} layers into {stages} stages "
            "(every stage needs at least one layer)")

    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def span(i: int, j: int) -> float:
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[i]: minimal max-stage cost for layers 0..i-1 with the current
    # number of stages; cut[s][i]: where stage s-1 began in that optimum
    best = [0.0 if i == 0 else INF for i in range(n + 1)]
    cuts: list[list[int]] = []
    for s in range(1, stages + 1):
        nxt = [INF] * (n + 1)
        cut_row = [0] * (n + 1)
        # with s stages, at least s layers are covered and at least
        # stages - s layers must remain for the later stages
        for i in range(s, n - (stages - s) + 1):
            b, c = INF, s - 1
            for k in range(s - 1, i):
                cand = max(best[k], span(k, i))
                # <= prefers the LATEST feasible cut: uniform costs then
                # split ceil-first (e.g. 4 layers / 3 stages -> 2,1,1)
                if cand <= b:
                    b, c = cand, k
            nxt[i], cut_row[i] = b, c
        best = nxt
        cuts.append(cut_row)

    bounds: list[tuple[int, int]] = []
    end = n
    for s in range(stages, 0, -1):
        start = cuts[s - 1][end]
        bounds.append((start, end))
        end = start
    bounds.reverse()
    return StagePlan(tuple(bounds), tuple(costs))


def uniform_plan(num_layers: int, stages: int) -> StagePlan:
    """The no-profile default: every layer costs 1.0 (transformer stacks are
    homogeneous, so this IS the optimal cut until a profile says otherwise)."""
    return plan_stages([1.0] * num_layers, stages)


def load_layer_costs(path: str, *, expect_layers: Optional[int] = None) -> list[float]:
    """Read per-layer costs from a ``tools/profile_step.py --per-layer``
    JSON artifact (key ``per_layer_ms``; a bare JSON list also works)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ConfigError(f"pp_profile: cannot read layer costs from {path!r}: {e}") from e
    costs = data if isinstance(data, list) else data.get("per_layer_ms")
    if not isinstance(costs, list) or not costs or \
            not all(isinstance(c, (int, float)) and not isinstance(c, bool) and c >= 0
                    for c in costs):
        raise ConfigError(
            f"pp_profile {path!r}: expected a non-empty 'per_layer_ms' list of "
            "non-negative numbers (tools/profile_step.py --per-layer output)")
    if expect_layers is not None and len(costs) != expect_layers:
        raise ConfigError(
            f"pp_profile {path!r} has {len(costs)} per-layer costs but the "
            f"model has {expect_layers} layers — re-profile with the served "
            "model_config")
    return [float(c) for c in costs]
