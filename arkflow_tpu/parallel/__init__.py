from arkflow_tpu.parallel.mesh import MeshSpec, create_mesh, shard_params  # noqa: F401
