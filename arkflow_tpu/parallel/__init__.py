from arkflow_tpu.parallel.mesh import MeshSpec, create_mesh, shard_params  # noqa: F401
from arkflow_tpu.parallel.segment import StagePlan, plan_stages, uniform_plan  # noqa: F401
