"""Multi-host distributed runtime (DCN tier).

One-call bootstrap over ``jax.distributed``: every host runs the same engine
binary, the JAX runtime forms the global device mesh across hosts (ICI within
a slice, DCN between), and the existing ``MeshSpec``/``shard_params`` path
works unchanged on the global device list. This is the XLA-collective
equivalent of a NCCL/MPI communication backend — collectives are compiled
into the program rather than hand-driven (SURVEY.md section 2.7: the
reference's only cross-node mechanisms are broker protocols and Ballista).

Environment-variable driven so k8s/slurm launchers need no config changes:

    ARKFLOW_COORDINATOR=host0:1234 ARKFLOW_NUM_PROCESSES=4 ARKFLOW_PROCESS_ID=2
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("arkflow.distributed")


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Initialize jax.distributed from args or ARKFLOW_* env; returns True if
    multi-process mode was activated (False = single host, no-op).

    Failures are wrapped in :class:`ConfigError` naming the effective
    ``ARKFLOW_COORDINATOR`` / ``ARKFLOW_NUM_PROCESSES`` /
    ``ARKFLOW_PROCESS_ID`` values — a raw RuntimeError out of
    ``jax.distributed.initialize`` (bad address, duplicate process id, a
    coordinator that never came up) tells an operator nothing about which
    knob on which host was wrong."""
    from arkflow_tpu.errors import ConfigError

    coordinator = coordinator or os.environ.get("ARKFLOW_COORDINATOR")
    if not coordinator:
        return False
    raw_np = (num_processes if num_processes is not None
              else os.environ.get("ARKFLOW_NUM_PROCESSES", "1"))
    raw_pid = (process_id if process_id is not None
               else os.environ.get("ARKFLOW_PROCESS_ID", "0"))
    where = (f"ARKFLOW_COORDINATOR={coordinator!r} "
             f"ARKFLOW_NUM_PROCESSES={raw_np!r} ARKFLOW_PROCESS_ID={raw_pid!r}")
    try:
        num_processes = int(raw_np)
        process_id = int(raw_pid)
    except (TypeError, ValueError) as e:
        raise ConfigError(
            f"distributed bootstrap: ARKFLOW_NUM_PROCESSES / "
            f"ARKFLOW_PROCESS_ID must be integers ({where}): {e}") from e
    if num_processes < 1:
        raise ConfigError(
            f"distributed bootstrap: num_processes must be >= 1 ({where})")
    if not 0 <= process_id < num_processes:
        # caught BEFORE jax.distributed.initialize: the coordinator would
        # otherwise hang waiting for a process id that can never arrive
        raise ConfigError(
            f"distributed bootstrap: process_id must be in "
            f"[0, num_processes) ({where})")
    import jax  # deferred: single-host pipelines shouldn't touch jax here

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:
        raise ConfigError(
            f"distributed bootstrap failed ({where}): {e}") from e
    logger.info(
        "distributed runtime up: process %d/%d, %d global / %d local devices",
        process_id, num_processes, jax.device_count(), jax.local_device_count(),
    )
    return True
