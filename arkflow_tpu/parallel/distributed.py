"""Multi-host distributed runtime (DCN tier).

One-call bootstrap over ``jax.distributed``: every host runs the same engine
binary, the JAX runtime forms the global device mesh across hosts (ICI within
a slice, DCN between), and the existing ``MeshSpec``/``shard_params`` path
works unchanged on the global device list. This is the XLA-collective
equivalent of a NCCL/MPI communication backend — collectives are compiled
into the program rather than hand-driven (SURVEY.md section 2.7: the
reference's only cross-node mechanisms are broker protocols and Ballista).

Environment-variable driven so k8s/slurm launchers need no config changes:

    ARKFLOW_COORDINATOR=host0:1234 ARKFLOW_NUM_PROCESSES=4 ARKFLOW_PROCESS_ID=2

Beyond the bootstrap, this module carries the **multi-host serving plane**
for the cluster tier (``runtime/cluster.py``): one model too big for a
single worker process served by a ``mesh: {pp: N}`` that spans several
``jax.distributed`` processes. The discipline is lockstep SPMD —

- every process builds the IDENTICAL processor chain (same config, same
  seed, same warmup order), so the jitted steps and their collectives are
  compiled and entered in the same order everywhere;
- host-side eager work pins to a process-LOCAL device
  (``pin_local_default_device``) — under ``jax.distributed`` the global
  device list leads with process 0's device, and an eager op placed on a
  non-addressable device is a hard error;
- process 0 (the **primary**) opens the serving port; before running each
  batch it fans the Arrow payload out over :class:`BroadcastChannel`, and
  every other process (a **follower**, :func:`run_follower`) replays the
  identical ``pipeline.process`` call — so the pp stages that live on the
  follower's devices execute their half of each collective in step.

The channel is two ``broadcast_one_to_all`` collectives per message (a
fixed-shape length header, then the exact-size payload), so followers never
need to know sizes in advance, and a negative header is the clean-shutdown
signal.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import time
from dataclasses import dataclass
from typing import Any, Mapping, Optional

logger = logging.getLogger("arkflow.distributed")

#: header value broadcast by the primary when the serving loop ends —
#: followers exit their replay loop cleanly instead of hanging on a recv
_CLOSE_SENTINEL = -1


def _split_coordinator(coordinator: str, where: str):
    """``host:port`` -> (host, port), with a ConfigError naming the knob."""
    from arkflow_tpu.errors import ConfigError

    host, sep, port_s = str(coordinator).rpartition(":")
    if not sep or not host:
        raise ConfigError(
            f"distributed bootstrap: coordinator must be host:port, "
            f"got {coordinator!r} ({where})")
    try:
        port = int(port_s)
    except ValueError as e:
        raise ConfigError(
            f"distributed bootstrap: coordinator port must be an integer, "
            f"got {coordinator!r} ({where})") from e
    if not 0 < port < 65536:
        raise ConfigError(
            f"distributed bootstrap: coordinator port out of range "
            f"({coordinator!r}, {where})")
    return host, port


def probe_coordinator(coordinator: str, *, timeout_s: float = 10.0,
                      where: str = "") -> None:
    """TCP-probe the coordinator before handing control to
    ``jax.distributed.initialize`` — a wrong address or a coordinator that
    never came up otherwise surfaces as a raw jax RuntimeError after a long
    opaque hang. Retries until ``timeout_s`` (the coordinator may still be
    binding), then raises :class:`ConfigError` naming the address."""
    from arkflow_tpu.errors import ConfigError

    host, port = _split_coordinator(coordinator, where or "probe")
    deadline = time.monotonic() + timeout_s
    last_err: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return
        except OSError as e:
            last_err = e
            time.sleep(0.25)
    raise ConfigError(
        f"distributed bootstrap: coordinator {coordinator!r} unreachable "
        f"after {timeout_s:.0f}s ({where or 'probe'}): {last_err} — is "
        f"process 0 up and the address/port right?")


def pin_local_default_device() -> None:
    """Pin eager dispatch to a process-local device. Must run AFTER
    ``jax.distributed.initialize``: the global ``jax.devices()`` list leads
    with process 0's devices, and any eager op (even ``PRNGKey``) placed on
    a non-addressable device raises ``INVALID_ARGUMENT``."""
    import jax

    local = jax.local_devices()
    if local:
        jax.config.update("jax_default_device", local[0])


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     *, probe_timeout_s: float = 10.0,
                     cpu_collectives: Optional[str] = None) -> bool:
    """Initialize jax.distributed from args or ARKFLOW_* env; returns True if
    multi-process mode was activated (False = single host, no-op).

    Failures are wrapped in :class:`ConfigError` naming the effective
    ``ARKFLOW_COORDINATOR`` / ``ARKFLOW_NUM_PROCESSES`` /
    ``ARKFLOW_PROCESS_ID`` values — a raw RuntimeError out of
    ``jax.distributed.initialize`` (bad address, duplicate process id, a
    coordinator that never came up) tells an operator nothing about which
    knob on which host was wrong. Non-zero processes TCP-probe the
    coordinator first (``probe_timeout_s``) so an unreachable address fails
    in seconds with the offending value, not after an opaque hang.

    ``cpu_collectives`` selects the CPU cross-process collective backend
    (``"gloo"`` is the one this repo's virtual-CPU environments support);
    default: ``gloo`` when the process is pinned to the CPU platform and
    more than one process participates. TPU slices ignore it — their
    collectives ride ICI/DCN natively."""
    from arkflow_tpu.errors import ConfigError

    coordinator = coordinator or os.environ.get("ARKFLOW_COORDINATOR")
    if not coordinator:
        return False
    raw_np = (num_processes if num_processes is not None
              else os.environ.get("ARKFLOW_NUM_PROCESSES", "1"))
    raw_pid = (process_id if process_id is not None
               else os.environ.get("ARKFLOW_PROCESS_ID", "0"))
    where = (f"ARKFLOW_COORDINATOR={coordinator!r} "
             f"ARKFLOW_NUM_PROCESSES={raw_np!r} ARKFLOW_PROCESS_ID={raw_pid!r}")
    try:
        num_processes = int(raw_np)
        process_id = int(raw_pid)
    except (TypeError, ValueError) as e:
        raise ConfigError(
            f"distributed bootstrap: ARKFLOW_NUM_PROCESSES / "
            f"ARKFLOW_PROCESS_ID must be integers ({where}): {e}") from e
    if num_processes < 1:
        raise ConfigError(
            f"distributed bootstrap: num_processes must be >= 1 ({where})")
    if not 0 <= process_id < num_processes:
        # caught BEFORE jax.distributed.initialize: the coordinator would
        # otherwise hang waiting for a process id that can never arrive
        raise ConfigError(
            f"distributed bootstrap: process_id must be in "
            f"[0, num_processes) ({where})")
    _split_coordinator(coordinator, where)  # malformed address fails here
    if process_id > 0:
        # process 0 BINDS the address (no probe possible before it starts);
        # everyone else can and should fail fast on an unreachable one
        probe_coordinator(coordinator, timeout_s=probe_timeout_s, where=where)
    import jax  # deferred: single-host pipelines shouldn't touch jax here

    prev_collectives = None
    set_collectives = False
    if num_processes > 1:
        backend = cpu_collectives
        if backend is None and _cpu_platform_pinned():
            backend = "gloo"
        if backend:
            try:
                prev_collectives = getattr(
                    jax.config, "jax_cpu_collectives_implementation", None)
                jax.config.update(
                    "jax_cpu_collectives_implementation", backend)
                set_collectives = True
            except Exception as e:  # older jax without the knob
                logger.warning("cpu collectives %r not configurable: %s",
                               backend, e)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:
        if set_collectives:
            # a cross-process collective backend with NO distributed client
            # breaks any later single-process backend init in this process
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  prev_collectives)
            except Exception:
                pass
        raise ConfigError(
            f"distributed bootstrap failed ({where}): {e}") from e
    pin_local_default_device()
    logger.info(
        "distributed runtime up: process %d/%d, %d global / %d local devices",
        process_id, num_processes, jax.device_count(), jax.local_device_count(),
    )
    return True


def _cpu_platform_pinned() -> bool:
    """True when the env pins jax to CPU (the containers this repo's tests
    and soaks run in do, via ``JAX_PLATFORMS=cpu``); consulted BEFORE any
    backend initializes, so it reads env rather than ``jax.devices()``."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    return "cpu" in [p.strip() for p in plats.split(",") if p.strip()]


# ---------------------------------------------------------------------------
# multi-host serving plane (cluster workers spanning processes)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MultihostContext:
    """An activated multi-host group: identity + the broadcast role."""

    coordinator: str
    num_processes: int
    process_id: int

    @property
    def is_primary(self) -> bool:
        return self.process_id == 0


def parse_distributed_config(cfg: Any, *,
                             who: str = "cluster worker") -> Optional[dict]:
    """Pure parse of a ``distributed:`` block. Env (``ARKFLOW_*``) overrides
    config — launchers stamp per-process identity there, while the shared
    YAML carries the group shape. None = block absent AND env silent."""
    from arkflow_tpu.errors import ConfigError
    from arkflow_tpu.utils.duration import parse_duration

    if cfg is None:
        cfg = {}
    if not isinstance(cfg, Mapping):
        raise ConfigError(f"{who}: 'distributed' must be a mapping, got {cfg!r}")
    known = {"coordinator", "num_processes", "process_id",
             "coordinator_timeout", "cpu_collectives"}
    unknown = set(cfg) - known
    if unknown:
        raise ConfigError(
            f"{who}: distributed: unknown keys {sorted(unknown)} "
            f"(known: {sorted(known)})")
    coordinator = os.environ.get("ARKFLOW_COORDINATOR") \
        or cfg.get("coordinator")
    if not coordinator:
        if cfg:
            raise ConfigError(
                f"{who}: distributed: needs a 'coordinator' (host:port) or "
                "the ARKFLOW_COORDINATOR env")
        return None
    out: dict = {"coordinator": str(coordinator)}
    for key, env in (("num_processes", "ARKFLOW_NUM_PROCESSES"),
                     ("process_id", "ARKFLOW_PROCESS_ID")):
        raw = os.environ.get(env, cfg.get(key))
        if raw is None:
            raw = 1 if key == "num_processes" else 0
        try:
            out[key] = int(raw)
        except (TypeError, ValueError) as e:
            raise ConfigError(
                f"{who}: distributed.{key} must be an integer, "
                f"got {raw!r}") from e
    timeout = cfg.get("coordinator_timeout", "10s")
    try:
        out["coordinator_timeout_s"] = parse_duration(timeout)
    except (ConfigError, TypeError, ValueError) as e:
        raise ConfigError(
            f"{who}: distributed.coordinator_timeout invalid: {e}") from e
    cc = cfg.get("cpu_collectives")
    if cc is not None and not isinstance(cc, str):
        raise ConfigError(
            f"{who}: distributed.cpu_collectives must be a string, got {cc!r}")
    out["cpu_collectives"] = cc
    return out


def multihost_from_config(config: Mapping) -> Optional[MultihostContext]:
    """Activate multi-host mode for a cluster worker when its config (or the
    env) names a group larger than one process: runs the full
    ``init_distributed`` bootstrap and returns the group context. None =
    single-process worker, nothing initialized."""
    parsed = parse_distributed_config(
        config.get("distributed") if isinstance(config, Mapping) else None)
    if parsed is None or parsed["num_processes"] < 2:
        return None
    init_distributed(parsed["coordinator"], parsed["num_processes"],
                     parsed["process_id"],
                     probe_timeout_s=parsed["coordinator_timeout_s"],
                     cpu_collectives=parsed["cpu_collectives"])
    return MultihostContext(coordinator=parsed["coordinator"],
                            num_processes=parsed["num_processes"],
                            process_id=parsed["process_id"])


class BroadcastChannel:
    """Primary → followers byte-stream over jax collectives.

    Each message is two ``broadcast_one_to_all`` rounds: a fixed-shape
    int64 length header, then the payload at exactly that size (so the
    follower side can allocate its placeholder — ``broadcast_one_to_all``
    needs matching shapes on every process). Both sides MUST call in the
    same order: ``send`` on the primary pairs with ``recv`` on every
    follower; ``close`` pairs with the ``recv`` that returns None.

    Calls are blocking (collectives): drive them through a thread executor
    from async code, as :class:`LockstepPipeline`/:func:`run_follower` do."""

    def __init__(self, ctx: MultihostContext):
        self.ctx = ctx
        self._closed = False

    def _bcast(self, arr):
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(arr)

    def send(self, payload: bytes) -> None:
        import numpy as np

        if self._closed:
            raise RuntimeError("broadcast channel is closed")
        self._bcast(np.array([len(payload)], dtype=np.int64))
        if payload:
            self._bcast(np.frombuffer(payload, dtype=np.uint8))

    def recv(self) -> Optional[bytes]:
        import numpy as np

        header = self._bcast(np.zeros((1,), dtype=np.int64))
        n = int(header[0])
        if n < 0:
            self._closed = True
            return None
        if n == 0:
            return b""
        data = self._bcast(np.zeros((n,), dtype=np.uint8))
        # the collective may promote uint8 (it reduces through a wider
        # accumulator); values stay 0..255, so cast back before rebuilding
        return np.asarray(data).astype(np.uint8, copy=False).tobytes()

    def close(self) -> None:
        import numpy as np

        if self._closed:
            return
        self._closed = True
        try:
            self._bcast(np.array([_CLOSE_SENTINEL], dtype=np.int64))
        except Exception:
            logger.exception("broadcast close failed (followers may hang "
                             "until their own timeout)")


class LockstepPipeline:
    """Primary-side pipeline wrapper: fan each batch out to the followers
    BEFORE running it locally, so every process executes the identical
    ``process`` sequence and the model's cross-process collectives stay
    matched. Batches serialize through one lock — a multi-host model IS one
    device group; interleaving two batches' collectives would deadlock."""

    def __init__(self, ctx: MultihostContext, inner):
        self._ctx = ctx
        self._inner = inner
        self.channel = BroadcastChannel(ctx)
        self._lock = asyncio.Lock()

    @property
    def processors(self):
        return self._inner.processors

    async def connect(self) -> None:
        # warmup's compiles/collectives happen here on the primary; the
        # followers run the identical connect() themselves — same order
        await self._inner.connect()

    async def process(self, batch):
        from arkflow_tpu.connect.flight import batch_to_ipc

        async with self._lock:
            ipc = batch_to_ipc(batch.record_batch)
            await asyncio.to_thread(self.channel.send, ipc)
            return await self._inner.process(batch)

    async def close(self) -> None:
        async with self._lock:
            await asyncio.to_thread(self.channel.close)
        await self._inner.close()


async def run_follower(ctx: MultihostContext, pipeline) -> None:
    """The follower loop: replay every batch the primary broadcasts through
    the identical local pipeline, discarding outputs (the primary owns the
    wire). Exits when the primary closes the channel.

    A follower-side processing error is logged and the loop continues: the
    computation is deterministic and device-spanning, so the primary saw
    the same failure and answered the client; both sides stay in step for
    the next batch."""
    from arkflow_tpu.batch import MessageBatch
    from arkflow_tpu.connect.flight import ipc_to_batches

    chan = BroadcastChannel(ctx)
    logger.info("multihost follower %d/%d: replay loop up",
                ctx.process_id, ctx.num_processes)
    while True:
        payload = await asyncio.to_thread(chan.recv)
        if payload is None:
            logger.info("multihost follower %d: primary closed; exiting",
                        ctx.process_id)
            return
        try:
            for rb in ipc_to_batches(payload):
                await pipeline.process(MessageBatch(rb))
        except Exception:
            logger.exception("multihost follower %d: replay step failed "
                             "(primary saw the same outcome)",
                             ctx.process_id)
