"""Multi-host distributed runtime (DCN tier).

One-call bootstrap over ``jax.distributed``: every host runs the same engine
binary, the JAX runtime forms the global device mesh across hosts (ICI within
a slice, DCN between), and the existing ``MeshSpec``/``shard_params`` path
works unchanged on the global device list. This is the XLA-collective
equivalent of a NCCL/MPI communication backend — collectives are compiled
into the program rather than hand-driven (SURVEY.md section 2.7: the
reference's only cross-node mechanisms are broker protocols and Ballista).

Environment-variable driven so k8s/slurm launchers need no config changes:

    ARKFLOW_COORDINATOR=host0:1234 ARKFLOW_NUM_PROCESSES=4 ARKFLOW_PROCESS_ID=2
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("arkflow.distributed")


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Initialize jax.distributed from args or ARKFLOW_* env; returns True if
    multi-process mode was activated (False = single host, no-op)."""
    coordinator = coordinator or os.environ.get("ARKFLOW_COORDINATOR")
    if not coordinator:
        return False
    import jax  # deferred: single-host pipelines shouldn't touch jax here
    num_processes = int(num_processes or os.environ.get("ARKFLOW_NUM_PROCESSES", "1"))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("ARKFLOW_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "distributed runtime up: process %d/%d, %d global / %d local devices",
        process_id, num_processes, jax.device_count(), jax.local_device_count(),
    )
    return True
