"""Ring attention: exact attention over sequence shards on a ring.

Long-context first-class path: the sequence axis is sharded over the ``sp``
mesh axis; each device holds Q/K/V chunks of shape [B, S/n, H, Dh] and the
K/V blocks rotate around the ring with ``lax.ppermute`` (one ICI hop per
step) while a streaming (online-softmax) accumulator folds each block in —
attention memory stays O(S/n) per chip and communication overlaps compute.
This is the blockwise/ring pattern referenced in SURVEY.md sections 2.7/5
(the reference engine has no model execution; its closest analog is window
buffers bounding context) expressed with XLA collectives instead of NCCL.

Numerics: scores/softmax accumulate in float32 regardless of input dtype;
causal masking uses global positions derived from the shard index.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

_NEG = -1e30


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Runs inside shard_map: q/k/v local chunks [B, Sl, H, Dh]."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, sl, h, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    qf = q.astype(jnp.float32)

    q_pos = idx * sl + jnp.arange(sl)  # global positions of local queries

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % n  # whose K/V block we hold at this step
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * sl + jnp.arange(sl)
            allowed = k_pos[None, :] <= q_pos[:, None]  # [Sq, Sk]
            scores = jnp.where(allowed[None, None], scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32))
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt

    o0 = jnp.zeros((b, h, sl, dh), jnp.float32)
    m0 = jnp.full((b, h, sl), _NEG, jnp.float32)
    l0 = jnp.zeros((b, h, sl), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention_spec(mesh: Mesh, sp_axis: str = "sp",
                             batch_axis: str | None = None,
                             head_axis: str | None = None, causal: bool = False):
    """Ring attention for use inside a sharded model forward.

    Inputs/outputs are [B, S, H, Dh]: the sequence dim rings over ``sp_axis``;
    the batch dim may be dp-sharded (``batch_axis``) and the head dim
    tp-sharded (``head_axis``) — each tp shard rings only its own heads, so
    attention memory/FLOPs stay O(S/n_sp * H/n_tp) per chip.
    """
    spec = P(batch_axis, sp_axis, head_axis, None)
    return shard_map(
        partial(_ring_attention_local, axis_name=sp_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )


def make_ring_attention(mesh: Mesh, axis: str = "sp", causal: bool = False):
    """Jittable ring attention over ``mesh[axis]`` (sequence-sharded only)."""
    return make_ring_attention_spec(mesh, sp_axis=axis, causal=causal)


def reference_attention(q, k, v, causal: bool = False):
    """Unsharded reference for testing: [B, S, H, Dh]."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores / math.sqrt(dh)
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, _NEG)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
