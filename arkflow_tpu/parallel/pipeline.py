"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

Completes the parallelism family (dp/tp/sp/ep/pp): the decoder's stacked
layer parameters shard along their leading (layer) dimension over ``pp``
stages, and activations stream stage-to-stage with ``jax.lax.ppermute``
inside a ``shard_map`` — the TPU-native expression of pipeline parallelism
(a ring of ICI hops, no NCCL-style send/recv). The classic GPipe schedule
runs M microbatches over ``M + S - 1`` ticks, so all S stages are busy in
the steady state and the bubble is (S-1)/(M+S-1).

Scope: dense decoder configs (MoE routes through ep, long context through
sp/ring attention — composing those with pp is future work; the builder
rejects the combinations). dp composes: the batch shards over ``dp`` while
each dp-replica's pipeline runs over ``pp``.

Correctness bar (tested): pp loss == single-device loss to float tolerance,
and grads flow to every stage's parameters (embedding/head replicate; their
grads psum across stages via the shard_map transpose).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.models import common as cm
from arkflow_tpu.models.decoder import DecoderConfig, _attention_block, _mlp


def pp_param_specs(cfg: DecoderConfig) -> dict:
    """Layer stacks shard over pp on the layer dim; the rest replicates."""
    layer = {
        "attn_norm": {"scale": P("pp")},
        "wq": {"w": P("pp")}, "wk": {"w": P("pp")}, "wv": {"w": P("pp")},
        "wo": {"w": P("pp")},
        "mlp_norm": {"scale": P("pp")},
        "w_gate": {"w": P("pp")}, "w_up": {"w": P("pp")}, "w_down": {"w": P("pp")},
    }
    return {
        "embed": {"table": P()},
        "norm_out": {"scale": P()},
        "lm_head": {"w": P()},
        "layers": layer,
    }


def _stage_apply(lp_stack, x, cfg: DecoderConfig, positions, causal):
    """Run this stage's local layer stack (the shared dense block math)."""

    def layer(x, lp):
        x = _attention_block(lp, x, cfg, positions, causal)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        return x + _mlp(lp, y, cfg), None

    x, _ = jax.lax.scan(layer, x, lp_stack)
    return x


def make_pp_train_step(cfg: DecoderConfig, optimizer, mesh: Mesh, *,
                       microbatches: int | None = None):
    """Pipeline-parallel training step over mesh axes (dp, pp).

    Returns ``train_step(params, opt_state, batch) -> (params, opt_state,
    loss)``; jit it under the mesh. ``batch`` carries input_ids/targets/mask
    sharded over dp. Params must be placed with ``pp_param_specs`` (layer
    stacks split across stages).
    """
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    if cfg.num_experts > 1:
        raise ConfigError("pipeline parallelism + MoE (ep) is not composed yet")
    if cfg.use_ring_attention:
        raise ConfigError("pipeline parallelism + ring attention is not composed yet")
    stages = mesh.shape["pp"]
    if cfg.layers % stages != 0:
        raise ConfigError(f"layers ({cfg.layers}) must divide by pp stages ({stages})")
    n_micro = microbatches or stages
    perm = [(i, (i + 1) % stages) for i in range(stages)]

    def pp_loss(params, ids, targets, mask):
        """Runs per-device under shard_map: layer stack is the LOCAL shard."""
        stage = jax.lax.axis_index("pp")
        b, s = ids.shape
        if b % n_micro != 0:
            raise ConfigError(
                f"per-replica batch {b} must divide by microbatches {n_micro}")
        mb = b // n_micro
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (mb, s))
        causal = jnp.tril(jnp.ones((s, s), bool))[None, None]

        # every stage embeds (params replicate; trivial FLOPs) — only stage
        # 0's result enters the pipeline, but a uniform program keeps SPMD
        x = cm.embedding(params["embed"], ids)                     # [B, S, D]
        mb_x = x.reshape(n_micro, mb, s, cfg.dim)

        def tick(cur, t):
            # stage 0 ingests microbatch t (clamped; ticks >= M recirculate
            # garbage that never reaches a valid output slot)
            inject = jax.lax.dynamic_index_in_dim(
                mb_x, jnp.minimum(t, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, inject, cur)
            out = _stage_apply(params["layers"], inp, cfg, positions, causal)
            nxt = jax.lax.ppermute(out, "pp", perm)
            return nxt, out

        zeros = jnp.zeros((mb, s, cfg.dim), x.dtype)
        _, outs = jax.lax.scan(tick, zeros, jnp.arange(n_micro + stages - 1))
        # the LAST stage's outputs at ticks S-1 .. S-1+M-1 are the finished
        # microbatches, in order
        final = outs[stages - 1:stages - 1 + n_micro]              # [M, mb, S, D]
        h = final.reshape(b, s, cfg.dim)
        h = cm.rms_norm(params["norm_out"], h, cfg.norm_eps)
        logits = cm.dense(params["lm_head"], h).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        maskf = mask.astype(jnp.float32)
        local = -(ll * maskf).sum() / jnp.maximum(maskf.sum(), 1.0)
        # only the last stage computed real logits; broadcast its loss
        loss = jax.lax.psum(jnp.where(stage == stages - 1, local, 0.0), "pp")
        return jax.lax.pmean(loss, "dp")

    specs = pp_param_specs(cfg)
    data_spec = P("dp")
    kwargs = dict(mesh=mesh, in_specs=(specs, data_spec, data_spec, data_spec),
                  out_specs=P())
    try:  # jax>=0.8 renamed the replication-check knob
        loss_fn = shard_map(pp_loss, **kwargs, check_vma=False)
    except TypeError:
        loss_fn = shard_map(pp_loss, **kwargs, check_rep=False)

    def train_step(params, opt_state, batch):
        import optax

        loss, grads = jax.value_and_grad(loss_fn)(
            params, batch["input_ids"], batch["targets"], batch["mask"])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
