"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

Completes the parallelism family (dp/tp/sp/ep/pp): the decoder's stacked
layer parameters shard along their leading (layer) dimension over ``pp``
stages, and activations stream stage-to-stage with ``jax.lax.ppermute``
inside a ``shard_map`` — the TPU-native expression of pipeline parallelism
(a ring of ICI hops, no NCCL-style send/recv). The classic GPipe schedule
runs M microbatches over ``M + S - 1`` ticks, so all S stages are busy in
the steady state and the bubble is (S-1)/(M+S-1).

Scope: dense decoder configs (MoE routes through ep, long context through
sp/ring attention — composing those with pp is future work; the builder
rejects the combinations). dp composes: the batch shards over ``dp`` while
each dp-replica's pipeline runs over ``pp``.

Correctness bar (tested): pp loss == single-device loss to float tolerance,
and grads flow to every stage's parameters (embedding/head replicate; their
grads psum across stages via the shard_map transpose).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.models import common as cm
from arkflow_tpu.models.decoder import DecoderConfig, _attention_block, _mlp
from arkflow_tpu.parallel.segment import StagePlan


def _shard_map():
    try:
        from jax import shard_map  # jax >= 0.8
        return shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
        return shard_map


def pp_param_specs(cfg: DecoderConfig) -> dict:
    """Layer stacks shard over pp on the layer dim; the rest replicates."""
    layer = {
        "attn_norm": {"scale": P("pp")},
        "wq": {"w": P("pp")}, "wk": {"w": P("pp")}, "wv": {"w": P("pp")},
        "wo": {"w": P("pp")},
        "mlp_norm": {"scale": P("pp")},
        "w_gate": {"w": P("pp")}, "w_up": {"w": P("pp")}, "w_down": {"w": P("pp")},
    }
    return {
        "embed": {"table": P()},
        "norm_out": {"scale": P()},
        "lm_head": {"w": P()},
        "layers": layer,
    }


def _stage_apply(lp_stack, x, cfg: DecoderConfig, positions, causal):
    """Run this stage's local layer stack (the shared dense block math)."""

    def layer(x, lp):
        x = _attention_block(lp, x, cfg, positions, causal)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        return x + _mlp(lp, y, cfg), None

    x, _ = jax.lax.scan(layer, x, lp_stack)
    return x


def make_pp_train_step(cfg: DecoderConfig, optimizer, mesh: Mesh, *,
                       microbatches: int | None = None):
    """Pipeline-parallel training step over mesh axes (dp, pp).

    Returns ``train_step(params, opt_state, batch) -> (params, opt_state,
    loss)``; jit it under the mesh. ``batch`` carries input_ids/targets/mask
    sharded over dp. Params must be placed with ``pp_param_specs`` (layer
    stacks split across stages).
    """
    shard_map = _shard_map()

    if cfg.num_experts > 1:
        raise ConfigError("pipeline parallelism + MoE (ep) is not composed yet")
    if cfg.use_ring_attention:
        raise ConfigError("pipeline parallelism + ring attention is not composed yet")
    stages = mesh.shape["pp"]
    if cfg.layers % stages != 0:
        raise ConfigError(f"layers ({cfg.layers}) must divide by pp stages ({stages})")
    n_micro = microbatches or stages
    perm = [(i, (i + 1) % stages) for i in range(stages)]

    def pp_loss(params, ids, targets, mask):
        """Runs per-device under shard_map: layer stack is the LOCAL shard."""
        stage = jax.lax.axis_index("pp")
        b, s = ids.shape
        if b % n_micro != 0:
            raise ConfigError(
                f"per-replica batch {b} must divide by microbatches {n_micro}")
        mb = b // n_micro
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (mb, s))
        causal = jnp.tril(jnp.ones((s, s), bool))[None, None]

        # every stage embeds (params replicate; trivial FLOPs) — only stage
        # 0's result enters the pipeline, but a uniform program keeps SPMD
        x = cm.embedding(params["embed"], ids)                     # [B, S, D]
        mb_x = x.reshape(n_micro, mb, s, cfg.dim)

        def tick(cur, t):
            # stage 0 ingests microbatch t (clamped; ticks >= M recirculate
            # garbage that never reaches a valid output slot)
            inject = jax.lax.dynamic_index_in_dim(
                mb_x, jnp.minimum(t, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, inject, cur)
            out = _stage_apply(params["layers"], inp, cfg, positions, causal)
            nxt = jax.lax.ppermute(out, "pp", perm)
            return nxt, out

        zeros = jnp.zeros((mb, s, cfg.dim), x.dtype)
        _, outs = jax.lax.scan(tick, zeros, jnp.arange(n_micro + stages - 1))
        # the LAST stage's outputs at ticks S-1 .. S-1+M-1 are the finished
        # microbatches, in order
        final = outs[stages - 1:stages - 1 + n_micro]              # [M, mb, S, D]
        h = final.reshape(b, s, cfg.dim)
        h = cm.rms_norm(params["norm_out"], h, cfg.norm_eps)
        logits = cm.dense(params["lm_head"], h).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        maskf = mask.astype(jnp.float32)
        local = -(ll * maskf).sum() / jnp.maximum(maskf.sum(), 1.0)
        # only the last stage computed real logits; broadcast its loss
        loss = jax.lax.psum(jnp.where(stage == stages - 1, local, 0.0), "pp")
        return jax.lax.pmean(loss, "dp")

    specs = pp_param_specs(cfg)
    data_spec = P("dp")
    kwargs = dict(mesh=mesh, in_specs=(specs, data_spec, data_spec, data_spec),
                  out_specs=P())
    try:  # jax>=0.8 renamed the replication-check knob
        loss_fn = shard_map(pp_loss, **kwargs, check_vma=False)
    except TypeError:
        loss_fn = shard_map(pp_loss, **kwargs, check_rep=False)

    def train_step(params, opt_state, batch):
        import optax

        loss, grads = jax.value_and_grad(loss_fn)(
            params, batch["input_ids"], batch["targets"], batch["mask"])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


# -- pipelined INFERENCE (profiled segmentation serving) ---------------------
#
# The serving twin of the train step above: stage-sharded layer stacks, the
# same ppermute ring and GPipe tick scan, but forward-only and driven by a
# StagePlan (parallel/segment.py) so stages can hold UNEVEN layer ranges when
# a measured profile says the balanced cut is uneven. Families plug in via an
# extras hook ``pp_stage_fns(cfg) -> (pre_fn, layer_fn, post_fn)``:
#
#   pre_fn(params, inputs)   -> (x, aux)   embeddings + per-batch side inputs
#   layer_fn(lp, x, aux)     -> x          ONE layer (math identical to the
#                                          family's single-device scan body)
#   post_fn(params, x, aux)  -> {outputs}  head (logits/labels/scores)
#
# Every stage runs pre_fn/post_fn on replicated params (trivial FLOPs — the
# uniform program keeps SPMD); only the last stage's head output is real, and
# a masked psum broadcasts it so the step returns replicated outputs.


def pp_layer_slot_tables(plan: StagePlan) -> tuple[np.ndarray, np.ndarray]:
    """Per-stage layer slot tables for an (possibly uneven) plan.

    Stages scan a PADDED local stack of ``Lmax = max(plan.sizes)`` slots so
    the sharded layer array stays rectangular; ``index[s, j]`` is the source
    layer for stage ``s`` slot ``j`` (filler slots point at layer 0) and
    ``active[s, j]`` marks real slots — the executor skips inactive slots
    with ``lax.cond``, so a short stage pays for ITS layers, not Lmax.
    """
    lmax = max(plan.sizes)
    index = np.zeros((plan.stages, lmax), np.int32)
    active = np.zeros((plan.stages, lmax), bool)
    for s, (start, end) in enumerate(plan.bounds):
        n = end - start
        index[s, :n] = np.arange(start, end, dtype=np.int32)
        active[s, :n] = True
    return index, active


def pp_repack_layers(params: dict, plan: StagePlan):
    """Repack a family's stacked ``params["layers"]`` (leading dim = layer)
    into the stage-padded layout ``[S * Lmax, ...]`` the pp executor shards
    over ``pp``: stage ``s`` owns slots ``s*Lmax .. (s+1)*Lmax - 1``, real
    layers first, filler slots repeating layer 0 (never executed — the slot
    table masks them). Host-side; returns a NEW params dict."""
    index, _ = pp_layer_slot_tables(plan)
    flat_idx = jnp.asarray(index.reshape(-1))

    def take(leaf):
        if plan.num_layers == 0 or leaf.shape[0] != plan.num_layers:
            raise ConfigError(
                f"pp repack: layer stack leaf has leading dim {leaf.shape[0]}, "
                f"expected {plan.num_layers} (the plan's layer count)")
        return jnp.take(leaf, flat_idx, axis=0)

    out = dict(params)
    out["layers"] = jax.tree_util.tree_map(take, params["layers"])
    return out


def pp_infer_param_specs(params: dict) -> dict:
    """PartitionSpec pytree for pp serving over REPACKED params: layer slots
    shard over ``pp`` on the leading dim, everything else replicates (embed/
    head run on every stage). Built from the actual (possibly quantized)
    tree, so int8's {w_q, w_scale} leaves need no spec rewrite."""
    return {
        k: jax.tree_util.tree_map(lambda _: P("pp") if k == "layers" else P(), v)
        for k, v in params.items()
    }


def make_pp_infer_step(family, cfg, mesh: Mesh, *, plan: StagePlan,
                       microbatch_rows: int, param_specs: Optional[dict] = None):
    """Pipeline-parallel INFERENCE step over mesh axes (dp, pp).

    Returns ``infer_fn(params, inputs) -> outputs`` to be jitted (the runner
    owns jit/donation/shardings). ``inputs`` are the family's input_spec
    arrays, batch-leading; params must be repacked (``pp_repack_layers``)
    and placed with ``pp_infer_param_specs`` — pass that same spec tree as
    ``param_specs`` (it becomes the shard_map in_specs, so the wrapped
    function's partitioning can never disagree with the placement).

    Schedule: the per-replica batch ``b`` splits into ``M = b /
    microbatch_rows`` microbatches streamed through S stages over
    ``M + S - 1`` ticks (GPipe forward). M is derived from the static batch
    shape, so every bucket keeps its own bucket-exact microbatch count and
    the analytic bubble is (S-1)/(M+S-1) per compiled shape.
    """
    extras = family.extras or {}
    if "pp_stage_fns" not in extras:
        raise ConfigError(
            f"model {family.name!r} has no pipeline-parallel serving support "
            "(family extras lack pp_stage_fns)")
    pre_fn, layer_fn, post_fn = extras["pp_stage_fns"](cfg)
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    stages = int(axis_sizes.get("pp", 1))
    if stages != plan.stages:
        raise ConfigError(
            f"pp mesh has {stages} stages but the plan cuts {plan.stages}")
    if microbatch_rows < 1:
        raise ConfigError(
            f"pp microbatch_rows must be >= 1, got {microbatch_rows}")
    if param_specs is None:
        raise ConfigError(
            "make_pp_infer_step requires param_specs "
            "(pp_infer_param_specs over the repacked tree)")
    perm = [(i, (i + 1) % stages) for i in range(stages)]
    index_tbl, active_tbl = pp_layer_slot_tables(plan)
    lmax = index_tbl.shape[1]

    def pp_infer(params, inputs):
        """Runs per-device under shard_map: ``params['layers']`` is the
        LOCAL [Lmax, ...] stage shard; inputs are the dp-local batch."""
        stage = jax.lax.axis_index("pp")
        x, aux = pre_fn(params, inputs)
        b = x.shape[0]
        mb = min(microbatch_rows, b)
        if b % mb != 0:
            raise ConfigError(
                f"pp: per-replica batch {b} must divide by microbatch rows "
                f"{mb} (align the bucket grid with pp_microbatch_rows)")
        n_micro = b // mb
        mb_x = x.reshape(n_micro, mb, *x.shape[1:])
        mb_aux = jax.tree_util.tree_map(
            lambda a: a.reshape(n_micro, mb, *a.shape[1:]), aux)
        active = jnp.asarray(active_tbl)[stage]  # [Lmax] bool, this stage's

        def stage_apply(h, aux_j):
            if plan.uniform:
                # even cut: every slot is real — plain scan, no masking
                def body(h, lp):
                    return layer_fn(lp, h, aux_j), None
                h, _ = jax.lax.scan(body, h, params["layers"])
                return h

            def body(h, slot):
                lp, act = slot
                # cond (not where): a filler slot SKIPS its layer math, so a
                # 2-layer stage next to a 4-layer stage costs 2 layers/tick
                return jax.lax.cond(
                    act, lambda t: layer_fn(lp, t, aux_j), lambda t: t, h), None

            h, _ = jax.lax.scan(body, h, (params["layers"], active))
            return h

        def tick(cur, t):
            # stage 0 ingests microbatch t (clamped: ticks >= M recirculate
            # garbage that never reaches a valid output slot); stage s is
            # processing microbatch t - s, so its side inputs index there
            inject = jax.lax.dynamic_index_in_dim(
                mb_x, jnp.minimum(t, n_micro - 1), 0, keepdims=False)
            j = jnp.clip(t - stage, 0, n_micro - 1)
            aux_j = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j, 0, keepdims=False),
                mb_aux)
            inp = jnp.where(stage == 0, inject, cur)
            out = stage_apply(inp, aux_j)
            nxt = jax.lax.ppermute(out, "pp", perm)
            return nxt, out

        zeros = jnp.zeros((mb, *x.shape[1:]), x.dtype)
        _, outs = jax.lax.scan(tick, zeros, jnp.arange(n_micro + stages - 1))
        # the LAST stage's outputs at ticks S-1 .. S-1+M-1 are the finished
        # microbatches, in order (garbage on every other stage)
        final = outs[stages - 1:stages - 1 + n_micro]
        h = final.reshape(b, *x.shape[1:])
        out = post_fn(params, h, aux)

        def bcast(leaf):
            # only the last stage computed real outputs; mask-then-psum
            # broadcasts them (adding exact zeros — argmax/bitwise safe for
            # every representable value except -0.0 -> +0.0)
            masked = jnp.where(stage == stages - 1, leaf,
                               jnp.zeros_like(leaf))
            return jax.lax.psum(masked, "pp")

        return jax.tree_util.tree_map(bcast, out)

    data_spec = P("dp")
    kwargs = dict(mesh=mesh, in_specs=(param_specs, data_spec),
                  out_specs=data_spec)
    try:  # jax>=0.8 renamed the replication-check knob
        return _shard_map()(pp_infer, **kwargs, check_vma=False)
    except TypeError:
        return _shard_map()(pp_infer, **kwargs, check_rep=False)
