"""Device mesh + sharding helpers.

The reference's only multi-node mechanism is Ballista SQL offload
(SURVEY.md section 2.7); it has no model parallelism. Here multi-chip scale is
first-class: a ``jax.sharding.Mesh`` over (dp, tp, sp) axes, parameter
PartitionSpec pytrees from each model family, and GSPMD inserting the
collectives (the scaling-book recipe: pick a mesh, annotate shardings, let XLA
place psum/all-gather/reduce-scatter on ICI).

Axes:
- ``dp``  data parallel (batch)
- ``tp``  tensor parallel (heads / FFN)
- ``sp``  sequence parallel (long-context; pairs with ring attention)
- ``ep``  expert parallel (MoE dispatch/combine)
- ``pp``  pipeline parallel (layer stages; parallel/pipeline.py schedule)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1  # expert parallel (MoE)
    pp: int = 1  # pipeline parallel (layer stages)
    axis_names: tuple = ("dp", "tp", "sp", "ep", "pp")

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.sp * self.ep * self.pp


def create_mesh(spec: Optional[MeshSpec] = None, devices=None) -> Mesh:
    """Build a Mesh; defaults to all devices on the dp axis."""
    devices = devices if devices is not None else jax.devices()
    if spec is None:
        spec = MeshSpec(dp=len(devices))
    if spec.num_devices > len(devices):
        raise ValueError(
            f"mesh {spec} needs {spec.num_devices} devices, have {len(devices)}"
        )
    arr = np.array(devices[: spec.num_devices]).reshape(
        spec.dp, spec.tp, spec.sp, spec.ep, spec.pp)
    return Mesh(arr, spec.axis_names)


def shard_params(params, specs, mesh: Mesh):
    """Place a param pytree onto the mesh per a PartitionSpec pytree.

    ``specs`` must mirror the param tree (model families produce it via
    ``param_specs``); ``None`` replicates everything.
    """

    def place(x, spec):
        s = NamedSharding(mesh, spec if spec is not None else P())
        return jax.device_put(x, s)

    if specs is None:
        return jax.tree_util.tree_map(lambda x: place(x, None), params)
    return jax.tree_util.tree_map(
        place, params, specs, is_leaf=lambda x: x is None or isinstance(x, P)
    )


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def dp_size(mesh: Mesh) -> int:
    """Size of the data-parallel axis (1 when the mesh has no ``dp``)."""
    return int(dict(zip(mesh.axis_names, mesh.devices.shape)).get("dp", 1))


def tp_size(mesh: Mesh) -> int:
    """Size of the tensor-parallel axis (1 when the mesh has no ``tp``)."""
    return int(dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement on the mesh (scalars, page tables, token
    ids — everything the paged serving path keeps static-shaped and global)."""
    return NamedSharding(mesh, P())


def kv_pool_shardings(mesh: Mesh) -> tuple[NamedSharding, NamedSharding]:
    """Shardings for the paged KV pools under tensor-parallel serving.

    Returns ``(full_pool, per_layer)``: the full pool is
    ``[layers, num_pages, page, kv_heads, dh]`` (the jitted steps' in/out
    sharding), the per-layer slice inside the layer scan is
    ``[num_pages, page, kv_heads, dh]`` (applied as a sharding constraint so
    GSPMD keeps the pools partitioned instead of all-gathering hundreds of
    MB per step). KV heads split over ``tp``; the page dims stay replicated,
    so page-table gathers/scatters remain static-shaped and local."""
    if tp_size(mesh) > 1:
        return (NamedSharding(mesh, P(None, None, None, "tp", None)),
                NamedSharding(mesh, P(None, None, "tp", None)))
    return replicated(mesh), replicated(mesh)


def validate_tp_heads(tp: int, kv_heads: int, who: str = "serving") -> None:
    """Tensor-parallel serving shards attention state over KV heads, so the
    tp degree must divide ``kv_heads`` (GQA keeps ``heads % kv_heads == 0``,
    so query heads divide automatically)."""
    if tp > 1 and kv_heads % tp != 0:
        from arkflow_tpu.errors import ConfigError

        raise ConfigError(
            f"{who}: mesh tp={tp} must divide the model's kv_heads={kv_heads} "
            "(the KV cache shards over heads on the tp axis)")


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for model INPUTS/OUTPUTS under serving: leading (batch) dim
    split over ``dp``, everything else replicated. On a mesh without a dp
    axis (or dp=1) this degenerates to full replication, which is exactly
    what tensor-parallel-only serving wants for its activations' batch dim."""
    return NamedSharding(mesh, P("dp") if dp_size(mesh) > 1 else P())


def param_shardings(params):
    """The sharding each param leaf ALREADY has (post ``shard_params``), as a
    pytree usable for ``jax.jit``'s ``in_shardings`` — pinning params to
    their placement keeps a host-numpy input from dragging them through a
    fresh layout decision on every executable."""
    return jax.tree_util.tree_map(lambda x: x.sharding, params)
