"""ArkFlow-TPU: a TPU-native streaming dataflow engine.

A ground-up re-design of the capabilities of ArkFlow (arkflow-rs/arkflow, a
Rust/Tokio/Arrow/DataFusion stream-processing engine) for TPU hardware:

- Arrow ``RecordBatch`` data plane with queryable ``__meta_*`` metadata columns.
- Config-driven streams of input -> buffer -> processors -> output, with
  ack-based at-least-once delivery, backpressure and ordered emission.
- Streaming ML inference processors that JIT-compile models with XLA and keep
  the TPU fed with fixed-shape micro-batches (shape bucketing + executable
  cache), sharded over ``jax.sharding.Mesh`` for multi-chip scale.

Layer map (mirrors reference SURVEY.md section 1):

- ``arkflow_tpu.batch``        data plane (ref: crates/arkflow-core/src/lib.rs)
- ``arkflow_tpu.components``   component traits + registries (ref: arkflow-core/src/{input,output,...})
- ``arkflow_tpu.runtime``      stream runtime / pipeline / engine / CLI
- ``arkflow_tpu.config``       typed config (YAML/JSON/TOML)
- ``arkflow_tpu.plugins``      all concrete components (ref: arkflow-plugin)
- ``arkflow_tpu.sql``          Arrow-native SQL engine (DataFusion equivalent)
- ``arkflow_tpu.tpu``          XLA execution layer: bucketing, executable cache, infeed
- ``arkflow_tpu.models``       model families (BERT, ViT, LSTM-AE, decoder LM)
- ``arkflow_tpu.ops``          Pallas kernels
- ``arkflow_tpu.parallel``     mesh/sharding/collectives/ring attention
- ``arkflow_tpu.native``       C++ host-runtime tier (ctypes)
- ``arkflow_tpu.obs``          metrics + tracing
"""

__version__ = "0.1.0"

from arkflow_tpu.errors import (  # noqa: F401
    ArkError,
    CodecError,
    ConfigError,
    ConnectError,
    Disconnection,
    EndOfInput,
    ProcessError,
    ReadError,
    UnsupportedSql,
    WriteError,
)
from arkflow_tpu.batch import MessageBatch  # noqa: F401


def run(config_path: str) -> None:
    """Library entry point: run an engine from a config file (blocks until
    the streams finish or SIGINT/SIGTERM)."""
    import asyncio

    from arkflow_tpu.config import EngineConfig
    from arkflow_tpu.runtime.engine import Engine

    asyncio.run(Engine(EngineConfig.from_file(config_path)).run())
