"""Error taxonomy.

Single error family with typed control-flow variants, mirroring the reference's
``Error`` enum (ref: crates/arkflow-core/src/lib.rs:66-110). Two variants are
control flow, not failures:

- ``EndOfInput``  -- graceful end of a finite source; the stream drains and shuts
  down (ref ``Error::EOF``, stream/mod.rs:178-181).
- ``Disconnection`` -- transient transport loss; the input task enters a
  reconnect loop (ref ``Error::Disconnection``, stream/mod.rs:183-194).
"""

from __future__ import annotations


class ArkError(Exception):
    """Base class for all engine errors."""


class ConfigError(ArkError):
    """Invalid or missing configuration."""


class ConnectError(ArkError):
    """Failed to establish a connection to an external system."""


class ReadError(ArkError):
    """Failed to read from an input."""


class FrameIntegrityError(ReadError):
    """A flight frame failed its crc32 integrity check: the bytes on the
    wire do not match what the peer sent. Corruption is never silent —
    the message names the frame class (infer request, kv_push slab, ...)
    so a flipped bit in a raw bf16 slab surfaces as a loud, attributable
    error instead of garbage logits."""


class WriteError(ArkError):
    """Failed to write to an output."""


class ProcessError(ArkError):
    """A processor failed on a batch."""


class CodecError(ArkError):
    """Encode/decode failure."""


class EndOfInput(ArkError):
    """Control flow: the input is exhausted; shut the stream down gracefully."""

    def __init__(self, msg: str = "end of input"):
        super().__init__(msg)


class Disconnection(ArkError):
    """Control flow: transient disconnect; the runtime retries the connection."""

    def __init__(self, msg: str = "disconnected"):
        super().__init__(msg)


class Overloaded(ArkError):
    """The engine is shedding load: admission rejected the batch/request
    before the worker queue (deadline cannot be met, queue window full, or
    priority band browned out). Carries the controller's drain estimate so
    transports can tell clients when to retry (HTTP 429 ``Retry-After``)."""

    def __init__(self, msg: str = "overloaded", retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class StepDeadlineExceeded(ArkError):
    """A device step missed its ``step_deadline``: the runner treats the
    device as hung (UNHEALTHY), abandons the in-flight step, and the stream
    nacks the batch so the source redelivers (at-least-once preserved)."""


class RunnerDead(ArkError):
    """A runner (or every member of a device pool) exhausted its recovery
    probes and was marked DEAD; batches can no longer be served by it."""


class SwapError(ArkError):
    """A live model hot-swap (``tpu/swap.py``) was rejected or rolled back:
    the candidate checkpoint failed to restore, the canary found the new
    weights disagreeing with the live model, a post-flip probe failed, or a
    swap was already in progress. The PRIOR params are serving throughout —
    a SwapError never implies an interruption of traffic."""


class TunerError(ArkError):
    """A runtime shape retune (``tpu/tuner.py``) was rejected or rolled
    back: the post-flip probe failed on the proposed grid, so every flipped
    unit re-adopted the incumbent bucket configuration. Like ``SwapError``,
    a TunerError never implies an interruption of traffic — the incumbent
    shapes served throughout, and no coalescer or cache was touched."""


class UnsupportedSql(ArkError):
    """Raised by the Arrow-native SQL planner when a query needs the fallback engine."""
