"""Replicated device pool: N independent single-device runners, one dispatcher.

The dp-mesh path (``ModelRunner`` + ``mesh: {dp: N}``) scales throughput by
splitting every batch over the chips with GSPMD — ideal for large buckets,
but every step pays collective/partitioning overhead and the whole pool runs
in lockstep. Small-bucket / latency-bound traffic scales better the dumb way:
``device_pool: N`` builds N fully independent single-device ``ModelRunner``s
with REPLICATED params (one host init/restore, N one-hop transfers) behind a
least-loaded round-robin dispatcher. No collectives, no GSPMD — each member
keeps flash attention, staging pools, input donation, and eager prefetch
exactly as in single-device serving, and concurrent stream workers fan out
across chips instead of queueing on one.

Failover preserves at-least-once delivery: a member that throws mid-step is
skipped for that batch and the batch retries on the remaining members; only
when EVERY member fails does the error propagate (and the stream nacks, so
the source redelivers). Deterministic config errors (bad input spec) are NOT
retried — they would fail identically on every chip.

Health-aware dispatch (the self-healing layer): every member carries a
``RunnerHealth`` state machine. ``_pick`` skips UNHEALTHY/DEAD members, and
when a suspect's recovery probe is due it is re-admitted by routing ONE real
batch to it first (claimed via ``try_begin_probe`` so concurrent workers
don't pile onto a maybe-still-hung chip); a successful probe promotes the
member back to HEALTHY, a failed one backs the probe schedule off further.
When nothing is dispatchable — every member mid-backoff — the dispatcher
waits for the earliest probe window instead of failing, so transient
whole-pool incidents heal without losing batches.

Per-chip observability: each member's runner metrics carry a ``device`` label
(``arkflow_tpu_device_busy_seconds_total{device="3"}`` ...), and the pool adds
dispatch/failover/skip/probe counters so imbalance or a limping chip shows up
directly.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

import numpy as np

from arkflow_tpu.errors import ConfigError, RunnerDead
from arkflow_tpu.tpu.health import CORRUPT, DEAD, DEGRADED, HEALTHY, UNHEALTHY
from arkflow_tpu.obs import global_registry
from arkflow_tpu.tpu.bucketing import BucketPolicy
from arkflow_tpu.tpu.runner import (ModelRunner, convert_for_serving,
                                    init_host_params)

logger = logging.getLogger("arkflow.tpu")


class ModelRunnerPool:
    """Drop-in for ``ModelRunner`` over N replicated single-device members.

    Exposes the same surface the ``tpu_inference`` processor uses (``spec``,
    ``buckets``, ``cfg``, ``family``, ``infer``/``infer_sync``/``warmup``),
    so processors don't branch on pool-vs-single beyond construction.
    """

    def __init__(
        self,
        model: str,
        model_config: Optional[dict] = None,
        *,
        pool_size: int,
        buckets: Optional[BucketPolicy] = None,
        checkpoint: Optional[str] = None,
        seed: int = 0,
        devices=None,
        serving_dtype: Optional[str] = None,
        max_in_flight: Optional[int] = None,
        dispatch_depth: Optional[int] = None,
        packed: bool = False,
        step_deadline_s: Optional[float] = None,
        step_deadline_first_s: Optional[float] = None,
        health_config=None,
    ):
        import jax

        if pool_size < 1:
            raise ConfigError(f"device_pool must be >= 1, got {pool_size}")
        devices = list(devices) if devices is not None else jax.devices()
        if pool_size > len(devices):
            raise ConfigError(
                f"device_pool: {pool_size} runners requested, "
                f"{len(devices)} devices visible")
        # one host-side init + checkpoint restore + dtype convert (bf16 cast /
        # int8 quantization); every member transfers the SAME finished tree to
        # its own chip — replication by construction, and the expensive
        # full-tree walks happen once instead of N times
        from arkflow_tpu.models import get_model

        family = get_model(model)
        cfg = family.make_config(**(model_config or {}))
        host_params = convert_for_serving(
            init_host_params(family, cfg, seed, checkpoint),
            serving_dtype, family.name)
        self.members: list[ModelRunner] = [
            ModelRunner(
                model,
                model_config,
                buckets=buckets,
                seed=seed,
                devices=[devices[i]],
                serving_dtype=serving_dtype,
                max_in_flight=max_in_flight,
                dispatch_depth=dispatch_depth,
                packed=packed,
                host_params=host_params,
                device_label=str(i),
                step_deadline_s=step_deadline_s,
                step_deadline_first_s=step_deadline_first_s,
                health_config=health_config,
            )
            for i in range(pool_size)
        ]
        self.pool_size = pool_size
        #: outstanding infer calls per member (the least-loaded signal)
        self._loads = [0] * pool_size
        self._rr = 0  # round-robin cursor for ties
        self._chaos_rr = 0  # separate cursor for injected step faults

        reg = global_registry()
        self.m_dispatch = [
            reg.counter(
                "arkflow_tpu_pool_dispatch_total",
                "batches dispatched to this pool member",
                {"model": model, "device": str(i)})
            for i in range(pool_size)
        ]
        self.m_failover = reg.counter(
            "arkflow_tpu_pool_failover_total",
            "batches retried on another member after a member error",
            {"model": model})
        self.m_skipped = reg.counter(
            "arkflow_tpu_pool_skipped_unhealthy_total",
            "dispatch decisions that passed over >=1 unhealthy/dead member",
            {"model": model})
        self.m_probes = reg.counter(
            "arkflow_tpu_pool_probes_total",
            "recovery probes dispatched to unhealthy members",
            {"model": model})

    # -- ModelRunner surface (delegated) -----------------------------------

    @property
    def family(self):
        return self.members[0].family

    @property
    def cfg(self):
        return self.members[0].cfg

    @property
    def spec(self):
        return self.members[0].spec

    @property
    def buckets(self) -> BucketPolicy:
        return self.members[0].buckets

    @property
    def packed(self) -> bool:
        return self.members[0].packed

    @property
    def max_in_flight(self) -> int:
        # aggregate device-queue depth across the pool (bench worker sizing)
        return sum(m.max_in_flight for m in self.members)

    def duty_cycle(self) -> float:
        cycles = [m.duty_cycle() for m in self.members]
        return sum(cycles) / len(cycles) if cycles else 0.0

    def warmup(self, seq_lens: Optional[list[int]] = None) -> int:
        """Precompile every member's bucket grid. Serial on purpose: member 0
        pays the real compiles, members 1..N-1 replay them from the
        persistent compile cache (identical shapes, identical HLO)."""
        return sum(m.warmup(seq_lens) for m in self.members)

    def inject_step_fault(self, kind: str, duration_s: float = 0.0) -> None:
        """Chaos hook (fault plugin): arm a one-shot device-step fault on one
        member, round-robin across calls so repeated faults spread over the
        pool the way real per-chip incidents would."""
        i = self._chaos_rr % self.pool_size
        self._chaos_rr += 1
        self.members[i].inject_step_fault(kind, duration_s)

    def health_report(self) -> list[dict]:
        """Per-member health snapshots for the engine's ``/health``."""
        return [m.health_report() for m in self.members]

    def swap_units(self) -> list[tuple[str, "ModelRunner"]]:
        """Independently-flippable serving surfaces for a rolling hot-swap
        (tpu/swap.py): each member flips and probes ALONE, in pool order, so
        the dispatcher keeps serving on the other N-1 members throughout —
        the pool's replication is exactly what makes the roll zero-downtime."""
        return [(f"member {i}", m) for i, m in enumerate(self.members)]

    # -- live shape retune surface (tpu/tuner.py) ---------------------------

    def count_new_shapes(self, policy: BucketPolicy) -> int:
        """Executables a retune would still compile, pool-wide. Member 0's
        count is the honest COST estimate (the others replay member 0's
        compiles from the persistent cache, like ``warmup``)."""
        return self.members[0].count_new_shapes(policy)

    def warm_shapes(self, policy: BucketPolicy) -> int:
        """Pre-compile a proposed grid on every member (serial, like
        ``warmup``: member 0 pays the compiles, the rest replay them)."""
        return sum(m.warm_shapes(policy) for m in self.members)

    async def warm_shapes_live(self, policy: BucketPolicy) -> int:
        """Serving-safe warm (see ``ModelRunner.warm_shapes_live``),
        member by member."""
        count = 0
        for m in self.members:
            count += await m.warm_shapes_live(policy)
        return count

    def retarget_buckets(self, policy: BucketPolicy) -> BucketPolicy:
        """Flip every member onto the new grid; returns member 0's prior
        policy (all members share one grid by construction)."""
        old = self.members[0].buckets
        for m in self.members:
            m.retarget_buckets(policy)
        return old

    def dispatch_counts(self) -> dict[tuple, int]:
        """Pool-wide traffic dispatches per padded shape key."""
        out: dict[tuple, int] = {}
        for m in self.members:
            for k, v in m.dispatch_counts().items():
                out[k] = out.get(k, 0) + v
        return out

    # -- dispatch ----------------------------------------------------------

    def _pick(self, exclude: set[int]) -> Optional[int]:
        """Health-aware least-loaded pick, round-robin among ties (the
        cursor advances every pick, so equal-load members take strict
        turns). UNHEALTHY/DEAD members are skipped — except that an
        UNHEALTHY member whose recovery probe is due takes priority (one
        batch re-admits it on success); ``None`` when nothing is
        dispatchable right now."""
        best: Optional[int] = None
        probe: Optional[int] = None
        skipped = False
        now = time.monotonic()
        n = self.pool_size
        for off in range(n):
            i = (self._rr + off) % n
            if i in exclude:
                continue
            h = self.members[i].health
            state = h.state
            if state in (HEALTHY, DEGRADED):
                if best is None or self._loads[i] < self._loads[best]:
                    best = i
            elif state == UNHEALTHY:
                skipped = True
                if probe is None and h.probe_due(now):
                    probe = i
            else:  # DEAD, or CORRUPT (quarantined: only integrity repair
                skipped = True  # re-admits it — never the probe schedule)
        if probe is not None and self.members[probe].health.try_begin_probe(now):
            # the probe outranks healthy members: without routing one real
            # batch at it, a recovered chip would never be re-admitted
            self.m_probes.inc()
            self._rr = (self._rr + 1) % n
            return probe
        if skipped and best is not None:
            self.m_skipped.inc()
        if best is not None:
            self._rr = (self._rr + 1) % n
        return best

    def _all_dead(self, exclude: set[int]) -> bool:
        """Every remaining member is terminally out of dispatch: DEAD, or
        CORRUPT (quarantined for integrity). CORRUPT fails fast like DEAD
        rather than waiting — the batch nacks for redelivery and serves
        after the integrity monitor repairs a member, instead of parking
        live traffic on an unbounded repair."""
        return all(self.members[i].health.state in (DEAD, CORRUPT)
                   for i in range(self.pool_size) if i not in exclude)

    def _probe_wait_s(self, exclude: set[int]) -> float:
        """Time until the earliest untried member may be probed again."""
        waits = [self.members[i].health.seconds_until_probe()
                 for i in range(self.pool_size)
                 if i not in exclude and self.members[i].health.state == UNHEALTHY]
        return min(waits) if waits else 0.05

    def _note_member_failure(self, i: int, e: Exception) -> None:
        """Health bookkeeping for a member step that raised: shared policy on
        the member's serving core (deadline misses and OOMs self-mark inside
        the step; anything else marks UNHEALTHY here) — the same surface any
        dispatcher sitting on ``ServingRunnerCore`` members uses."""
        self.members[i].core.note_external_failure(e)

    def infer_sync(self, inputs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        while True:
            i = self._pick(set())
            if i is not None:
                break
            if self._all_dead(set()):
                raise RunnerDead(
                    "device pool: every member is DEAD or quarantined CORRUPT")
            time.sleep(max(self._probe_wait_s(set()), 0.01))
        self._loads[i] += 1
        self.m_dispatch[i].inc()
        try:
            return self.members[i].infer_sync(inputs)
        except ConfigError:
            raise  # deterministic (bad input/spec), not a chip fault
        except Exception as e:
            self._note_member_failure(i, e)
            raise
        finally:
            self._loads[i] -= 1

    async def infer(self, inputs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        """Route one batch to the least-loaded healthy member; fail over to
        the remaining members on a member error (at-least-once: the batch
        either completes on SOME chip or the error propagates and the stream
        nacks). When every untried member is mid-probe-backoff the dispatch
        waits for the earliest probe window rather than failing the batch.
        """
        tried: set[int] = set()
        last_err: Exception = RuntimeError("device pool has no members")
        while True:
            i = self._pick(tried)
            if i is None:
                if len(tried) >= self.pool_size:
                    raise last_err  # every member failed this batch
                if self._all_dead(tried):
                    raise RunnerDead(
                        "device pool: every remaining member is DEAD or "
                        "quarantined CORRUPT")
                # all untried members are unhealthy mid-backoff: wait for the
                # earliest probe window instead of dropping the batch
                await asyncio.sleep(max(self._probe_wait_s(tried), 0.01))
                continue
            self._loads[i] += 1
            self.m_dispatch[i].inc()
            try:
                return await self.members[i].infer(inputs)
            except (asyncio.CancelledError, ConfigError):
                # cancellation is not a chip fault; ConfigError is
                # deterministic (bad input/spec) and would fail on every chip
                raise
            except Exception as e:
                last_err = e
                tried.add(i)
                self._note_member_failure(i, e)
                if len(tried) >= self.pool_size:
                    raise
                self.m_failover.inc()
                logger.warning(
                    "device pool: member %d failed a step (%s); retrying on "
                    "another member (%d/%d tried)",
                    i, e, len(tried), self.pool_size)
            finally:
                self._loads[i] -= 1
