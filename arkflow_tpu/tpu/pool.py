"""Replicated device pool: N independent single-device runners, one dispatcher.

The dp-mesh path (``ModelRunner`` + ``mesh: {dp: N}``) scales throughput by
splitting every batch over the chips with GSPMD — ideal for large buckets,
but every step pays collective/partitioning overhead and the whole pool runs
in lockstep. Small-bucket / latency-bound traffic scales better the dumb way:
``device_pool: N`` builds N fully independent single-device ``ModelRunner``s
with REPLICATED params (one host init/restore, N one-hop transfers) behind a
least-loaded round-robin dispatcher. No collectives, no GSPMD — each member
keeps flash attention, staging pools, input donation, and eager prefetch
exactly as in single-device serving, and concurrent stream workers fan out
across chips instead of queueing on one.

Failover preserves at-least-once delivery: a member that throws mid-step is
skipped for that batch and the batch retries on the remaining members; only
when EVERY member fails does the error propagate (and the stream nacks, so
the source redelivers). Deterministic config errors (bad input spec) are NOT
retried — they would fail identically on every chip.

Per-chip observability: each member's runner metrics carry a ``device`` label
(``arkflow_tpu_device_busy_seconds_total{device="3"}`` ...), and the pool adds
dispatch/failover counters so imbalance or a limping chip shows up directly.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import numpy as np

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.obs import global_registry
from arkflow_tpu.tpu.bucketing import BucketPolicy
from arkflow_tpu.tpu.runner import ModelRunner, convert_for_serving, init_host_params

logger = logging.getLogger("arkflow.tpu")


class ModelRunnerPool:
    """Drop-in for ``ModelRunner`` over N replicated single-device members.

    Exposes the same surface the ``tpu_inference`` processor uses (``spec``,
    ``buckets``, ``cfg``, ``family``, ``infer``/``infer_sync``/``warmup``),
    so processors don't branch on pool-vs-single beyond construction.
    """

    def __init__(
        self,
        model: str,
        model_config: Optional[dict] = None,
        *,
        pool_size: int,
        buckets: Optional[BucketPolicy] = None,
        checkpoint: Optional[str] = None,
        seed: int = 0,
        devices=None,
        serving_dtype: Optional[str] = None,
        max_in_flight: Optional[int] = None,
        packed: bool = False,
    ):
        import jax

        if pool_size < 1:
            raise ConfigError(f"device_pool must be >= 1, got {pool_size}")
        devices = list(devices) if devices is not None else jax.devices()
        if pool_size > len(devices):
            raise ConfigError(
                f"device_pool: {pool_size} runners requested, "
                f"{len(devices)} devices visible")
        # one host-side init + checkpoint restore + dtype convert (bf16 cast /
        # int8 quantization); every member transfers the SAME finished tree to
        # its own chip — replication by construction, and the expensive
        # full-tree walks happen once instead of N times
        from arkflow_tpu.models import get_model

        family = get_model(model)
        cfg = family.make_config(**(model_config or {}))
        host_params = convert_for_serving(
            init_host_params(family, cfg, seed, checkpoint),
            serving_dtype, family.name)
        self.members: list[ModelRunner] = [
            ModelRunner(
                model,
                model_config,
                buckets=buckets,
                seed=seed,
                devices=[devices[i]],
                serving_dtype=serving_dtype,
                max_in_flight=max_in_flight,
                packed=packed,
                host_params=host_params,
                device_label=str(i),
            )
            for i in range(pool_size)
        ]
        self.pool_size = pool_size
        #: outstanding infer calls per member (the least-loaded signal)
        self._loads = [0] * pool_size
        self._rr = 0  # round-robin cursor for ties

        reg = global_registry()
        self.m_dispatch = [
            reg.counter(
                "arkflow_tpu_pool_dispatch_total",
                "batches dispatched to this pool member",
                {"model": model, "device": str(i)})
            for i in range(pool_size)
        ]
        self.m_failover = reg.counter(
            "arkflow_tpu_pool_failover_total",
            "batches retried on another member after a member error",
            {"model": model})

    # -- ModelRunner surface (delegated) -----------------------------------

    @property
    def family(self):
        return self.members[0].family

    @property
    def cfg(self):
        return self.members[0].cfg

    @property
    def spec(self):
        return self.members[0].spec

    @property
    def buckets(self) -> BucketPolicy:
        return self.members[0].buckets

    @property
    def packed(self) -> bool:
        return self.members[0].packed

    @property
    def max_in_flight(self) -> int:
        # aggregate device-queue depth across the pool (bench worker sizing)
        return sum(m.max_in_flight for m in self.members)

    def duty_cycle(self) -> float:
        cycles = [m.duty_cycle() for m in self.members]
        return sum(cycles) / len(cycles) if cycles else 0.0

    def warmup(self, seq_lens: Optional[list[int]] = None) -> int:
        """Precompile every member's bucket grid. Serial on purpose: member 0
        pays the real compiles, members 1..N-1 replay them from the
        persistent compile cache (identical shapes, identical HLO)."""
        return sum(m.warmup(seq_lens) for m in self.members)

    # -- dispatch ----------------------------------------------------------

    def _pick(self, exclude: set[int]) -> Optional[int]:
        """Least-loaded member, round-robin among ties (the cursor advances
        every pick, so equal-load members take strict turns)."""
        best: Optional[int] = None
        n = self.pool_size
        for off in range(n):
            i = (self._rr + off) % n
            if i in exclude:
                continue
            if best is None or self._loads[i] < self._loads[best]:
                best = i
        if best is not None:
            self._rr = (self._rr + 1) % n
        return best

    def infer_sync(self, inputs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        i = self._pick(set())
        self._loads[i] += 1
        self.m_dispatch[i].inc()
        try:
            return self.members[i].infer_sync(inputs)
        finally:
            self._loads[i] -= 1

    async def infer(self, inputs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        """Route one batch to the least-loaded member; fail over to the
        remaining members on a member error (at-least-once: the batch either
        completes on SOME chip or the error propagates and the stream nacks).
        """
        tried: set[int] = set()
        last_err: Exception = RuntimeError("device pool has no members")
        while True:
            i = self._pick(tried)
            if i is None:  # every member failed this batch
                raise last_err
            self._loads[i] += 1
            self.m_dispatch[i].inc()
            try:
                return await self.members[i].infer(inputs)
            except (asyncio.CancelledError, ConfigError):
                # cancellation is not a chip fault; ConfigError is
                # deterministic (bad input/spec) and would fail on every chip
                raise
            except Exception as e:
                last_err = e
                tried.add(i)
                if len(tried) >= self.pool_size:
                    raise
                self.m_failover.inc()
                logger.warning(
                    "device pool: member %d failed a step (%s); retrying on "
                    "another member (%d/%d tried)",
                    i, e, len(tried), self.pool_size)
            finally:
                self._loads[i] -= 1
