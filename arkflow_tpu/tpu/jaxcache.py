"""Persistent XLA compilation cache for the serving tier.

Remote/tunneled TPU backends pay tens of seconds (sometimes minutes) per
executable compile; with the persistent cache each (model, shape, dtype)
bucket compiles once per machine instead of once per process, so engine
restarts, benchmark reruns, and the driver's end-of-round `bench.py` all
start serving at full speed immediately.

The reference engine has no analog (an interpreted CPU data plane never
compiles); this is TPU-native operational hygiene, same motivation as the
executable warm-up hook (SURVEY.md §7.5: keep the compiled model fed, never
stall steady-state on a compile).

Knobs:
- ``ARKFLOW_JAX_CACHE=0`` disables.
- ``ARKFLOW_JAX_CACHE_DIR`` overrides the location (default: ``.jax_cache``
  next to the package, i.e. the repo root; falls back silently if the
  directory is not creatable).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("arkflow.tpu")

_configured: Optional[str] = None
_attempted = False


def enable_persistent_cache() -> Optional[str]:
    """Idempotently point JAX at an on-disk compilation cache.

    Returns the cache directory in use, or None when disabled/unavailable.
    Must run before the first compile to help that compile; safe any time.
    """
    global _configured, _attempted
    if _attempted:
        return _configured
    _attempted = True
    if os.environ.get("ARKFLOW_JAX_CACHE", "1") == "0":
        return None
    path = os.environ.get("ARKFLOW_JAX_CACHE_DIR") or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR"
    )
    if not path:
        # CPU backends: no persistent cache. XLA:CPU AOT entries embed the
        # build machine's feature set and the loader re-checks it against a
        # host list that never includes XLA's prefer-no-gather/scatter
        # pseudo-features — so every reload warns (and a cross-host reload
        # risks SIGILL). The round-2 driver artifact was swamped by exactly
        # that spew. CPU compiles are fast; the cache only pays for real on
        # the slow tunneled-TPU compiles. Explicit env dirs still override.
        if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
            return None
        path = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            ".jax_cache",
        )
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache every executable regardless of compile time (jax's default
        # threshold of 1s would skip the small bucket-grid executables that
        # recompile on every engine restart)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _configured = path
        logger.debug("persistent XLA compilation cache at %s", path)
    except Exception as e:  # never let cache plumbing break serving
        logger.warning("persistent compilation cache unavailable: %s", e)
        _configured = None
    return _configured
