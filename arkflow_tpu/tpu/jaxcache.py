"""Persistent XLA compilation cache for the serving tier.

Remote/tunneled TPU backends pay tens of seconds (sometimes minutes) per
executable compile; with the persistent cache each (model, shape, dtype)
bucket compiles once per machine instead of once per process, so engine
restarts, benchmark reruns, and the driver's end-of-round `bench.py` all
start serving at full speed immediately.

The reference engine has no analog (an interpreted CPU data plane never
compiles); this is TPU-native operational hygiene, same motivation as the
executable warm-up hook (SURVEY.md §7.5: keep the compiled model fed, never
stall steady-state on a compile).

Knobs:
- ``ARKFLOW_JAX_CACHE=0`` disables.
- ``ARKFLOW_JAX_CACHE_DIR`` overrides the location (default: ``.jax_cache``
  next to the package, i.e. the repo root; falls back silently if the
  directory is not creatable).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("arkflow.tpu")

_configured: Optional[str] = None
_attempted = False


def _host_key() -> str:
    """Short stable hash of this host's CPU feature set.

    XLA:CPU AOT executables are ISA-specific; keying the CPU cache dir by
    the cpuinfo flags guarantees a repo checked out on different silicon
    starts a fresh cache instead of loading foreign AOT code (SIGILL risk).
    """
    import hashlib
    import platform

    feats = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats += line
                    break
    except OSError:
        pass
    return hashlib.sha256(feats.encode()).hexdigest()[:10]


def enable_persistent_cache() -> Optional[str]:
    """Idempotently point JAX at an on-disk compilation cache.

    Returns the cache directory in use, or None when disabled/unavailable.
    Must run before the first compile to help that compile; safe any time.
    """
    global _configured, _attempted
    if _attempted:
        return _configured
    _attempted = True
    if os.environ.get("ARKFLOW_JAX_CACHE", "1") == "0":
        return None
    path = os.environ.get("ARKFLOW_JAX_CACHE_DIR") or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR"
    )
    if not path:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
            # CPU backend. XLA:CPU AOT entries embed the build machine's
            # feature set and the loader re-checks it against a host list
            # that never includes XLA's prefer-no-gather/scatter
            # pseudo-features — so every reload logs two C++-level E lines
            # (cosmetic on the same host; a cross-host reload risks SIGILL).
            # Bench fallback children must stay off the cache entirely: the
            # round-2 driver artifact lost its metric line to that spew.
            # Everywhere else (the test suite above all) the cache is worth
            # ~9 min/run of recompiles, so keep it on, keyed by host CPU
            # features so a copied repo on different silicon recompiles, and
            # silence the loader lines via TF_CPP_MIN_LOG_LEVEL (set before
            # jax import by cleanenv.pin_cpu_env).
            if os.environ.get("ARKFLOW_BENCH_CHILD") == "1":
                return None
            path = os.path.join(repo_root, f".jax_cache_cpu-{_host_key()}")
        else:
            path = os.path.join(repo_root, ".jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache every executable regardless of compile time (jax's default
        # threshold of 1s would skip the small bucket-grid executables that
        # recompile on every engine restart)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _configured = path
        logger.debug("persistent XLA compilation cache at %s", path)
    except Exception as e:  # never let cache plumbing break serving
        logger.warning("persistent compilation cache unavailable: %s", e)
        _configured = None
    return _configured


def cache_info() -> dict:
    """JSON-able snapshot of the persistent compile cache — the shape
    tuner's warm phase reports through this so an operator can tell whether
    a retune's compiles were real or cache replays."""
    if not _configured:
        return {"enabled": False}
    try:
        entries = sum(1 for e in os.scandir(_configured) if e.is_file())
    except OSError:
        entries = None
    return {"enabled": True, "dir": _configured, "entries": entries}
