"""Silent-data-corruption defense plane: digests, golden probes, quarantine.

PRs 4/16/19 made the serving tier survive crashes, hangs, OOMs, preemption
and partitions with a zero-silent-LOSS identity — but nothing defended
against the fleet returning wrong ANSWERS: a bit-flipped param leaf in HBM,
a defective chip, or stale weights after a botched swap would serve corrupt
results undetected, and an elastic controller spawning workers on arbitrary
hosts makes defective hardware a routine event, not an anomaly. This module
extends the invariant from "never silently lost" to "never silently wrong":

1. **Param-tree digests** (:func:`tree_digests`): one blake2b-128 per leaf
   over dtype+shape+bytes, keyed by the leaf's tree path. The baseline is
   taken from the LIVE placed tree at the first off-path verification after
   boot / adopt (``ModelRunner.param_digests`` invalidates on every
   ``adopt_params``, so legitimate weight flips never read as drift) and
   re-verified on the probe cadence — fetch-and-hash on an executor thread
   holding the in-flight permit, exactly like ``warm_shapes_live``, so
   verification never interleaves with a live device schedule. A mismatch
   names the offending leaf paths and marks the member UNHEALTHY through
   the PR-4 state machine, then forces a golden probe as the tiebreak.

2. **Live golden probes**: a deterministic golden batch per model family —
   tie-free BY CONSTRUCTION (:func:`find_golden_reference` searches seeds
   until the smallest top-1/top-2 logit gap clears the serving dtype's
   noise floor) — runs through each member's REAL serving path on a
   periodic schedule, and its argmax signature is compared against a
   host-computed reference. A mismatch is an integrity failure, not a
   transient error: the member is quarantined (health ``CORRUPT``,
   DEAD-adjacent — never re-admitted by backoff alone, because a corrupt
   chip passes liveness probes while still answering wrongly) and repaired
   (re-adopt the retained known-good host tree, digests re-baselined,
   golden probe re-verified before re-admission).

3. **Quarantine hooks**: anything whose cached state may hold a corrupt
   member's answers registers here — the ingest ``ResponseCache`` bumps its
   epoch so a post-quarantine byte-identical duplicate recomputes instead
   of replaying poisoned bytes.

The cluster tier reuses the same machinery: worker heartbeats carry this
monitor's ``digest_epoch`` and corrupt-member count, the ingest dispatcher
fences digest-outlier or corrupt-reporting workers through the PR-19
incarnation-fencing path, and ``shadow_verify`` dual-dispatches a sampled
fraction of live batches to the ring successor to catch corruption the
worker cannot see in itself (runtime/cluster.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Optional, Sequence

import numpy as np

from arkflow_tpu.errors import ConfigError, RunnerDead
from arkflow_tpu.obs import global_registry
from arkflow_tpu.tpu.health import CORRUPT, DEAD

logger = logging.getLogger("arkflow.tpu.integrity")

#: result label values of ``arkflow_integrity_probe_total``
PROBE_RESULTS = ("ok", "mismatch", "digest_mismatch", "error")


# -- param-tree digests ------------------------------------------------------


def _leaf_digest(arr) -> str:
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.blake2b(digest_size=16)
    h.update(str(a.dtype).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def tree_digests(tree) -> dict[str, str]:
    """Per-leaf blake2b-128 digests keyed by tree path (``keystr``).

    Blocking — ``device_get`` of every leaf — so callers keep it off the
    event loop (executor thread, holding the in-flight permit when the
    member is serving). Digest covers dtype + shape + bytes: a corrupt
    value, a silent re-cast, and a re-shape all read as drift.
    """
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    host = jax.device_get([leaf for _, leaf in flat])
    return {jax.tree_util.keystr(path): _leaf_digest(a)
            for (path, _), a in zip(flat, host)}


def combined_digest(digests: Mapping[str, str]) -> str:
    """One order-independent digest over a ``tree_digests`` map — the
    ``param_digest`` epoch a cluster worker heartbeat carries."""
    h = hashlib.blake2b(digest_size=16)
    for path in sorted(digests):
        h.update(path.encode())
        h.update(digests[path].encode())
    return h.hexdigest()


def diff_digests(baseline: Mapping[str, str],
                 current: Mapping[str, str]) -> list[str]:
    """Leaf paths whose digests differ (missing/extra leaves included)."""
    return [p for p in sorted(set(baseline) | set(current))
            if baseline.get(p) != current.get(p)]


# -- tie-free golden reference -----------------------------------------------

#: minimum top-1/top-2 logit gap a golden batch must clear, per serving
#: dtype: below this, benign rounding drift between the host-computed
#: reference and the device step could flip an argmax and read as
#: corruption. bf16 has ~2^-8 relative precision, int8 re-quantizes
#: activations — their floors are far above float32's.
MARGIN_FLOOR = {
    None: 1e-5,
    "float32": 1e-5,
    "bfloat16": 1.0 / 64,
    "float16": 1e-3,
    "int8": 1e-2,
}


@dataclass(frozen=True)
class GoldenReference:
    """A deterministic golden batch and its host-computed answer: the
    member-side inputs (serving layout — packed when the runner packs), the
    reference argmax signature, the seed that produced a tie-free batch,
    and the margin it cleared. Same (family, cfg, dtype, seed) => bitwise
    identical across process restarts."""

    inputs: dict[str, np.ndarray]
    signature: np.ndarray
    seed: int
    margin: float


def _packed_golden(spec_cfg, rows: int, seq: int, seed: int):
    """Golden batch in the packed layout (tpu/packing.py): equal-length
    full-seq examples, one per row — deterministic, and the packed apply's
    [E] outputs land in input example order."""
    from arkflow_tpu.tpu.packing import pack_tokens

    rng = np.random.default_rng(seed)
    vocab = int(getattr(spec_cfg, "vocab_size", 256) or 256)
    ids = rng.integers(1, max(vocab, 2), size=(rows, seq)).astype(np.int32)
    pk = pack_tokens(ids, np.full(rows, seq, np.int64), seq)
    return {"input_ids": pk.input_ids, "segment_ids": pk.segment_ids,
            "position_ids": pk.position_ids, "example_row": pk.example_row,
            "example_pos": pk.example_pos}


def find_golden_reference(family, cfg, host_params, *, rows: int, seq: int,
                          seed: int, serving_dtype: Optional[str],
                          packed: bool = False) -> GoldenReference:
    """Build a tie-free golden batch + host-computed reference signature.

    Seeds are searched (base, base+1, ...) until the batch's
    :func:`~arkflow_tpu.tpu.swap.signature_margin` clears the serving
    dtype's :data:`MARGIN_FLOOR` — so the signature cannot flip from benign
    float drift, only from actual corruption. Blocking (one host forward
    per candidate seed); callers run it off the event loop at build time.
    """
    from arkflow_tpu.tpu.swap import (argmax_signature, golden_inputs,
                                      signature_margin)

    floor = MARGIN_FLOOR.get(serving_dtype, 1e-2)
    apply_fn = (family.extras["apply_packed"] if packed else family.apply)
    best: Optional[tuple[float, int]] = None
    for k in range(64):
        s = seed + k
        if packed:
            golden = _packed_golden(cfg, rows, seq, s)
        else:
            golden = golden_inputs(family.input_spec(cfg), cfg, rows, s,
                                   seq=seq)
        out = apply_fn(host_params, cfg, **golden)
        out = {k_: np.asarray(v) for k_, v in out.items()}
        margin = signature_margin(out)
        if margin >= floor:
            return GoldenReference(inputs=golden,
                                   signature=argmax_signature(out),
                                   seed=s, margin=margin)
        if best is None or margin > best[0]:
            best = (margin, s)
    raise ConfigError(
        f"integrity: no tie-free golden batch for {family.name} in 64 seeds "
        f"(best margin {best[0]:.2e} at seed {best[1]}, need >= {floor:.2e} "
        f"for serving_dtype {serving_dtype or 'float32'}); raise golden.rows "
        "or pick another golden.seed")


# -- config ------------------------------------------------------------------


@dataclass(frozen=True)
class IntegrityConfig:
    """Knobs for the ``integrity:`` block on ``tpu_inference`` (opt-in: no
    block, no monitor — probes cost one real golden step per member per
    interval)."""

    #: golden-probe cadence per member
    probe_interval_s: float = 10.0
    #: every Nth probe tick ALSO re-verifies param digests (full-tree
    #: fetch-and-hash — heavier than the golden step; 0 disables)
    digest_every: int = 3
    #: golden-batch rows (kept small: the probe rides the live schedule)
    golden_rows: int = 2
    #: golden-batch sequence length (clamped to the smallest seq bucket)
    golden_seq: int = 16
    #: base seed for the tie-free seed search
    golden_seed: int = 0x90D
    #: repair quarantined members automatically (re-adopt the retained
    #: host tree, re-baseline, golden re-verify); False = quarantine only
    repair: bool = True


def parse_integrity_config(cfg: Any, who: str = "processor"
                           ) -> Optional[IntegrityConfig]:
    """Pure parse of an ``integrity:`` block (config.py runs this at
    --validate without importing jax). None in, None out: the monitor is
    opt-in."""
    if cfg is None:
        return None
    if not isinstance(cfg, Mapping):
        raise ConfigError(f"{who}.integrity must be a mapping, got {cfg!r}")
    unknown = set(cfg) - {"probe_interval", "digest_every", "golden", "repair"}
    if unknown:
        raise ConfigError(
            f"{who}.integrity: unknown keys {sorted(unknown)} "
            "(allowed: probe_interval, digest_every, golden, repair)")
    out: dict[str, Any] = {}
    if cfg.get("probe_interval") is not None:
        from arkflow_tpu.utils.duration import parse_duration

        v = parse_duration(cfg["probe_interval"])
        if v <= 0:
            raise ConfigError(f"{who}.integrity.probe_interval must be positive")
        out["probe_interval_s"] = v
    de = cfg.get("digest_every")
    if de is not None:
        if isinstance(de, bool) or not isinstance(de, int) or de < 0:
            raise ConfigError(
                f"{who}.integrity.digest_every must be an int >= 0, got {de!r}")
        out["digest_every"] = de
    golden = cfg.get("golden")
    if golden is not None:
        if not isinstance(golden, Mapping):
            raise ConfigError(
                f"{who}.integrity.golden must be a mapping, got {golden!r}")
        bad = set(golden) - {"rows", "seq", "seed"}
        if bad:
            raise ConfigError(
                f"{who}.integrity.golden: unknown keys {sorted(bad)} "
                "(allowed: rows, seq, seed)")
        for key, lo in (("rows", 1), ("seq", 1), ("seed", None)):
            v = golden.get(key)
            if v is None:
                continue
            if isinstance(v, bool) or not isinstance(v, int) \
                    or (lo is not None and v < lo):
                raise ConfigError(
                    f"{who}.integrity.golden.{key} must be an int"
                    f"{f' >= {lo}' if lo is not None else ''}, got {v!r}")
            out[f"golden_{key}"] = v
    repair = cfg.get("repair")
    if repair is not None:
        if not isinstance(repair, bool):
            raise ConfigError(
                f"{who}.integrity.repair must be a bool, got {repair!r}")
        out["repair"] = repair
    return IntegrityConfig(**out)


# -- member adapters ---------------------------------------------------------


class RunnerIntegrityMember:
    """Integrity surface over one ``ModelRunner`` (standalone or a pool
    member): the golden probe is one REAL step through the runner's own
    serving path (heal gate, deadline watchdog, in-flight permit), digests
    ride the runner's ``verify_params_live`` off-path discipline, and
    repair re-adopts the runner's retained known-good host tree."""

    def __init__(self, runner, label: str, golden: GoldenReference):
        self.runner = runner
        self.label = label
        self.golden = golden
        self.last_probe_at: Optional[float] = None
        self.last_result = "never"

    @property
    def health(self):
        return self.runner.health

    def state(self) -> str:
        return self.runner.health.state

    async def verify_digests(self) -> list[str]:
        return await self.runner.verify_params_live()

    async def golden_probe(self) -> bool:
        from arkflow_tpu.tpu.swap import argmax_signature

        out = await self.runner.infer(
            {k: v.copy() for k, v in self.golden.inputs.items()})
        sig = argmax_signature({k: np.asarray(v) for k, v in out.items()})
        return bool(np.array_equal(sig, self.golden.signature))

    def note_probe_failure(self, e: Exception) -> None:
        """A probe step that RAISED is a transient incident, not proof of
        corruption: apply the shared external-failure policy so the member
        enters the same probe/backoff schedule pool dispatch honors."""
        self.runner.core.note_external_failure(e)

    async def repair(self) -> None:
        """Re-adopt the retained known-good host tree (one placement, one
        atomic flip), clear any armed sdc fault (the 'replaced hardware'),
        and re-baseline digests off the new tree."""
        loop = asyncio.get_running_loop()
        r = self.runner
        placed = await loop.run_in_executor(None, r.place_params,
                                            r.host_params)
        r.adopt_params(placed)
        r.core.clear_sdc()
        await loop.run_in_executor(None, r.rebaseline_digests)

    def report(self) -> dict:
        rep = {"label": self.label, "state": self.state(),
               "last_probe": self.last_result}
        if self.last_probe_at is not None:
            rep["last_probe_age_s"] = round(
                time.monotonic() - self.last_probe_at, 3)
        return rep

    def baseline_digests(self) -> Optional[dict[str, str]]:
        return self.runner.param_digests

    def reset_baseline(self) -> None:
        self.runner.param_digests = None


class ServerIntegrityMember:
    """Integrity surface over a continuous ``GenerationServer``: the probe
    is a host-side forward-apply of the server's live tree against the
    golden reference (the generation loop itself samples — its outputs are
    not signature-comparable), digests hash the same tree, and repair
    re-places a freshly-built known-good host tree through ``swap_params``
    (which rebuilds the jits and resets page pools + prefix cache — cached
    KV from corrupt weights must not survive the repair)."""

    def __init__(self, server, label: str, golden: GoldenReference, *,
                 family, cfg, place_fn: Callable[[Any], Any],
                 host_source: Callable[[], Any],
                 drain_timeout_s: float = 30.0, owner=None):
        self.server = server
        self.label = label
        self.golden = golden
        self.family = family
        self.cfg = cfg
        self._place_fn = place_fn
        self._host_source = host_source
        self._drain_timeout_s = drain_timeout_s
        self._owner = owner
        self._baseline: Optional[dict[str, str]] = None
        self.last_probe_at: Optional[float] = None
        self.last_result = "never"

    @property
    def health(self):
        return self.server.core.health

    def state(self) -> str:
        return self.server.core.health.state

    async def verify_digests(self) -> list[str]:
        loop = asyncio.get_running_loop()
        digests = await loop.run_in_executor(
            None, tree_digests, self.server.params)
        if self._baseline is None:
            self._baseline = digests
            return []
        return diff_digests(self._baseline, digests)

    async def golden_probe(self) -> bool:
        from arkflow_tpu.tpu.swap import argmax_signature

        def forward() -> np.ndarray:
            out = self.family.apply(self.server.params, self.cfg,
                                    **self.golden.inputs)
            return argmax_signature(
                {k: np.asarray(v) for k, v in out.items()})

        sig = await asyncio.get_running_loop().run_in_executor(None, forward)
        return bool(np.array_equal(sig, self.golden.signature))

    def note_probe_failure(self, e: Exception) -> None:
        core = getattr(self.server, "core", None)
        if core is not None:
            core.note_external_failure(e)

    async def repair(self) -> None:
        loop = asyncio.get_running_loop()
        host = await loop.run_in_executor(None, self._host_source)
        placed = await loop.run_in_executor(None, self._place_fn, host)
        await self.server.swap_params(placed, self._drain_timeout_s)
        if self._owner is not None:
            self._owner.params = placed
        core = getattr(self.server, "core", None)
        if core is not None:
            core.clear_sdc()
        self._baseline = await loop.run_in_executor(
            None, tree_digests, placed)

    def report(self) -> dict:
        rep = {"label": self.label, "state": self.state(),
               "last_probe": self.last_result}
        if self.last_probe_at is not None:
            rep["last_probe_age_s"] = round(
                time.monotonic() - self.last_probe_at, 3)
        return rep

    def baseline_digests(self) -> Optional[dict[str, str]]:
        return self._baseline

    def reset_baseline(self) -> None:
        self._baseline = None


# -- the monitor -------------------------------------------------------------


class IntegrityMonitor:
    """Periodic integrity verification + quarantine-and-repair over a list
    of members (one per independently-servable surface, the same granularity
    as swap units).

    Per tick, for every member: skip DEAD; repair CORRUPT (when enabled);
    otherwise run the golden probe — and on every ``digest_every``-th tick,
    verify param digests first. Digest drift names the offending leaves,
    marks the member UNHEALTHY (PR-4 machine) and forces the golden probe
    as the behavioral tiebreak; a golden-probe signature mismatch is PROOF
    of corruption: ``mark_corrupt`` (never re-admitted by backoff),
    quarantine hooks fire (response-cache epoch bump), and the repair path
    re-adopts known-good params, re-baselines, and golden re-verifies
    before ``mark_repaired`` re-admits the member.
    """

    def __init__(self, *, name: str, cfg: IntegrityConfig,
                 members: Sequence[Any]):
        if not members:
            raise ConfigError("IntegrityMonitor needs at least one member")
        self.name = name
        self.cfg = cfg
        self.members = list(members)
        self._task: Optional[asyncio.Task] = None
        self._tick = 0
        self._quarantine_hooks: list[Callable[[], None]] = []
        self._lock = asyncio.Lock()
        #: probing held off during a weights transition (hot-swap roll)
        self._suspended = False
        #: recompute the golden reference for a given host tree — set by
        #: the builders, used when a committed swap changes the weights
        self._golden_factory: Optional[Callable[[Any], GoldenReference]] = None

        reg = global_registry()
        labels = {"model": name}
        self.m_probe = {
            r: reg.counter(
                "arkflow_integrity_probe_total",
                "integrity probes by result (golden signature + digests)",
                {**labels, "result": r})
            for r in PROBE_RESULTS
        }
        self.m_quarantine = reg.counter(
            "arkflow_integrity_quarantine_total",
            "members quarantined (CORRUPT) for proven integrity failures",
            labels)
        self.m_repair = reg.counter(
            "arkflow_integrity_repair_total",
            "quarantined members repaired, re-verified, and re-admitted",
            labels)
        #: per-instance counts for report() (the registry dedupes series on
        #: (name, labels): two streams serving one model share counters)
        self.n_probes = self.n_mismatches = 0
        self.n_quarantined = self.n_repaired = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start the background probe loop (processor ``connect``)."""
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    def add_quarantine_hook(self, hook: Callable[[], None]) -> None:
        """Run whenever a member is quarantined: its past answers are no
        longer trustworthy, so anything replaying them (response caches)
        must epoch-flush here."""
        self._quarantine_hooks.append(hook)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.probe_interval_s)
            try:
                await self.probe_now()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("[%s] integrity probe tick failed", self.name)

    # -- swap coexistence ----------------------------------------------------

    async def begin_quiesce(self) -> None:
        """Hold off probing for a weights transition (the hot-swap manager
        calls this before its rolling flip): mid-roll, flipped members
        legitimately diverge from the golden reference, and a probe would
        quarantine them — whose repair would silently roll the swap back.
        Awaits any in-flight tick, so the roll starts probe-free."""
        self._suspended = True
        async with self._lock:
            pass

    def end_quiesce(self) -> None:
        self._suspended = False

    def rebuild_reference(self, host_params) -> None:
        """Recompute the golden reference + reset digest baselines against
        a newly COMMITTED weights version (blocking — host forwards; the
        swap manager runs it on an executor thread before re-enabling
        probes). Without this, the first post-swap probe would read the new
        weights as corruption."""
        if self._golden_factory is None:
            raise ConfigError(
                f"IntegrityMonitor[{self.name}] has no golden factory; "
                "cannot follow a weights swap")
        golden = self._golden_factory(host_params)
        for m in self.members:
            m.golden = golden
            m.reset_baseline()
        logger.info("[%s] integrity reference rebuilt for new weights "
                    "(golden seed %d, margin %.2e)", self.name,
                    golden.seed, golden.margin)

    # -- probing -------------------------------------------------------------

    async def probe_now(self) -> dict:
        """One full verification pass over every member (the loop body;
        also the soak/test surface — and the worker-side handler of the
        cluster's ``integrity_probe`` action). Returns a summary dict."""
        if self._suspended:
            return {"tick": self._tick, "suspended": True, "checked": 0,
                    "ok": 0, "mismatches": 0, "repaired": 0}
        async with self._lock:  # ticks never interleave (repair is stateful)
            self._tick += 1
            with_digests = bool(self.cfg.digest_every) and (
                self._tick % self.cfg.digest_every == 0)
            summary = {"tick": self._tick, "checked": 0, "ok": 0,
                       "mismatches": 0, "repaired": 0}
            for m in self.members:
                await self._probe_member(m, with_digests, summary)
            return summary

    async def _probe_member(self, m, with_digests: bool, summary: dict) -> None:
        state = m.state()
        if state == DEAD:
            return  # terminal: repair must never resurrect a DEAD member
        if state == CORRUPT:
            # quarantined earlier (possibly by the cluster dispatcher's
            # shadow-verify tiebreak): this tick's job is the repair
            if self.cfg.repair:
                summary["repaired"] += await self._repair(m)
            return
        summary["checked"] += 1
        self.n_probes += 1
        if with_digests:
            try:
                drifted = await m.verify_digests()
            except RunnerDead:
                return
            except Exception as e:
                self.m_probe["error"].inc()
                m.last_result = "error"
                m.note_probe_failure(e)
                return
            if drifted:
                # drift is a strong signal, not yet proof: name the leaves,
                # mark UNHEALTHY (PR-4 schedule), and let the golden probe
                # below decide whether behavior actually changed
                self.m_probe["digest_mismatch"].inc()
                m.last_result = "digest_mismatch"
                preview = drifted[:3] + (["..."] if len(drifted) > 3 else [])
                logger.error("[%s] %s: param digest drift on %d leaves: %s",
                             self.name, m.label, len(drifted), preview)
                m.health.mark_unhealthy(
                    f"param digest drift: {preview}")
        try:
            ok = await m.golden_probe()
        except RunnerDead:
            return  # went DEAD/CORRUPT under us; next tick handles it
        except Exception as e:
            self.m_probe["error"].inc()
            m.last_result = "error"
            m.note_probe_failure(e)
            return
        m.last_probe_at = time.monotonic()
        if ok:
            self.m_probe["ok"].inc()
            if m.last_result != "digest_mismatch":
                m.last_result = "ok"
            summary["ok"] += 1
            return
        self.m_probe["mismatch"].inc()
        self.n_mismatches += 1
        m.last_result = "mismatch"
        summary["mismatches"] += 1
        self.quarantine(m, "golden-probe signature mismatch")
        if self.cfg.repair:
            summary["repaired"] += await self._repair(m)

    # -- quarantine / repair -------------------------------------------------

    def quarantine(self, m, reason: str) -> None:
        """Mark a member CORRUPT and fire the quarantine hooks. Also the
        entry point for EXTERNAL proof (the cluster dispatcher's
        shadow-verify tiebreak)."""
        m.health.mark_corrupt(reason)
        self.m_quarantine.inc()
        self.n_quarantined += 1
        for hook in self._quarantine_hooks:
            try:
                hook()
            except Exception:  # a cache flush must not compound a quarantine
                logger.exception("[%s] quarantine hook failed", self.name)

    async def _repair(self, m) -> int:
        """Repair one CORRUPT member: re-adopt known-good params, then
        golden re-verify BEFORE the member serves again. Returns 1 on a
        successful re-admission, 0 when the member stays quarantined."""
        try:
            await m.repair()
        except Exception:
            logger.exception("[%s] %s: repair failed; member stays "
                             "quarantined", self.name, m.label)
            return 0
        # re-admit first (the heal gate rejects CORRUPT members, so the
        # verifying probe could not run while quarantined), then verify:
        # dispatch skips CORRUPT members throughout the repair, and a
        # failed re-verify re-quarantines immediately
        m.health.mark_repaired()
        try:
            ok = await m.golden_probe()
        except Exception as e:
            m.health.mark_corrupt(f"repair re-verify errored: {e}")
            return 0
        m.last_probe_at = time.monotonic()
        if not ok:
            m.health.mark_corrupt("repair failed golden re-verify")
            m.last_result = "mismatch"
            return 0
        m.last_result = "ok"
        self.m_repair.inc()
        self.n_repaired += 1
        logger.info("[%s] %s: repaired, re-verified, re-admitted",
                    self.name, m.label)
        return 1

    # -- introspection -------------------------------------------------------

    def digest_epoch(self) -> Optional[str]:
        """One digest over every member's baseline — the ``param_digest``
        a cluster worker's heartbeat carries, so the dispatcher can spot a
        digest-outlier worker against its same-model peers. None until
        every member has a baseline (first digest tick)."""
        parts: dict[str, str] = {}
        for i, m in enumerate(self.members):
            base = m.baseline_digests()
            if base is None:
                return None
            parts[str(i)] = combined_digest(base)
        return combined_digest(parts)

    def corrupt_members(self) -> int:
        return sum(1 for m in self.members if m.state() == CORRUPT)

    def report(self) -> dict:
        """JSON-able snapshot for the engine's ``/health`` (per-member
        integrity state + last-probe age) and worker heartbeats."""
        rep = {
            "probes": self.n_probes,
            "mismatches": self.n_mismatches,
            "quarantined": self.n_quarantined,
            "repaired": self.n_repaired,
            "members": [m.report() for m in self.members],
        }
        epoch = self.digest_epoch()
        if epoch is not None:
            rep["digest_epoch"] = epoch
        return rep


# -- builders ----------------------------------------------------------------


def build_integrity_monitor(runner, *, model: str,
                            cfg: Optional[IntegrityConfig]
                            ) -> Optional[IntegrityMonitor]:
    """Monitor over a ``ModelRunner`` or ``ModelRunnerPool`` (one member
    per swap unit — the same granularity the rolling hot-swap flips).
    None when the ``integrity:`` block is absent (opt-in)."""
    if cfg is None:
        return None
    units = runner.swap_units()
    first = units[0][1]
    buckets = first.buckets
    seq = (min(buckets.seq_buckets) if buckets.seq_buckets
           else cfg.golden_seq)
    def factory(host) -> GoldenReference:
        return find_golden_reference(
            first.family, first.cfg, host,
            rows=cfg.golden_rows, seq=min(cfg.golden_seq, seq),
            seed=cfg.golden_seed, serving_dtype=first.serving_dtype,
            packed=first.packed)

    # the reference is computed ONCE against the retained known-good host
    # tree all members share (pool replication is by construction)
    golden = factory(first.host_params)
    members = [RunnerIntegrityMember(r, label, golden)
               for label, r in units]
    mon = IntegrityMonitor(name=model, cfg=cfg, members=members)
    mon._golden_factory = factory
    return mon


def build_generate_integrity_monitor(proc, *, model: str,
                                     cfg: Optional[IntegrityConfig]
                                     ) -> Optional[IntegrityMonitor]:
    """Monitor over a continuous ``TpuGenerateProcessor``: one member, the
    generation server. The probe is a host-side forward-apply of the
    server's live tree (the generation loop itself samples — its outputs
    are not signature-comparable), repair re-places the retained host tree
    through ``swap_params``. Batch-mode generation has no resident member
    to probe between calls, so the block is rejected there."""
    if cfg is None:
        return None
    server = getattr(proc, "_server", None)
    if server is None:
        raise ConfigError(
            "tpu_generate: integrity requires serving: continuous (batch "
            "mode holds no resident serving member to probe); drop the "
            "integrity block or switch serving modes")
    import jax
    import jax.numpy as jnp

    dtype = None
    for leaf in jax.tree_util.tree_leaves(proc.host_params):
        dt = getattr(leaf, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.floating):
            dtype = str(dt)
            break
    def factory(host) -> GoldenReference:
        return find_golden_reference(
            proc.family, proc.cfg, host,
            rows=cfg.golden_rows, seq=cfg.golden_seq, seed=cfg.golden_seed,
            serving_dtype=dtype)

    golden = factory(proc.host_params)
    member = ServerIntegrityMember(
        server, "generate[continuous]", golden,
        family=proc.family, cfg=proc.cfg, place_fn=proc._place_params,
        host_source=lambda: proc.host_params, owner=proc)
    mon = IntegrityMonitor(name=model, cfg=cfg, members=[member])
    mon._golden_factory = factory
    return mon
