"""Traffic-adaptive shapes: the runtime tuner that closes the feedback loop.

Every performance-critical shape knob — the seq bucket grid, the coalescer's
``token_budget`` and ``deadline``, ``example_scale`` — ships as static YAML
chosen once against one synthetic workload, and the bench artifacts show the
cost: ~6% packed-fill headroom and padding waste over-weighted by tail
windows whenever live traffic drifts from the assumed length mix (ROADMAP
item 4). This module learns those knobs from the live signals the repo
already exports and reconfigures them ON THE FLY, without ever paying a
compile or a flap on the serving path:

1. **Observe.** The inference processor feeds every batch's true token
   lengths into a windowed :class:`WorkloadSketch` (ring buffer + arrival
   EWMA — the tokenized twin of the PR-6 ``payload_token_estimates`` pass);
   the runner's per-bucket dispatch counts, fill/waste histograms
   (``arkflow_padding_waste_frac``), and the overload controller's step
   EWMA + AIMD window ride along in the report.
2. **Propose.** :func:`plan_shapes` is a deterministic planner (no RL,
   seeded by nothing but the sketch): quantile-aligned seq bucket edges
   instead of blind pow2, a token budget sized by simulating the real
   first-fit packing against the observed length mix so packed fill p50
   targets ``target_fill``, a coalesce deadline sized from the arrival rate
   so the budget actually fills before the deadline flush, and an
   ``example_scale`` that keeps token-budget emissions example-servable.
   Proposals whose predicted waste does not beat the incumbent's by
   ``min_improvement`` — or that would mint more than ``max_compiles`` new
   executables — are rejected (hysteresis: a stable workload never flaps).
3. **Warm.** Every shape of the accepted grid precompiles OFF the serving
   path through the persistent XLA cache (``tpu/jaxcache.py``) via
   ``ModelRunner.warm_shapes`` — warmed shapes are marked seen, so the flip
   itself costs ZERO on-path recompiles (``arkflow_tpu_compiles_total``
   stays flat; warm-path compiles count in
   ``arkflow_tpu_warm_compiles_total`` instead).
4. **Flip.** The swap-unit machinery from the hot-swap layer is reused
   verbatim: each serving unit (a runner, or every pool member) retargets
   its grid atomically, runs one health-gated probe step on the NEW grid,
   and any probe failure rolls every unit back to the incumbent grid with
   nothing flushed. Only after every probe passes does the
   :class:`~arkflow_tpu.tpu.bucketing.BucketCapBus` broadcast retarget the
   live coalescers' grids/budgets/deadlines (the OOM-cap plumbing already
   proves coalescers can follow a live grid change), and a config epoch
   folds into the response cache via the commit hooks — a post-flip
   duplicate can never be answered with bytes produced under the old
   padding.

Ground (PAPERS.md): "Optimizing Inference Performance of Transformers on
CPUs" (bucket the shapes you actually observe) and "Flex-TPU: runtime
reconfigurable dataflow" (reconfigure what the chip runs per workload, not
per deployment).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Callable, Mapping, Optional, Sequence

import numpy as np

from arkflow_tpu.errors import ConfigError, TunerError
from arkflow_tpu.obs import global_registry

logger = logging.getLogger("arkflow.tpu.tuner")

#: chaos fault kinds a test/soak may arm on a tuner (consumed by the next
#: cycle's probe step — the rollback path a sick device would take)
TUNER_FAULT_KINDS = ("probe_fail",)


# -- config ------------------------------------------------------------------


@dataclass(frozen=True)
class TunerConfig:
    """Knobs for the ``tuner:`` block on ``tpu_inference``."""

    enabled: bool = True
    #: seconds between autonomous observe->propose cycles (0 disables the
    #: background loop; ``POST /admin/tune`` still forces cycles)
    interval_s: float = 30.0
    #: predicted-waste margin a proposal must beat the incumbent by —
    #: the hysteresis that prevents flapping on a stable workload
    min_improvement: float = 0.02
    #: packed fill p50 the token budget is sized toward
    target_fill: float = 0.97
    #: seq bucket edges round up to a multiple of this (lane alignment)
    align: int = 8
    #: bound on proposed seq-grid size (incumbent top bucket always kept)
    max_seq_buckets: int = 4
    #: reject proposals that would mint more than this many new executables
    max_compiles: int = 64
    #: length samples required before a proposal is considered
    min_samples: int = 256
    #: sliding window of per-row token lengths the sketch retains
    window: int = 4096
    #: clamp on the derived coalesce deadline
    deadline_min_s: float = 0.01
    deadline_max_s: float = 1.0
    #: deadline = slack x predicted budget fill time (headroom so the budget
    #: genuinely fills before the deadline flush)
    deadline_slack: float = 1.25


_TUNER_KEYS = {
    "enabled", "interval", "min_improvement", "target_fill", "align",
    "max_seq_buckets", "max_compiles", "min_samples", "window",
    "deadline_min", "deadline_max", "deadline_slack",
}


def parse_tuner_config(cfg: Any, who: str = "tpu_inference") -> Optional[TunerConfig]:
    """Pure parse of a ``tuner:`` block (config.py runs this at --validate
    without building a tuner or importing jax). None = no tuner."""
    if cfg is None or cfg is False:
        return None
    if cfg is True:
        return TunerConfig()
    if not isinstance(cfg, Mapping):
        raise ConfigError(f"{who}.tuner must be a mapping or boolean, got {cfg!r}")
    unknown = set(cfg) - _TUNER_KEYS
    if unknown:
        raise ConfigError(
            f"{who}.tuner: unknown keys {sorted(unknown)} "
            f"(allowed: {sorted(_TUNER_KEYS)})")
    from arkflow_tpu.utils.duration import parse_duration

    out: dict[str, Any] = {}
    enabled = cfg.get("enabled", True)
    if not isinstance(enabled, bool):
        raise ConfigError(f"{who}.tuner.enabled must be a bool, got {enabled!r}")
    out["enabled"] = enabled

    def _dur(key: str, attr: str, *, allow_zero: bool = False) -> None:
        v = cfg.get(key)
        if v is None:
            return
        s = parse_duration(v)
        if s < 0 or (s == 0 and not allow_zero):
            raise ConfigError(f"{who}.tuner.{key} must be positive, got {v!r}")
        out[attr] = s

    _dur("interval", "interval_s", allow_zero=True)
    _dur("deadline_min", "deadline_min_s")
    _dur("deadline_max", "deadline_max_s")

    def _frac(key: str, attr: str, lo: float, hi: float) -> None:
        v = cfg.get(key)
        if v is None:
            return
        if isinstance(v, bool) or not isinstance(v, (int, float)) \
                or not (lo <= float(v) <= hi):
            raise ConfigError(
                f"{who}.tuner.{key} must be a number in [{lo}, {hi}], got {v!r}")
        out[attr] = float(v)

    _frac("min_improvement", "min_improvement", 0.0, 1.0)
    _frac("target_fill", "target_fill", 0.1, 1.0)

    def _int(key: str, attr: str, minimum: int) -> None:
        v = cfg.get(key)
        if v is None:
            return
        if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
            raise ConfigError(
                f"{who}.tuner.{key} must be an int >= {minimum}, got {v!r}")
        out[attr] = v

    _int("align", "align", 1)
    _int("max_seq_buckets", "max_seq_buckets", 1)
    _int("max_compiles", "max_compiles", 1)
    _int("min_samples", "min_samples", 1)
    _int("window", "window", 8)
    slack = cfg.get("deadline_slack")
    if slack is not None:
        if isinstance(slack, bool) or not isinstance(slack, (int, float)) \
                or float(slack) < 1.0:
            raise ConfigError(
                f"{who}.tuner.deadline_slack must be a number >= 1, got {slack!r}")
        out["deadline_slack"] = float(slack)
    parsed = TunerConfig(**out)
    if parsed.deadline_min_s > parsed.deadline_max_s:
        raise ConfigError(
            f"{who}.tuner: deadline_min ({parsed.deadline_min_s}s) exceeds "
            f"deadline_max ({parsed.deadline_max_s}s)")
    return parsed


# -- the workload sketch -----------------------------------------------------


@dataclass(frozen=True)
class SketchView:
    """Immutable snapshot of the sketch — the planner's ONLY input, so a
    saved view replays to an identical proposal (determinism tests pin
    this)."""

    #: per-row token lengths, arrival order (the window's worth)
    lengths: np.ndarray
    #: EWMA of offered rows per second (0.0 = unknown/idle)
    arrival_rows_per_sec: float
    #: rows observed since the sketch was created (not just the window)
    rows_seen: int

    @property
    def n(self) -> int:
        return int(self.lengths.size)

    def quantile(self, q: float) -> float:
        if not self.lengths.size:
            return 0.0
        return float(np.quantile(self.lengths, q))

    @property
    def mean_len(self) -> float:
        return float(self.lengths.mean()) if self.lengths.size else 0.0


class WorkloadSketch:
    """Windowed workload observation: a ring buffer of recent per-row token
    lengths plus an arrival-rate EWMA. ``observe`` runs on the serving path
    (processor threads AND the event loop), so it is O(rows) numpy under a
    small lock; everything analytical happens on :meth:`snapshot` copies,
    off-path."""

    def __init__(self, window: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self._window = max(8, int(window))
        self._buf = np.zeros(self._window, np.int64)
        self._pos = 0
        self._filled = 0
        self._rows_seen = 0
        self._rate_ewma = 0.0
        self._last_t: Optional[float] = None
        self._clock = clock
        self._lock = threading.Lock()

    def observe(self, lengths: np.ndarray) -> None:
        lengths = np.asarray(lengths, np.int64).reshape(-1)
        if lengths.size == 0:
            return
        now = self._clock()
        with self._lock:
            n = min(lengths.size, self._window)
            take = lengths[-n:]
            end = self._pos + n
            if end <= self._window:
                self._buf[self._pos:end] = take
            else:
                split = self._window - self._pos
                self._buf[self._pos:] = take[:split]
                self._buf[:end - self._window] = take[split:]
            self._pos = end % self._window
            self._filled = min(self._window, self._filled + n)
            self._rows_seen += int(lengths.size)
            if self._last_t is not None:
                dt = now - self._last_t
                if dt > 1e-6:
                    sample = lengths.size / dt
                    self._rate_ewma += 0.2 * (sample - self._rate_ewma)
            self._last_t = now

    def snapshot(self) -> SketchView:
        with self._lock:
            if self._filled < self._window:
                lengths = self._buf[:self._filled].copy()
            else:
                # unroll the ring into arrival order
                lengths = np.concatenate(
                    [self._buf[self._pos:], self._buf[:self._pos]])
            return SketchView(lengths=lengths,
                              arrival_rows_per_sec=self._rate_ewma,
                              rows_seen=self._rows_seen)


# -- shapes ------------------------------------------------------------------


@dataclass(frozen=True)
class ShapeConfig:
    """One complete shape configuration — the unit proposals and rollbacks
    move around in."""

    batch_buckets: tuple[int, ...]
    seq_buckets: tuple[int, ...]
    example_scale: int = 1
    packed: bool = False
    #: coalescer token budget (packed serving); None = row-mode coalescing
    token_budget: Optional[int] = None
    #: coalesce deadline; None = leave the buffer's configured deadline
    deadline_s: Optional[float] = None

    def to_policy(self):
        from arkflow_tpu.tpu.bucketing import BucketPolicy

        return BucketPolicy(self.batch_buckets, self.seq_buckets,
                            self.example_scale)

    def report(self) -> dict:
        out = {"batch_buckets": list(self.batch_buckets),
               "seq_buckets": list(self.seq_buckets),
               "example_scale": self.example_scale}
        if self.token_budget is not None:
            out["token_budget"] = self.token_budget
        if self.deadline_s is not None:
            out["deadline_ms"] = round(self.deadline_s * 1000.0, 3)
        return out


@dataclass(frozen=True)
class Proposal:
    shape: ShapeConfig
    predicted_waste: float
    predicted_fill: float
    incumbent_waste: float
    #: incumbent_waste - predicted_waste (the hysteresis margin input)
    improvement: float
    notes: tuple[str, ...] = ()

    def report(self) -> dict:
        return {"shape": self.shape.report(),
                "predicted_waste": round(self.predicted_waste, 4),
                "predicted_fill": round(self.predicted_fill, 4),
                "incumbent_predicted_waste": round(self.incumbent_waste, 4),
                "improvement": round(self.improvement, 4),
                **({"notes": list(self.notes)} if self.notes else {})}


# -- the deterministic planner ----------------------------------------------


def _pick(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _align_up(n: float, align: int) -> int:
    n = max(1, int(np.ceil(n)))
    return ((n + align - 1) // align) * align


def quantile_aligned_edges(lengths: np.ndarray, top: int, *, align: int,
                           qs: Sequence[float]) -> tuple[int, ...]:
    """Seq bucket edges aligned to the OBSERVED length distribution: one
    ``align``-rounded edge per requested quantile, deduped, clamped to
    ``top`` — which is always kept as the final bucket (the configured top
    bucket is the truncation contract; the tuner only re-cuts the interior
    edges)."""
    edges: list[int] = []
    for q in qs:
        e = _align_up(float(np.quantile(lengths, q)), align)
        if align <= e < top and e not in edges:
            edges.append(e)
    return tuple(sorted(edges) + [top])


def _ffd_rows(lengths: np.ndarray, seq: int) -> int:
    """First-fit-decreasing bin count — the planner's twin of
    ``pack_tokens``'s binning (same order, same fit rule), so predicted row
    counts match what the packer will actually produce."""
    ls = np.minimum(np.maximum(np.asarray(lengths, np.int64), 1), seq)
    if ls.size == 0:
        return 0
    order = np.sort(ls)[::-1]
    bin_free = np.empty(ls.size, np.int64)
    n_bins = 0
    for length in order:
        fits = bin_free[:n_bins] >= length
        if n_bins and fits.any():
            b = int(np.argmax(fits))
        else:
            b = n_bins
            n_bins += 1
            bin_free[b] = seq
        bin_free[b] -= length
    return n_bins


def _emission_slices(lengths: np.ndarray, budget: int) -> list[np.ndarray]:
    """Split the sample (arrival order) into consecutive token-budget
    emissions, rows atomic — mirrors ``MicroBatchCoalescer._carve_tokens``'s
    carving discipline (a single over-budget row still flows solo)."""
    out: list[np.ndarray] = []
    cs = np.cumsum(lengths)
    start = 0
    base = 0
    while start < lengths.size:
        k = int(np.searchsorted(cs, base + budget, side="right"))
        if k <= start:
            k = start + 1
        out.append(lengths[start:k])
        base = float(cs[k - 1])
        start = k
    return out


def predict_waste(view: SketchView, shape: ShapeConfig) -> tuple[float, float]:
    """(capacity-weighted padding waste, fill) the workload in ``view``
    would pay under ``shape`` — the ONE evaluator both the incumbent and
    every proposal are scored with, so the hysteresis margin compares
    apples to apples. Deterministic in (view, shape)."""
    lengths = view.lengths
    if lengths.size == 0:
        return 0.0, 1.0
    true = 0.0
    cap = 0.0
    if shape.packed:
        budget = shape.token_budget
        if budget is None:
            budget = shape.batch_buckets[-1] * shape.seq_buckets[-1]
        for em in _emission_slices(lengths, budget):
            sb = _pick(int(em.max()), shape.seq_buckets)
            ls = np.minimum(em, sb)
            rows = _ffd_rows(ls, sb)
            top = shape.batch_buckets[-1]
            # over-top emissions carve into top-bucket windows cascading
            # down the grid (carve_row_windows); model the pad-up per chunk
            while rows > top:
                cap += top * sb
                rows -= top
            cap += _pick(rows, shape.batch_buckets) * sb
            true += float(ls.sum())
    else:
        # coalesced steady state: bucket-exact emissions of the top row
        # bucket; seq buckets by each emission's longest row (what the
        # processor's seq_bucket(max) does), tail emission on its row bucket
        rows_per = shape.batch_buckets[-1]
        for start in range(0, lengths.size, rows_per):
            em = lengths[start:start + rows_per]
            sb = _pick(int(em.max()), shape.seq_buckets)
            rb = _pick(int(em.size), shape.batch_buckets)
            cap += rb * sb
            true += float(np.minimum(em, sb).sum())
    if cap <= 0:
        return 0.0, 1.0
    fill = true / cap
    return 1.0 - fill, fill


def plan_shapes(view: SketchView, incumbent: ShapeConfig,
                cfg: TunerConfig) -> Proposal:
    """Deterministic shape proposal for the observed workload.

    Candidate seq grids are generated from quantile-aligned edges (several
    quantile sets, so skewed AND bimodal mixes both get a grid that hugs
    their modes), the packed token budget comes from simulating the real
    first-fit packing at the candidate grid, the deadline from the arrival
    rate, and the winner is whichever candidate the shared
    :func:`predict_waste` evaluator scores best. Pure function of
    ``(view, incumbent, cfg)`` — same inputs, same proposal, always."""
    if view.n == 0:
        return Proposal(shape=incumbent, predicted_waste=0.0,
                        predicted_fill=1.0, incumbent_waste=0.0,
                        improvement=0.0, notes=("empty sketch",))
    lengths = view.lengths
    top_seq = incumbent.seq_buckets[-1]
    row_buckets = incumbent.batch_buckets  # the row grid is a capacity
    # contract (backpressure bound, OOM caps); the tuner re-cuts seq edges,
    # budget, deadline and example_scale around it
    inc_waste, _ = predict_waste(view, incumbent)

    # candidate seq grids: quantile-edge sets (interior edges; top kept).
    # Several sets on purpose: skewed mixes want mid/high quantiles,
    # 50/50 bimodal mixes want a LOW quantile hugging the short mode (the
    # median falls between modes and helps neither) — the shared evaluator
    # below picks whichever grid the observed mix actually scores best on
    candidate_grids: list[tuple[int, ...]] = []
    for qs in ((0.5, 0.9), (0.75,), (0.5, 0.75, 0.95), (0.9,),
               (0.25, 0.5, 0.9), (0.45, 0.9), ()):
        grid = quantile_aligned_edges(lengths, top_seq, align=cfg.align,
                                      qs=qs[:max(0, cfg.max_seq_buckets - 1)])
        if grid not in candidate_grids:
            candidate_grids.append(grid)

    notes: list[str] = []
    best: Optional[tuple[float, float, ShapeConfig]] = None
    for grid in candidate_grids:
        if incumbent.packed:
            for shape in _packed_candidates(view, incumbent, grid, cfg):
                waste, fill = predict_waste(view, shape)
                if best is None or waste < best[0] - 1e-12:
                    best = (waste, fill, shape)
        else:
            shape = replace(incumbent, seq_buckets=grid, deadline_s=None)
            waste, fill = predict_waste(view, shape)
            if best is None or waste < best[0] - 1e-12:
                best = (waste, fill, shape)
    assert best is not None
    waste, fill, shape = best

    # deadline: size from the arrival rate so the emission target actually
    # fills before the deadline flush (no rate observed -> leave configured)
    rate = view.arrival_rows_per_sec
    if rate > 0:
        if shape.packed and shape.token_budget:
            fill_time = shape.token_budget / max(rate * max(view.mean_len, 1.0), 1e-6)
        else:
            fill_time = row_buckets[-1] / max(rate, 1e-6)
        deadline = min(max(cfg.deadline_slack * fill_time,
                           cfg.deadline_min_s), cfg.deadline_max_s)
        shape = replace(shape, deadline_s=deadline)
    else:
        notes.append("no arrival rate observed; deadline left as configured")

    return Proposal(shape=shape, predicted_waste=waste, predicted_fill=fill,
                    incumbent_waste=inc_waste,
                    improvement=inc_waste - waste, notes=tuple(notes))


def _packed_candidates(view: SketchView, incumbent: ShapeConfig,
                       grid: tuple[int, ...],
                       cfg: TunerConfig) -> list[ShapeConfig]:
    """Token-budget + example_scale candidates for one seq grid: the budget
    that fills the top (rows, seq) shape at the SIMULATED packing
    efficiency of the observed mix, plus small perturbations (the simulator
    scores them; the best survives)."""
    lengths = view.lengths
    top_rows = incumbent.batch_buckets[-1]
    sb_hat = _pick(int(np.quantile(lengths, 0.99)), grid)
    rows_all = _ffd_rows(lengths, sb_hat)
    eta = (float(np.minimum(lengths, sb_hat).sum()) / (rows_all * sb_hat)
           if rows_all else 1.0)
    base = max(sb_hat, int(top_rows * sb_hat * min(eta, cfg.target_fill + 0.03)))
    out: list[ShapeConfig] = []
    for scale in (1.0, 0.95, 1.05):
        budget = max(sb_hat, int(base * scale))
        # example grid must cover a budget emission's example count: es is
        # the pow2 extension of the row grid that reaches it
        mean_len = max(view.mean_len, 1.0)
        examples = int(np.ceil(budget / mean_len))
        es = 1
        while top_rows * es < examples and es < 64:
            es *= 2
        out.append(replace(incumbent, seq_buckets=grid, token_budget=budget,
                           example_scale=es, deadline_s=None))
    return out


# -- the manager -------------------------------------------------------------


class ShapeTuner:
    """Closes the observe -> propose -> warm -> flip loop for one serving
    processor, entirely off the serving path.

    The serving path's only contributions are O(rows) sketch observations;
    planning, warming (compiles) and probing all run in cycle tasks on
    executor threads. The flip reuses the hot-swap layer's unit discipline:
    every ``swap_units()`` member retargets and probes individually, and a
    failed probe rolls every flipped unit back to the incumbent grid with
    nothing flushed and the old shapes serving throughout.
    """

    def __init__(self, runner, *, model: str, cfg: Optional[TunerConfig] = None,
                 packed: bool = False, bus=None):
        from arkflow_tpu.tpu.bucketing import bucket_cap_bus

        self.runner = runner
        self.cfg = cfg or TunerConfig()
        self.packed = packed
        self.sketch = WorkloadSketch(self.cfg.window)
        self._bus = bus if bus is not None else bucket_cap_bus()
        self._controller = None
        self._commit_hooks: list[Callable[[], None]] = []
        #: stream-bound retarget listeners (the stream wires its OWN buffer
        #: here at build): when any are bound, commits notify exactly them
        #: and never touch the process-global bus — two streams with
        #: coincidentally-equal grids can each tune without disturbing the
        #: other. The bus broadcast remains the fallback for unbound use.
        self._bound_listeners: list[Any] = []
        self._chaos: deque[str] = deque()
        self._lock = asyncio.Lock()
        self._task: Optional[asyncio.Task] = None
        self.epoch = 0
        self._incumbent = self._shape_from_runner()
        self._last_decision: Optional[dict] = None
        self._last_error: Optional[str] = None

        reg = global_registry()
        labels = {"model": model}
        self.m_epoch = reg.gauge(
            "arkflow_tuner_epoch",
            "shape-config epoch (increments on each committed retune)", labels)
        self.m_epoch.set(0)
        self.m_predicted_waste = reg.gauge(
            "arkflow_tuner_predicted_waste",
            "planner-predicted capacity-weighted padding waste of the "
            "CURRENTLY-SERVING shape config against the live sketch", labels)
        self.m_proposals = reg.counter(
            "arkflow_tuner_proposals_total", "tuner proposals planned", labels)
        self.m_commits = reg.counter(
            "arkflow_tuner_commits_total", "tuner proposals committed", labels)
        self.m_rollbacks = reg.counter(
            "arkflow_tuner_rollbacks_total",
            "tuner flips rolled back (probe failure) with the incumbent "
            "grid serving throughout", labels)
        self.m_rejected = reg.counter(
            "arkflow_tuner_rejected_total",
            "tuner proposals rejected by hysteresis/compile gates", labels)

    # -- wiring ------------------------------------------------------------

    def _shape_from_runner(self) -> ShapeConfig:
        b = self.runner.buckets
        return ShapeConfig(
            batch_buckets=tuple(b.batch_buckets),
            seq_buckets=tuple(b.seq_buckets),
            example_scale=b.example_scale,
            packed=self.packed,
            token_budget=(b.token_budget(b.seq_buckets[-1])
                          if self.packed else None))

    def attach_overload_controller(self, controller) -> None:
        """Stream hook: the controller's step EWMA + AIMD window join the
        sketch report (and /health)."""
        self._controller = controller

    def bind_listener(self, listener) -> None:
        """Stream hook: bind a shape listener (the stream's own buffer) so
        commits retarget exactly this stream's coalescers — never another
        stream's that merely shares a grid."""
        if listener not in self._bound_listeners:
            self._bound_listeners.append(listener)

    def add_commit_hook(self, hook: Callable[[], None]) -> None:
        """Run after every COMMITTED flip (never on rejection/rollback):
        the response cache's epoch bump registers here, so a duplicate
        arriving after a shape flip recomputes instead of returning bytes
        produced under the old padding."""
        self._commit_hooks.append(hook)

    def inject_fault(self, kind: str) -> None:
        """Arm a one-shot chaos fault consumed by the NEXT cycle's probe
        (``probe_fail``): the flip must roll back to the incumbent grid."""
        if kind not in TUNER_FAULT_KINDS:
            raise ConfigError(
                f"unknown tuner fault kind {kind!r} ({'/'.join(TUNER_FAULT_KINDS)})")
        self._chaos.append(kind)

    def observe(self, lengths) -> None:
        """Serving-path feed: one batch's per-row token lengths."""
        self.sketch.observe(np.asarray(lengths))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start the background cycle loop on the running event loop."""
        if self._task is not None or self.cfg.interval_s <= 0:
            return
        self._task = asyncio.get_running_loop().create_task(self._run_loop())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _run_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_s)
            try:
                await self.run_cycle()
            except asyncio.CancelledError:
                raise
            except TunerError:
                pass  # rolled back; the decision/report carries the story
            except Exception:
                logger.exception("tuner cycle failed")

    # -- the cycle ---------------------------------------------------------

    async def run_cycle(self, force: bool = False) -> dict:
        """One observe->propose->warm->flip cycle. Returns the decision
        report; raises :class:`TunerError` when a probe failure rolled the
        flip back (the incumbent grid serving throughout). ``force``
        (``POST /admin/tune``) skips the sample-count gate down to a
        handful of rows — the hysteresis margin still applies, so a forced
        cycle on a stable workload is a no-op, not a flap."""
        async with self._lock:
            return await self._cycle_locked(force)

    async def _cycle_locked(self, force: bool) -> dict:
        loop = asyncio.get_running_loop()
        live_bb = tuple(self.runner.buckets.batch_buckets)
        if live_bb != self._incumbent.batch_buckets:
            # an OOM cap shrank the row grid under us: adopt it — the row
            # grid is a device FACT the planner must carry forward, or the
            # next flip would resurrect the exact buckets the device just
            # proved it cannot hold
            self._incumbent = replace(self._incumbent, batch_buckets=live_bb)
        view = self.sketch.snapshot()
        need = 8 if force else self.cfg.min_samples
        if view.n < need:
            decision = {"action": "skipped",
                        "reason": f"insufficient samples ({view.n} < {need})"}
            self._last_decision = decision
            return self._decision_report(decision)

        # planning simulates the real packing against the whole window —
        # tens of ms at full window — so it runs off the event loop like
        # every other tuner stage (the serving path only ever pays the
        # O(rows) sketch insert)
        proposal = await loop.run_in_executor(
            None, plan_shapes, view, self._incumbent, self.cfg)
        self.m_proposals.inc()
        # keep the serving-shape prediction gauge fresh even on rejection:
        # predicted-vs-measured waste is the tuner's honesty metric
        self.m_predicted_waste.set(proposal.incumbent_waste)

        if self._grids_equal(proposal.shape, self._incumbent):
            decision = {"action": "rejected", "reason": "proposal equals incumbent",
                        "proposal": proposal.report()}
            self.m_rejected.inc()
            self._last_decision = decision
            return self._decision_report(decision)
        if proposal.improvement < self.cfg.min_improvement:
            decision = {"action": "rejected",
                        "reason": (f"improvement {proposal.improvement:.4f} < "
                                   f"min_improvement {self.cfg.min_improvement}"),
                        "proposal": proposal.report()}
            self.m_rejected.inc()
            self._last_decision = decision
            return self._decision_report(decision)

        policy = proposal.shape.to_policy()
        # member 0's count is the honest cost for pools too: the other
        # members replay its compiles from the persistent cache
        n_new = self.runner.count_new_shapes(policy)
        if n_new > self.cfg.max_compiles:
            decision = {"action": "rejected",
                        "reason": (f"{n_new} new executables > max_compiles "
                                   f"{self.cfg.max_compiles}"),
                        "proposal": proposal.report()}
            self.m_rejected.inc()
            self._last_decision = decision
            return self._decision_report(decision)

        # warm: every new shape compiles OFF the serving path through the
        # persistent cache — each compile holds the in-flight permit (no
        # interleaving with live device schedules) and runs under the
        # first-compile watchdog, so a wedged compile aborts the cycle
        # instead of holding the tuner lock forever. Nothing has flipped
        # yet, so a warm failure needs no rollback.
        try:
            warmed = await self.runner.warm_shapes_live(policy)
        except Exception as e:
            decision = {"action": "warm_failed", "error": str(e),
                        "proposal": proposal.report()}
            self._last_decision = decision
            self._last_error = str(e)
            raise TunerError(
                f"shape warm failed before any flip: {e}; incumbent grid "
                "still serving") from e

        # flip + probe, one unit at a time; roll every flipped unit back on
        # any probe failure (the swap-unit discipline, reused verbatim)
        flipped: list[tuple[Any, Any]] = []
        try:
            for _label, member in self.runner.swap_units():
                old_policy = member.retarget_buckets(policy)
                flipped.append((member, old_policy))
                await self._probe(member, policy)
        except Exception as e:
            for member, old_policy in reversed(flipped):
                try:
                    member.retarget_buckets(old_policy)
                except Exception:
                    logger.exception("tuner rollback retarget failed")
            self.m_rollbacks.inc()
            decision = {"action": "rolled_back", "error": str(e),
                        "proposal": proposal.report()}
            self._last_decision = decision
            self._last_error = str(e)
            raise TunerError(
                f"shape flip rolled back at probe: {e}; incumbent grid "
                "still serving") from e

        # commit: only now do live coalescers retarget (a rollback must
        # flush/retarget nothing), and the config epoch folds into caches.
        # With stream-bound listeners the notification goes to exactly this
        # stream's buffer(s) — never across streams; the process-global bus
        # broadcast is the fallback for unbound (test/tool) tuners. Either
        # path clamps under any announced OOM cap.
        if self._bound_listeners:
            bb, tb = self._bus.clamp(proposal.shape.batch_buckets,
                                     proposal.shape.token_budget)
            for listener in self._bound_listeners:
                try:
                    applied = listener.retarget_shapes(
                        bb, tb, proposal.shape.deadline_s,
                        expect=self._incumbent.batch_buckets)
                    if applied is False:
                        # grid mismatch on the stream's OWN buffer is a
                        # misconfiguration (e.g. coalesce.dp not matching
                        # mesh dp) — say so instead of silently shipping
                        # half a commit
                        logger.warning(
                            "[tuner] commit did not retarget the stream's "
                            "coalescer: its grid does not match the "
                            "incumbent %s (check buffer.coalesce matches "
                            "the runner's grid, incl. dp scaling)",
                            self._incumbent.batch_buckets)
                except Exception:
                    logger.exception("tuner bound-listener retarget failed")
        else:
            self._bus.retarget(
                proposal.shape.batch_buckets,
                token_budget=proposal.shape.token_budget,
                deadline_s=proposal.shape.deadline_s,
                expect=self._incumbent.batch_buckets)
        self._incumbent = proposal.shape
        self.epoch += 1
        self.m_epoch.set(self.epoch)
        self.m_commits.inc()
        self.m_predicted_waste.set(proposal.predicted_waste)
        self._last_error = None
        for hook in self._commit_hooks:
            try:
                hook()
            except Exception:
                logger.exception("tuner commit hook failed")
        decision = {"action": "committed", "epoch": self.epoch,
                    "warmed_shapes": warmed, "new_shapes": n_new,
                    "proposal": proposal.report()}
        self._last_decision = decision
        logger.info("[tuner] committed shape epoch %d: %s", self.epoch,
                    proposal.shape.report())
        return self._decision_report(decision)

    @staticmethod
    def _grids_equal(a: ShapeConfig, b: ShapeConfig) -> bool:
        return (a.batch_buckets == b.batch_buckets
                and a.seq_buckets == b.seq_buckets
                and a.example_scale == b.example_scale
                and a.token_budget == b.token_budget)

    async def _probe(self, member, policy) -> None:
        """One real health-gated step on the NEW grid's top shape, through
        the runner's own serving path (heal gate, deadline watchdog) — the
        same dispatcher discipline as a hot-swap unit probe: a failing
        member enters its probe/backoff schedule."""
        if self._chaos and self._chaos[0] == "probe_fail":
            self._chaos.popleft()
            err = TunerError("chaos: injected tuner probe failure")
            try:
                member.core.note_external_failure(err)
            except Exception:
                pass
            raise err
        try:
            await member.infer(self._probe_inputs(member, policy))
        except Exception as e:
            try:
                member.core.note_external_failure(e)
            except Exception:
                pass
            raise

    def _probe_inputs(self, member, policy) -> dict[str, np.ndarray]:
        from arkflow_tpu.tpu.swap import golden_inputs

        seq = policy.seq_buckets[-1]
        rows = min(2, policy.batch_buckets[0])
        if not self.packed:
            return golden_inputs(member.spec, member.cfg, rows, seed=0x7DE,
                                 seq=seq)
        from arkflow_tpu.tpu.packing import pack_tokens

        rng = np.random.default_rng(0x7DE)
        vocab = int(getattr(member.cfg, "vocab_size", 256) or 256)
        ids = rng.integers(1, max(vocab, 2), size=(rows, seq)).astype(np.int32)
        pk = pack_tokens(ids, np.full(rows, seq, np.int64), seq)
        return {"input_ids": pk.input_ids, "segment_ids": pk.segment_ids,
                "position_ids": pk.position_ids, "example_row": pk.example_row,
                "example_pos": pk.example_pos}

    # -- introspection -----------------------------------------------------

    def _decision_report(self, decision: dict) -> dict:
        return {"epoch": self.epoch, **decision}

    def report(self) -> dict:
        """JSON-able snapshot for the engine's ``/health``."""
        from arkflow_tpu.tpu.jaxcache import cache_info

        view = self.sketch.snapshot()
        out: dict[str, Any] = {
            "enabled": self.cfg.enabled,
            "epoch": self.epoch,
            "packed": self.packed,
            "interval_s": self.cfg.interval_s,
            "incumbent": self._incumbent.report(),
            "predicted_waste": round(float(self.m_predicted_waste.value), 4),
            "proposals": int(self.m_proposals.value),
            "commits": int(self.m_commits.value),
            "rollbacks": int(self.m_rollbacks.value),
            "rejected": int(self.m_rejected.value),
            "sketch": {
                "rows_seen": view.rows_seen,
                "window_rows": view.n,
                "arrival_rows_per_sec": round(view.arrival_rows_per_sec, 2),
                "len_p50": round(view.quantile(0.5), 1),
                "len_p90": round(view.quantile(0.9), 1),
                "len_p99": round(view.quantile(0.99), 1),
            },
            "jax_cache": cache_info(),
        }
        # per-bucket dispatch counts from the runner(s): the observe side's
        # ground truth for which compiled shapes traffic actually lands on
        counts = getattr(self.runner, "dispatch_counts", None)
        if counts is not None:
            out["bucket_dispatches"] = _summarize_dispatches(counts())
        if self._controller is not None:
            try:
                out["overload"] = self._controller.signals()
            except Exception:
                pass
        if self._last_decision is not None:
            out["last_decision"] = self._last_decision
        if self._last_error:
            out["last_error"] = self._last_error
        return out


def _summarize_dispatches(counts: Mapping[tuple, int]) -> dict[str, int]:
    """Shape-key dispatch counts -> a compact ``"rows x seq" -> n`` map."""
    out: dict[str, int] = {}
    for key, n in counts.items():
        rows = seq = None
        for _, shape in key:
            if len(shape) >= 2 and seq is None:
                rows, seq = shape[0], shape[1]
        if rows is None and key:
            rows = key[0][1][0] if key[0][1] else 0
        label = f"{rows}x{seq}" if seq is not None else f"{rows}"
        out[label] = out.get(label, 0) + n
    return out


def build_shape_tuner(runner, *, model: str, cfg: Optional[TunerConfig],
                      packed: bool, cache=None) -> Optional[ShapeTuner]:
    """Processor-builder entry: None when the block is absent/disabled."""
    if cfg is None or not cfg.enabled:
        return None
    if getattr(runner, "_pp_plan", None) is not None:
        # a warm compile interleaving its collectives with a live GPipe
        # schedule can deadlock the ring (the same hazard that pinned pp
        # probes under the in-flight permit at max_in_flight 1 — which
        # would serialize every warm compile against serving anyway)
        raise ConfigError(
            "tpu_inference: 'tuner' does not compose with mesh pp "
            "(pipelined stages serve one schedule at a time; retune the "
            "pp grid by redeploy instead)")
    tuner = ShapeTuner(runner, model=model, cfg=cfg, packed=packed)
    if cache is not None:
        tuner.add_commit_hook(cache.bump_epoch)
    return tuner
