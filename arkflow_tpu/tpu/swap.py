"""Zero-downtime model lifecycle: rolling hot-swap with canary + rollback.

ROADMAP item 4 names "rolling model hot-swap so a weight update never stops
serving" as a required capability of the serving tier; until this module the
only way to change weights was a full process restart — every in-flight
batch dropped, every executable recompiled from cold. Flex-TPU (PAPERS.md)
makes the same argument at the hardware layer: reconfigure at runtime
instead of tearing down. ``ModelSwapManager`` gives the serving tier that
property for model weights:

1. **Restore off the serving path.** The candidate checkpoint is restored
   and dtype-converted on an executor thread against a freshly-initialized
   host tree — the live params are never touched, and a corrupt/mismatched
   checkpoint fails here (``ConfigError`` from ``tpu/checkpoint.py``) with
   the old version serving throughout.
2. **Canary-verify.** A deterministic golden batch runs through the model
   family's forward with the LIVE params and with the candidate; the swap
   proceeds only when their argmax signatures agree to ``min_agreement``
   (default 1.0 — right for same-prediction weight refreshes; lower it for
   genuinely behavior-changing updates, or set ``rows: 0`` to skip).
3. **Flip atomically, one serving unit at a time.** ``ModelRunner`` params
   ride the jitted step as an argument, so a flip is one attribute
   assignment — no recompiles, in-flight steps finish on the weights they
   already read. ``ModelRunnerPool`` members flip one at a time, so the
   pool keeps serving on N-1 members while each flips and probes. The
   continuous ``GenerationServer`` flips only after its slot grid drains,
   then rebuilds its jits and resets page pools + prefix cache (cached KV
   against new weights is a silent correctness bug — so are response-cache
   hits, which the commit hooks epoch-flush).
4. **Probe, then commit — or roll back.** After each flip one real
   health-gated step runs through the unit (the PR-4 serving core: deadline
   watchdog, probe/backoff on failure). Any probe failure, canary
   disagreement, restore error, or chaos-injected crash rolls every flipped
   unit back to the prior params and raises ``SwapError`` — the old version
   served continuously and keeps serving.

Chaos: ``inject_swap_fault("swap_corrupt")`` mangles the next swap's
restored tree (the canary/rollback path a truncated checkpoint would take);
``"swap_crash"`` raises mid-roll after the first unit flipped (the
rollback-under-partial-flip path a crashed operator process would leave).
Both are armed by the fault plugin's processor wrapper, like hang/oom.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Optional, Sequence

import numpy as np

from arkflow_tpu.errors import ConfigError, SwapError
from arkflow_tpu.obs import global_registry

logger = logging.getLogger("arkflow.tpu.swap")

#: chaos fault kinds the fault plugin may arm on a swapper
SWAP_FAULT_KINDS = ("swap_corrupt", "swap_crash")


@dataclass(frozen=True)
class SwapConfig:
    """Knobs for the ``swap:`` block on ``tpu_inference``/``tpu_generate``."""

    #: golden-batch rows for the canary (0 disables canary verification)
    canary_rows: int = 4
    #: fraction of golden argmax positions that must agree between the live
    #: model and the candidate (1.0 = exact)
    min_agreement: float = 1.0
    #: rng seed for the golden batch (deterministic across both runs)
    canary_seed: int = 0x5117
    #: continuous generation only: budget for the slot grid to run dry
    drain_timeout_s: float = 30.0


def parse_swap_config(cfg: Any, who: str = "processor") -> SwapConfig:
    """Pure parse of a ``swap:`` block (config.py runs this at --validate
    without building a swapper or importing jax)."""
    if cfg is None:
        return SwapConfig()
    if not isinstance(cfg, Mapping):
        raise ConfigError(f"{who}.swap must be a mapping, got {cfg!r}")
    unknown = set(cfg) - {"canary", "drain_timeout"}
    if unknown:
        raise ConfigError(
            f"{who}.swap: unknown keys {sorted(unknown)} "
            "(allowed: canary, drain_timeout)")
    out: dict[str, Any] = {}
    canary = cfg.get("canary")
    if canary is not None:
        if not isinstance(canary, Mapping):
            raise ConfigError(f"{who}.swap.canary must be a mapping, got {canary!r}")
        bad = set(canary) - {"rows", "min_agreement", "seed"}
        if bad:
            raise ConfigError(
                f"{who}.swap.canary: unknown keys {sorted(bad)} "
                "(allowed: rows, min_agreement, seed)")
        rows = canary.get("rows", SwapConfig.canary_rows)
        if isinstance(rows, bool) or not isinstance(rows, int) or rows < 0:
            raise ConfigError(
                f"{who}.swap.canary.rows must be an int >= 0, got {rows!r}")
        out["canary_rows"] = rows
        agree = canary.get("min_agreement", SwapConfig.min_agreement)
        if isinstance(agree, bool) or not isinstance(agree, (int, float)) \
                or not (0.0 <= float(agree) <= 1.0):
            raise ConfigError(
                f"{who}.swap.canary.min_agreement must be in [0, 1], got {agree!r}")
        out["min_agreement"] = float(agree)
        seed = canary.get("seed", SwapConfig.canary_seed)
        if isinstance(seed, bool) or not isinstance(seed, int):
            raise ConfigError(
                f"{who}.swap.canary.seed must be an int, got {seed!r}")
        out["canary_seed"] = seed
    drain = cfg.get("drain_timeout")
    if drain is not None:
        from arkflow_tpu.utils.duration import parse_duration

        drain_s = parse_duration(drain)
        if drain_s <= 0:
            raise ConfigError(
                f"{who}.swap.drain_timeout must be positive, got {drain!r}")
        out["drain_timeout_s"] = drain_s
    return SwapConfig(**out)


# -- golden batch / canary signature ----------------------------------------


def golden_inputs(spec: Mapping[str, tuple], cfg, rows: int, seed: int,
                  seq: int = 16) -> dict[str, np.ndarray]:
    """Deterministic spec-shaped inputs for the canary: token ids drawn
    below the model's vocab, masks a contiguous prefix of ones (the flash
    kernels' contract), float features standard-normal. Same (spec, cfg,
    rows, seed) => bitwise-same batch, so live and candidate score the
    exact same inputs."""
    rng = np.random.default_rng(seed)
    vocab = int(getattr(cfg, "vocab_size", 256) or 256)
    out: dict[str, np.ndarray] = {}
    for name, (dtype, trailing) in spec.items():
        dims = tuple(seq if d == "seq" else int(d) for d in trailing)
        shape = (rows, *dims)
        if name == "attention_mask":
            out[name] = np.ones(shape, dtype)
        elif np.issubdtype(np.dtype(dtype), np.integer):
            out[name] = rng.integers(1, max(vocab, 2), size=shape).astype(dtype)
        else:
            out[name] = rng.standard_normal(shape).astype(dtype)
    return out


def argmax_signature(outputs: Mapping[str, Any]) -> np.ndarray:
    """Discrete decision signature of a forward pass: the argmax over the
    class/vocab axis of the logits (robust to benign float drift between
    hosts/devices in a way raw logits are not)."""
    cand = outputs.get("logits")
    if cand is None:
        for v in outputs.values():
            arr = np.asarray(v)
            if arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating):
                cand = v
                break
    if cand is None:  # no float output: compare the first output verbatim
        return np.asarray(next(iter(outputs.values())))
    return np.asarray(np.argmax(np.asarray(cand, np.float32), axis=-1))


def signature_margin(outputs: Mapping[str, Any]) -> float:
    """Smallest top-1/top-2 logit gap over every argmax position of the
    signature: the tie-distance of :func:`argmax_signature`. A golden batch
    is only trustworthy as a corruption detector when this margin clears the
    serving dtype's noise floor — otherwise benign rounding drift between a
    host-computed reference and the device flips the signature and reads as
    corruption (tpu/integrity.py searches seeds until it does clear).
    Returns +inf when no float output exists (exact-compare signatures have
    no ties by construction)."""
    cand = outputs.get("logits")
    if cand is None:
        for v in outputs.values():
            arr = np.asarray(v)
            if arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating):
                cand = v
                break
    if cand is None:
        return float("inf")
    arr = np.asarray(cand, np.float32)
    if arr.shape[-1] < 2:
        return float("inf")
    top2 = np.partition(arr, -2, axis=-1)[..., -2:]
    return float(np.min(top2[..., 1] - top2[..., 0]))


# -- swap units (one per independently-flippable serving surface) ------------


class BatchRunnerUnit:
    """One ``ModelRunner`` (standalone, or a pool member): place/flip are the
    runner's own swap surface; the probe is one real health-gated step."""

    def __init__(self, runner, label: str):
        self.runner = runner
        self.label = label

    def live(self):
        return self.runner.params

    def place(self, host_params):
        return self.runner.place_params(host_params)

    async def adopt(self, placed):
        return self.runner.adopt_params(placed)

    def note_committed_host(self, host) -> None:
        """A committed swap makes ``host`` the member's known-good tree:
        the integrity monitor's repair source must track the serving
        version, or a post-swap repair would silently roll weights back."""
        self.runner.host_params = host

    def _probe_inputs(self) -> dict[str, np.ndarray]:
        r = self.runner
        seq = min(r.buckets.seq_buckets) if r.buckets.seq_buckets else 16
        rows = min(2, r.buckets.batch_buckets[0]) if r.buckets.batch_buckets else 1
        if not r.packed:
            return golden_inputs(r.spec, r.cfg, rows, seed=0xB0B, seq=seq)
        # packed runners consume the packed layout; build a tiny valid one
        from arkflow_tpu.tpu.packing import pack_tokens

        rng = np.random.default_rng(0xB0B)
        vocab = int(getattr(r.cfg, "vocab_size", 256) or 256)
        ids = rng.integers(1, max(vocab, 2), size=(rows, seq)).astype(np.int32)
        pk = pack_tokens(ids, np.full(rows, seq, np.int64), seq)
        return {"input_ids": pk.input_ids, "segment_ids": pk.segment_ids,
                "position_ids": pk.position_ids, "example_row": pk.example_row,
                "example_pos": pk.example_pos}

    async def probe(self) -> None:
        """One real step through the runner's own gate (heal gate, deadline
        watchdog). The swap manager is a dispatcher here: a failed probe
        applies the shared ``note_external_failure`` policy (deadline
        misses/OOMs self-mark inside the step), so the rolled-back unit
        enters the SAME probe/backoff schedule pool dispatch honors."""
        try:
            await self.runner.infer(self._probe_inputs())
        except Exception as e:
            self.runner.core.note_external_failure(e)
            raise


class BatchGenerateUnit:
    """``tpu_generate`` in batch mode: the processor holds the params and
    its whole-generation jit takes them as an argument — flip is one
    assignment, like the batch runner."""

    label = "generate[batch]"

    def __init__(self, proc):
        self.proc = proc

    def live(self):
        return self.proc.params

    def place(self, host_params):
        return self.proc._place_params(host_params)

    async def adopt(self, placed):
        old, self.proc.params = self.proc.params, placed
        return old

    def note_committed_host(self, host) -> None:
        self.proc.host_params = host

    def _probe_blocking(self) -> None:
        import jax
        import jax.numpy as jnp

        p = self.proc
        seq = min(8, p.buckets.seq_bucket(8))
        ids = np.ones((p.buckets.batch_bucket(1), seq), np.int32)
        lengths = np.ones(ids.shape[0], np.int32)
        # fixed key: the probe must not race the serving path's rng state
        out = p._generate(p.params, input_ids=jnp.asarray(ids),
                          lengths=jnp.asarray(lengths, jnp.int32),
                          n_real=jnp.asarray(1, jnp.int32),
                          rng_key=jax.random.PRNGKey(0))
        jax.block_until_ready(out)

    async def probe(self) -> None:
        await asyncio.get_running_loop().run_in_executor(
            None, self._probe_blocking)


class GenerationServerUnit:
    """Continuous generation: the server drains its slot grid, flips,
    rebuilds jits, and resets pools/prefix cache inside ``swap_params``;
    the probe is one real (health-gated) generation."""

    label = "generate[continuous]"

    def __init__(self, server, place_fn: Callable[[Any], Any],
                 drain_timeout_s: float, owner=None):
        self.server = server
        self._place_fn = place_fn
        self._drain_timeout_s = drain_timeout_s
        #: the TpuGenerateProcessor holding a ``params`` alias of the
        #: server's tree: kept in sync on every flip, or the boot-time tree
        #: would stay pinned in device memory for the process lifetime (a
        #: third full weight copy on every later swap) and introspection
        #: would read version-0 weights forever
        self._owner = owner

    def live(self):
        return self.server.params

    def place(self, host_params):
        return self._place_fn(host_params)

    async def adopt(self, placed):
        old = await self.server.swap_params(placed, self._drain_timeout_s)
        if self._owner is not None:
            self._owner.params = placed
        return old

    def note_committed_host(self, host) -> None:
        if self._owner is not None:
            self._owner.host_params = host

    async def probe(self) -> None:
        vocab = int(getattr(self.server.cfg, "vocab_size", 256) or 256)
        await self.server.generate([t % max(vocab, 2) for t in (3, 5, 7)],
                                   max_new_tokens=2)


# -- the manager -------------------------------------------------------------


class ModelSwapManager:
    """Orchestrates one rolling hot-swap at a time over a list of units.

    ``prepare(path)`` is the blocking restore+convert (runs on an executor
    thread, off the serving path); ``canary(params)`` is the blocking golden
    forward returning an :func:`argmax_signature`-style array. ``commit
    hooks`` run after a successful swap — the response cache's epoch bump
    registers here so post-swap duplicates can never return pre-swap bytes.
    """

    def __init__(self, *, name: str, config: Optional[SwapConfig] = None,
                 prepare: Callable[[str], Any],
                 canary: Callable[[Any], np.ndarray],
                 units: Sequence[Any],
                 checkpoint: Optional[str] = None):
        if not units:
            raise ConfigError("ModelSwapManager needs at least one swap unit")
        self.name = name
        self.cfg = config or SwapConfig()
        self._prepare = prepare
        self._canary = canary
        self.units = list(units)
        #: monotonically-increasing model-version epoch; 0 = the params the
        #: process booted with (possibly from ``checkpoint:`` config)
        self.version = 0
        self.checkpoint = checkpoint
        self._lock = asyncio.Lock()
        self._state = "idle"
        self._last_error: Optional[str] = None
        self._chaos: deque[str] = deque()
        self._commit_hooks: list[Callable[[], None]] = []
        #: SDC monitor (tpu/integrity.py), attached by processor builders
        #: when both features are on: probing quiesces across the roll
        #: (mid-flip members legitimately diverge from the golden
        #: reference — a probe would quarantine them and "repair" would
        #: silently roll the swap back), and a committed swap recomputes
        #: the reference + repair source against the new weights
        self.integrity = None

        reg = global_registry()
        labels = {"model": name}
        self.m_version = reg.gauge(
            "arkflow_model_version",
            "model-version epoch (increments on each committed hot-swap)",
            labels)
        self.m_version.set(0)
        self.m_started = reg.counter(
            "arkflow_swap_started_total", "hot-swap attempts started", labels)
        self.m_completed = reg.counter(
            "arkflow_swap_completed_total", "hot-swaps committed", labels)
        self.m_rolled_back = reg.counter(
            "arkflow_swap_rolled_back_total",
            "hot-swaps rolled back (canary/restore/probe failure) with the "
            "prior version serving throughout", labels)
        #: per-instance counts for report() (the registry dedupes series on
        #: (name, labels): two streams serving the same model share counters)
        self.n_started = self.n_completed = self.n_rolled_back = 0

    # -- chaos / hooks ------------------------------------------------------

    def inject_swap_fault(self, kind: str) -> None:
        """Arm a one-shot fault consumed by the NEXT swap (fault plugin):
        ``swap_corrupt`` mangles the restored tree so the canary rejects it;
        ``swap_crash`` raises mid-roll after the first unit flipped so the
        partial-flip rollback path runs."""
        if kind not in SWAP_FAULT_KINDS:
            raise ConfigError(
                f"unknown swap fault kind {kind!r} ({'/'.join(SWAP_FAULT_KINDS)})")
        self._chaos.append(kind)

    def _consume_chaos(self, kind: str) -> bool:
        if self._chaos and self._chaos[0] == kind:
            self._chaos.popleft()
            return True
        return False

    def add_commit_hook(self, hook: Callable[[], None]) -> None:
        """Run whenever the WEIGHTS SERVING TRAFFIC may have changed: after
        every committed swap, and after a rollback in which any unit had
        already flipped (a flipped member may have answered live requests
        with the candidate weights — those responses must not survive in
        any cache). Swap-aware caches flush here."""
        self._commit_hooks.append(hook)

    def _run_flush_hooks(self) -> None:
        for hook in self._commit_hooks:
            try:
                hook()
            except Exception:  # a cache flush must not undo/compound a swap
                logger.exception("[%s] swap flush hook failed", self.name)

    # -- introspection ------------------------------------------------------

    def report(self) -> dict:
        """JSON-able snapshot for the engine's ``/health``."""
        rep = {
            "version": self.version,
            "checkpoint": self.checkpoint,
            "state": self._state,
            "units": len(self.units),
            "started": self.n_started,
            "completed": self.n_completed,
            "rolled_back": self.n_rolled_back,
        }
        if self._last_error:
            rep["last_error"] = self._last_error
        return rep

    # -- the swap -----------------------------------------------------------

    @staticmethod
    def _mangle(host_params):
        """swap_corrupt: the restored-garbage a truncated/mangled checkpoint
        would produce — every float leaf perturbed hard enough that no
        argmax survives, deterministically."""
        import jax

        def garble(leaf):
            if hasattr(leaf, "dtype") and np.issubdtype(
                    np.asarray(leaf).dtype, np.floating):
                return np.asarray(leaf) * -1000.0 + 3.7
            return leaf

        return jax.tree_util.tree_map(garble, host_params)

    def _prepare_checked(self, checkpoint: str):
        host = self._prepare(checkpoint)
        if self._consume_chaos("swap_corrupt"):
            logger.warning("[%s] chaos: mangling restored checkpoint tree",
                           self.name)
            host = self._mangle(host)
        return host

    def _fail(self, stage: str, err: Exception) -> SwapError:
        self.m_rolled_back.inc()
        self.n_rolled_back += 1
        msg = f"swap rolled back at {stage}: {err}"
        self._last_error = msg
        logger.warning("[%s] %s (version %d still serving)",
                       self.name, msg, self.version)
        return SwapError(f"[{self.name}] {msg}; version {self.version} "
                         "still serving")

    async def swap(self, checkpoint: str) -> dict:
        """Run one rolling hot-swap to ``checkpoint``. Returns the committed
        report; raises ``SwapError`` on rejection/rollback (the prior params
        served continuously either way)."""
        if self._lock.locked():
            raise SwapError(f"[{self.name}] a swap is already in progress")
        async with self._lock:
            loop = asyncio.get_running_loop()
            self.m_started.inc()
            self.n_started += 1
            self._state = "restoring"
            if self.integrity is not None:
                await self.integrity.begin_quiesce()
            try:
                # 1. restore + convert the candidate OFF the serving path
                try:
                    host = await loop.run_in_executor(
                        None, self._prepare_checked, checkpoint)
                except Exception as e:
                    raise self._fail("restore", e) from e

                # 2. canary: the candidate must agree with the live model on
                # the golden batch before any serving unit flips
                self._state = "canary"
                placed0 = None
                if self.cfg.canary_rows > 0:
                    try:
                        placed0 = await loop.run_in_executor(
                            None, self.units[0].place, host)
                        live_sig, cand_sig = await loop.run_in_executor(
                            None, self._canary_pair, placed0)
                    except Exception as e:
                        raise self._fail("canary", e) from e
                    agreement = (float(np.mean(live_sig == cand_sig))
                                 if live_sig.size else 1.0)
                    if agreement < self.cfg.min_agreement:
                        raise self._fail("canary", SwapError(
                            f"golden-batch agreement {agreement:.3f} < "
                            f"min_agreement {self.cfg.min_agreement:.3f}"))

                # 3. rolling flip: one unit at a time, probe after each —
                # the pool keeps serving on the not-yet-flipped members
                self._state = "rolling"
                flipped: list[tuple[Any, Any]] = []
                try:
                    for i, unit in enumerate(self.units):
                        placed = (placed0 if i == 0 and placed0 is not None
                                  else await loop.run_in_executor(
                                      None, unit.place, host))
                        old = await unit.adopt(placed)
                        flipped.append((unit, old))
                        if self._consume_chaos("swap_crash"):
                            raise SwapError(
                                "chaos: injected crash mid-swap "
                                f"({len(flipped)}/{len(self.units)} units flipped)")
                        await unit.probe()
                except Exception as e:
                    await self._rollback(flipped)
                    if flipped:
                        # live traffic may have been answered by the
                        # candidate weights while a unit was flipped — those
                        # responses must not survive the rollback in any
                        # cache, so the flush hooks run here too
                        self._run_flush_hooks()
                    raise self._fail("rolling flip", e) from e

                # 4. commit — the committed host tree becomes every unit's
                # known-good repair source, and the integrity monitor's
                # golden reference recomputes against it (the old reference
                # would read the NEW weights as corruption)
                for unit in self.units:
                    note = getattr(unit, "note_committed_host", None)
                    if note is not None:
                        note(host)
                if self.integrity is not None:
                    await loop.run_in_executor(
                        None, self.integrity.rebuild_reference, host)
                self.version += 1
                self.checkpoint = checkpoint
                self.m_version.set(self.version)
                self.m_completed.inc()
                self.n_completed += 1
                self._last_error = None
                self._run_flush_hooks()
                logger.info("[%s] hot-swap committed: version %d <- %s",
                            self.name, self.version, checkpoint)
                self._state = "idle"
                return self.report()
            finally:
                self._state = "idle"
                if self.integrity is not None:
                    self.integrity.end_quiesce()

    def _canary_pair(self, placed_candidate) -> tuple[np.ndarray, np.ndarray]:
        """Blocking golden forwards (executor thread): live first, then the
        candidate, on identical inputs."""
        live = self._canary(self.units[0].live())
        cand = self._canary(placed_candidate)
        return np.asarray(live), np.asarray(cand)

    async def _rollback(self, flipped: list[tuple[Any, Any]]) -> None:
        """Re-adopt the prior params on every flipped unit, newest first.
        The old trees are the exact device/sharded arrays that were serving
        before, so re-adoption can't fail on placement; a unit whose
        re-adopt still raises is left to the PR-4 probe/backoff schedule
        (marked unhealthy by its own failing step, re-admitted by probes)."""
        for unit, old in reversed(flipped):
            try:
                await unit.adopt(old)
            except Exception:
                logger.exception(
                    "[%s] rollback re-adopt failed on %s; unit left to its "
                    "probe/backoff schedule", self.name,
                    getattr(unit, "label", "unit"))


# -- builders ---------------------------------------------------------------


def build_batch_swapper(runner, *, model: str, serving_dtype: Optional[str],
                        seed: int, swap_cfg: Optional[SwapConfig],
                        checkpoint: Optional[str] = None) -> ModelSwapManager:
    """Swapper over a ``ModelRunner`` or ``ModelRunnerPool`` (one unit per
    pool member — the rolling flip IS the N-1 availability story)."""
    from arkflow_tpu.tpu.runner import convert_for_serving, init_host_params

    family, cfg = runner.family, runner.cfg
    units = [BatchRunnerUnit(member, label)
             for label, member in runner.swap_units()]
    swap_cfg = swap_cfg or SwapConfig()

    def prepare(path: str):
        # one restore + ONE dtype convert for the whole pool (the full-tree
        # walk is the expensive part), exactly like pool construction
        return convert_for_serving(
            init_host_params(family, cfg, seed, checkpoint=path),
            serving_dtype, family.name)

    def canary(params) -> np.ndarray:
        golden = golden_inputs(
            family.input_spec(cfg), cfg, swap_cfg.canary_rows,
            seed=swap_cfg.canary_seed)
        return argmax_signature(family.apply(params, cfg, **golden))

    return ModelSwapManager(name=model, config=swap_cfg, prepare=prepare,
                            canary=canary, units=units, checkpoint=checkpoint)


def build_generate_swapper(proc, *, model: str, seed: int,
                           swap_cfg: Optional[SwapConfig],
                           checkpoint: Optional[str] = None) -> ModelSwapManager:
    """Swapper over a ``TpuGenerateProcessor`` (batch mode flips the
    processor's own params; continuous mode drains and flips the server)."""
    from arkflow_tpu.tpu.runner import init_host_params

    family, cfg = proc.family, proc.cfg
    swap_cfg = swap_cfg or SwapConfig()
    if proc._server is not None:
        units: list[Any] = [GenerationServerUnit(
            proc._server, proc._place_params, swap_cfg.drain_timeout_s,
            owner=proc)]
    else:
        units = [BatchGenerateUnit(proc)]

    def prepare(path: str):
        return init_host_params(family, cfg, seed, checkpoint=path)

    def canary(params) -> np.ndarray:
        golden = golden_inputs(
            family.input_spec(cfg), cfg, swap_cfg.canary_rows,
            seed=swap_cfg.canary_seed)
        return argmax_signature(family.apply(params, cfg, **golden))

    return ModelSwapManager(name=model, config=swap_cfg, prepare=prepare,
                            canary=canary, units=units, checkpoint=checkpoint)
