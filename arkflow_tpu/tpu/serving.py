"""Continuous-batching generation server over the paged KV cache.

The serving pattern the reference cannot express (its processors are
stateless user code): a fixed grid of decode slots steps in lockstep under
one jitted ``paged_decode_step``; requests are admitted into free slots the
moment pages are available, finished sequences free their pages immediately,
and new work rides along mid-flight — the device never waits for the
longest sequence in a batch (continuous batching, as in vLLM/Orca).

Split of responsibilities (TPU-first):
- device: static-shaped jitted prefill/decode (models/paged_decode.py);
  compiled once per (slot-count, page-table-width) + per prompt bucket.
- host (this module): page allocation, slot bookkeeping, EOS/max-token
  tracking, admission — cheap numpy/python between steps.

Multi-chip (``mesh``): the server runs tensor-parallel over a Mesh's ``tp``
axis. The page pools shard over KV heads (``P(None, None, None, "tp",
None)``), params carry their tensor-parallel PartitionSpecs, and every jitted
step is built with explicit NamedSharding in/out shardings — page tables,
token ids, and lengths stay static-shaped and replicated, so the layer scan
lowers to GSPMD collectives with zero dynamic shapes. The host-side
scheduler is untouched: it only ever sees replicated scalars.

Self-healing: the server sits on the shared ``ServingRunnerCore``
(tpu/serving_core.py) — the same health state machine, step-deadline
watchdog, and chaos hooks the ``tpu_inference`` runner uses. A generate step
that blows its deadline marks the server UNHEALTHY, fails every in-flight
request (their batches NACK for redelivery), and the next step waits out the
probe backoff, rebuilds the jitted steps, and reinitializes the pools.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from arkflow_tpu.errors import ConfigError, StepDeadlineExceeded
from arkflow_tpu.models.decoder import DecoderConfig
from arkflow_tpu.models.paged_decode import (
    init_page_pool,
    paged_decode_step,
    paged_prefill,
)
from arkflow_tpu.obs import global_registry
from arkflow_tpu.tpu.serving_core import ServingRunnerCore

logger = logging.getLogger("arkflow.serving")


@dataclass
class _Request:
    prompt: list[int]
    max_new_tokens: int
    future: asyncio.Future
    tokens: list[int] = field(default_factory=list)
    #: wall-clock (monotonic) submit stamp for the TTFT histogram
    submitted_at: float = 0.0
    #: set once the first decoded token has been observed for this request
    ttft_stamped: bool = False
    #: disaggregated serving: stop after prefill and resolve the future
    #: with a KV-page export instead of decoding locally
    prefill_only: bool = False
    #: disaggregated serving: a received KV-page export to adopt instead
    #: of prefilling (the decode half of a prefill/decode split)
    adopt: Optional[dict] = None
    #: export payload built by ``_export_and_finish`` (prefill_only path)
    export: Optional[dict] = None


@dataclass
class _InFlightDecode:
    """One dispatched-but-unapplied decode step (``dispatch_depth`` 2).

    ``nxt`` is the step's DEVICE-resident next-token array — fed straight
    into the next dispatch so the device never waits for a host round trip.
    ``reqs`` snapshots per-slot request identity at dispatch: a slot whose
    request finished (or was replaced) between dispatch and apply drops its
    token instead of crediting it to the wrong request."""

    nxt: object
    act: "np.ndarray"
    reqs: list
    dispatched_at: float


class GenerationServer:
    """Greedy continuous-batching decode over ``slots`` lockstep lanes."""

    def __init__(self, params, cfg: DecoderConfig, *, slots: int = 8,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_seq: int = 512, eos_id: int = 2,
                 prompt_buckets: Optional[list[int]] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 prefill_chunk: int = 0, speculative_tokens: int = 0,
                 prefix_cache_pages: int = 0, mesh=None,
                 decode_kernel: str = "auto", kernel_interpret: bool = False,
                 kernel_parity_check: bool = True, dispatch_depth: int = 1,
                 step_deadline_s: Optional[float] = None,
                 step_deadline_first_s: Optional[float] = None,
                 health_config=None, name: str = "decoder_lm"):
        from arkflow_tpu.tpu.jaxcache import enable_persistent_cache

        enable_persistent_cache()
        if cfg.use_ring_attention:
            raise ConfigError("paged serving does not support ring attention")
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.page_size = page_size
        self.max_seq = max_seq
        self.eos_id = eos_id
        self.pages_per_slot = -(-max_seq // page_size)
        # page 0 is scratch; default pool fits every slot at max_seq
        self.num_pages = num_pages or (1 + self.slots * self.pages_per_slot)
        if self.num_pages < 1 + self.pages_per_slot:
            raise ConfigError(
                f"num_pages={self.num_pages} cannot hold one sequence "
                f"({self.pages_per_slot} pages + scratch)")
        # always top out at max_seq so every admissible prompt has a bucket
        # (generate() rejects prompts longer than max_seq up front)
        self.prompt_buckets = sorted(
            {b for b in (prompt_buckets or [32, 128]) if b <= max_seq} | {max_seq})

        # tensor-parallel serving: the page pools shard over KV heads on the
        # mesh's tp axis; everything the host scheduler touches (page tables,
        # token ids, lengths, active masks) stays replicated, so admission /
        # page accounting is identical whether one chip serves or eight
        self.mesh = mesh
        self._kv_io_sharding = None     # full pool  [L, pages, page, kv, dh]
        self._kv_layer_sharding = None  # scan slice [pages, page, kv, dh]
        self._repl_sharding = None
        if mesh is not None:
            from arkflow_tpu.parallel.mesh import (dp_size, kv_pool_shardings,
                                                   replicated, tp_size,
                                                   validate_tp_heads)

            if dp_size(mesh) > 1:
                raise ConfigError(
                    "continuous serving shards tensor-parallel only — the "
                    "lockstep slot grid does not batch-split over dp (use "
                    "serving: batch or tpu_inference for dp)")
            validate_tp_heads(tp_size(mesh), cfg.kv_heads,
                              who="continuous serving")
            self._kv_io_sharding, self._kv_layer_sharding = kv_pool_shardings(mesh)
            self._repl_sharding = replicated(mesh)
        self.k_pages, self.v_pages = self._init_pools()

        # chunked prefill: prompts longer than this admit in fixed-size
        # chunks interleaved with decode steps, so one long prompt never
        # stalls every decode lane for a monolithic prefill (0 = one-shot)
        self.prefill_chunk = int(prefill_chunk)
        #: slot -> next absolute prefill offset (present while admitting)
        self._prefill_pos: dict[int, int] = {}
        self._turn_prefill = True  # alternate chunk/decode under contention

        # automatic prefix caching (vLLM-style): finished requests donate
        # their prompt's FULL pages to an LRU keyed by the token prefix;
        # later requests alias those pages (refcounted, read-only by
        # construction — decode only ever writes positions >= its prompt
        # length, and RoPE positions are absolute, so cached K/V is exact
        # for any request sharing the token prefix) and prefill only the
        # remainder through the chunk kernel. 0 = off; N = max cached pages.
        self.prefix_cache_pages = int(prefix_cache_pages)
        if self.prefix_cache_pages < 0:
            raise ConfigError("prefix_cache_pages must be >= 0")
        from collections import OrderedDict

        self._prefix_cache: "OrderedDict[tuple, list[int]]" = OrderedDict()
        #: DISTINCT pages held by cache entries (page -> entry count):
        #: nested prefixes share pages, so capacity counts physical pages
        self._cache_pages: dict[int, int] = {}
        #: token-lengths present in the cache (length -> entry count), so
        #: lookup probes only stored lengths instead of every page multiple
        self._prefix_lengths: dict[int, int] = {}

        # host-side state
        self._free_pages: list[int] = list(range(1, self.num_pages))
        self._page_refs: dict[int, int] = {}
        self._slot_req: list[Optional[_Request]] = [None] * slots
        self._slot_pages: list[list[int]] = [[] for _ in range(slots)]
        self._lengths = np.zeros(slots, np.int32)
        self._cur_tokens = np.zeros(slots, np.int32)
        # plain deque: admission needs FIFO peek, which asyncio.Queue only
        # offers via private internals
        self._pending: deque[_Request] = deque()
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False
        #: hot-swap drain flag (``swap_params``): admission pauses, the slot
        #: grid runs dry, then params flip + jits rebuild + pools reset —
        #: queued requests wait through the flip instead of failing
        self._draining = False

        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self._key = jax.random.PRNGKey(seed)
        # self-speculative greedy decode: draft k-1 tokens by n-gram lookup
        # over the sequence's own history, verify all k in ONE chunk call.
        # Decode steps are HBM-bandwidth-bound (weights + KV reads dominate),
        # so scoring k positions costs barely more than one — every accepted
        # draft is nearly-free throughput. Greedy only: acceptance compares
        # argmax, which preserves exact greedy outputs.
        self.speculative_tokens = int(speculative_tokens)
        if self.speculative_tokens < 0:
            raise ConfigError("speculative_tokens must be >= 0")
        if self.speculative_tokens > 0 and self.temperature != 0.0:
            raise ConfigError(
                "speculative_tokens requires greedy decoding (temperature 0); "
                "sampled acceptance is not implemented")

        # decode attention kernel: "gather" materializes each slot's context
        # from the page pools and masks (the reference path); "paged" runs
        # the Pallas kernel that reads the page table in place
        # (ops/ragged_attention.paged_flash_attention) for decode AND
        # chunked prefill. "auto" (default) picks paged on TPU backends and
        # gather elsewhere — same idiom as the runner's auto flash. Compiled
        # Pallas needs a TPU backend; CPU tests opt in via kernel_interpret.
        # The swap is gated on argmax parity against the gather reference
        # (mismatch falls back, never fails).
        self.decode_kernel = str(decode_kernel)
        if self.decode_kernel not in ("auto", "gather", "paged"):
            raise ConfigError(
                f"decode_kernel must be auto|gather|paged, got {decode_kernel!r}")
        self.kernel_interpret = bool(kernel_interpret)
        if self.decode_kernel == "auto":
            self.decode_kernel = (
                "paged" if (self._on_tpu() or self.kernel_interpret)
                else "gather")
        elif (self.decode_kernel == "paged" and not self.kernel_interpret
                and not self._on_tpu()):
            logger.warning(
                "decode_kernel: paged needs a TPU backend (or "
                "kernel_interpret for CPU tests); serving with the dense "
                "gather reference instead")
            self.decode_kernel = "gather"

        # dispatch depth: 2 pipelines decode — step N+1 is dispatched with
        # step N's DEVICE-resident next-token array before N's outputs are
        # fetched, so host bookkeeping overlaps device compute. Greedy-only:
        # the host learns about EOS one step late, so a lane that finished
        # at N still rides N+1 (its token is dropped on apply) — exact for
        # argmax decoding, but a sampled RNG stream or an MoE's shared
        # expert capacity would see the dead lane and diverge from depth-1.
        self.dispatch_depth = int(dispatch_depth)
        if self.dispatch_depth < 1:
            raise ConfigError("dispatch_depth must be >= 1")
        if self.dispatch_depth > 2:
            raise ConfigError(
                "dispatch_depth > 2 is not supported: lockstep decode can "
                "only lag host bookkeeping by one step (deeper queues would "
                "admit tokens the host has never validated)")
        if self.dispatch_depth > 1:
            if self.temperature != 0.0:
                raise ConfigError(
                    "dispatch_depth > 1 requires greedy decoding "
                    "(temperature 0): a lane that finished at step N still "
                    "rides step N+1, which would consume sampling RNG")
            if self.speculative_tokens > 0:
                raise ConfigError(
                    "dispatch_depth > 1 and speculative_tokens are mutually "
                    "exclusive (both restructure the decode loop)")
            if getattr(cfg, "num_experts", 0) > 0:
                raise ConfigError(
                    "dispatch_depth > 1 does not compose with MoE models: "
                    "a finished-but-still-riding lane consumes shared "
                    "expert capacity and changes other lanes' outputs")
        #: the one in-flight, not-yet-applied decode step (depth 2)
        self._pipeline: Optional[_InFlightDecode] = None
        #: monotonic count of pipelined dispatches — unlike ``_pipeline``
        #: (None while the previous step's fetch applies), this is a stable
        #: "did the depth-2 path engage" signal for tests/diagnostics
        self._pipelined_dispatches = 0

        #: first-seen jitted-step keys — a cold (kind, shape) compiles before
        #: it executes, so the deadline watchdog grants it the first-compile
        #: budget (cleared on rebuild, like the runner's seen-shape set)
        self._seen_steps: set[tuple] = set()
        if (self.decode_kernel == "paged" and kernel_parity_check
                and self.mesh is None):
            # one tiny golden batch through both kernels before the swap is
            # trusted (PR-6 convention: parity gates the fast path, failure
            # falls back loudly instead of serving wrong tokens). Under a
            # mesh the gate is skipped — per-shard math is identical and the
            # tp parity suite covers it; the init-time check stays local.
            if not self._paged_kernel_parity_ok():
                logger.warning(
                    "paged decode kernel failed argmax parity vs the dense "
                    "gather reference; serving with gather")
                self.decode_kernel = "gather"
        self._build_jitted()

        # the shared serving-runner core: health state machine, step-deadline
        # watchdog, chaos hooks — the generate path inherits the PR-4/5
        # hardening instead of reimplementing it
        self.core = ServingRunnerCore(
            name=f"{name}[generate]",
            labels={"model": name, "path": "generate"},
            step_deadline_s=step_deadline_s,
            step_deadline_first_s=step_deadline_first_s,
            health_config=health_config,
            rebuild_fn=self._rebuild_after_incident,
        )

        reg = global_registry()
        self.m_steps = reg.counter("arkflow_gen_decode_steps_total", "lockstep decode steps")
        self.m_tokens = reg.counter("arkflow_gen_tokens_total", "tokens generated")
        self.m_spec_drafted = reg.counter(
            "arkflow_gen_spec_drafted_total", "draft tokens offered for verification")
        self.m_spec_accepted = reg.counter(
            "arkflow_gen_spec_accepted_total", "draft tokens accepted")
        self.m_active = reg.gauge("arkflow_gen_active_slots", "busy decode slots")
        self.m_waiting = reg.gauge("arkflow_gen_waiting_requests", "admission queue depth")
        self.m_truncated = reg.counter(
            "arkflow_gen_truncated_total",
            "requests cut short by page-pool exhaustion (pool undersized)")
        self.m_prefix_hits = reg.counter(
            "arkflow_gen_prefix_cache_hits_total", "admissions that reused cached prefix pages")
        self.m_prefix_pages = reg.counter(
            "arkflow_gen_prefix_pages_shared_total", "pages aliased from the prefix cache")
        # observability satellites: the generation server used to be nearly
        # dark — these four answer "is the server keeping up" from /metrics
        self.m_slots_busy = reg.gauge(
            "arkflow_gen_slots_busy", "decode slots occupied (admitting + decoding)")
        self.m_pool_occupancy = reg.gauge(
            "arkflow_gen_page_pool_occupancy",
            "fraction of KV pages in use (scratch page excluded)")
        self.m_prefix_evictions = reg.counter(
            "arkflow_gen_prefix_cache_evictions_total",
            "prefix-cache entries evicted (LRU capacity or page pressure)")
        self.m_tps = reg.gauge(
            "arkflow_gen_tokens_per_sec",
            "windowed generation throughput (tokens/s over the serve loop)")
        # the dispatch-depth scoreboard (ROADMAP item 5): the same idle-gap
        # family the batch runner exports, labeled path=generate — depth 2
        # drives the p50 toward zero because step N+1 is already queued
        # when step N completes
        self.m_idle_gap = reg.histogram(
            "arkflow_tpu_device_idle_gap_seconds",
            "gap between step N completing and step N+1 launching "
            "(device idle between consecutive steps)",
            {"model": name, "path": "generate"})
        self.m_depth = reg.gauge(
            "arkflow_gen_dispatch_depth",
            "configured decode dispatch depth (2 = pipelined)",
            {"model": name})
        self.m_depth.set(self.dispatch_depth)
        self.m_kernel_paged = reg.gauge(
            "arkflow_gen_decode_kernel_paged",
            "1 when the paged flash-attention kernel serves decode/chunk "
            "(0 = dense gather reference)", {"model": name})
        self.m_kernel_paged.set(1 if self.decode_kernel == "paged" else 0)
        # time-to-first-token: the latency-bound regime's headline metric —
        # stamped once per request at its first decoded token (or at page
        # export on a prefill-role worker, where the first token ships with
        # the pages); adopted requests arrive already stamped upstream
        self.m_ttft = reg.histogram(
            "arkflow_gen_ttft_seconds",
            "submit-to-first-decoded-token latency per request",
            {"model": name})
        #: per-server TTFT reservoir behind health_report() percentiles
        #: (m_ttft is registry-global and would mix servers in-process)
        self._ttft_samples: deque[float] = deque(maxlen=2048)
        self._ttft_count = 0
        #: device-step in-flight count + last-all-complete stamp behind the
        #: idle-gap histogram (mirrors the runner's _track_dispatch/_complete)
        self._gen_inflight = 0
        self._gen_idle_since: Optional[float] = None
        #: tokens emitted by THIS server (m_tokens is registry-global)
        self._tokens_emitted = 0
        self._rate_window: Optional[tuple[float, int]] = None

    # -- device plumbing (jit build / sharding / reset) --------------------

    def _on_tpu(self) -> bool:
        """Backend check for the compiled Pallas path (the probe shared
        with the runner's auto-flash resolution)."""
        from arkflow_tpu.tpu.serving_core import on_tpu_backend

        devs = (list(self.mesh.devices.flat) if self.mesh is not None
                else None)
        return on_tpu_backend(devs)

    def _paged_kernel_parity_ok(self) -> bool:
        """Argmax-parity gate for the paged attention kernel: one tiny
        golden batch — prompts that cross a page boundary plus a
        single-token tail, on non-contiguous page tables — through prefill,
        then one decode step and one 2-token chunk with BOTH kernels. The
        fast path only serves if every argmax agrees with the dense-gather
        reference (PR-6 convention: parity gates the measured default).
        Runs eagerly on this server's params; one-time init cost."""
        from arkflow_tpu.models.paged_decode import paged_prefill_chunk

        page = self.page_size
        n0 = min(page + 1, self.max_seq)  # crosses a page boundary
        pages_per = -(-(n0 + 3) // page)  # room for prompt + decode + chunk
        kp, vp = init_page_pool(self.cfg, 1 + 2 * pages_per, page)
        rng = np.random.RandomState(1234)
        ids = np.zeros((2, n0), np.int32)
        ids[0] = rng.randint(1, self.cfg.vocab_size, n0)
        ids[1, 0] = rng.randint(1, self.cfg.vocab_size)
        lens = jnp.asarray([n0, 1], jnp.int32)
        table = np.zeros((2, pages_per), np.int32)
        table[0] = np.arange(1, 2 * pages_per, 2)[::-1]  # non-contiguous
        table[1] = np.arange(2, 2 * pages_per + 1, 2)
        table = jnp.asarray(table)
        _, kp, vp = paged_prefill(
            self.params, self.cfg, jnp.asarray(ids), lens, table, kp, vp)
        tok = jnp.asarray(ids[:, 0])
        act = jnp.asarray([True, True])
        ref, *_ = paged_decode_step(
            self.params, self.cfg, tok, lens, act, table, kp, vp,
            return_logits=True)
        got, *_ = paged_decode_step(
            self.params, self.cfg, tok, lens, act, table, kp, vp,
            return_logits=True, attention_kernel="paged",
            kernel_interpret=self.kernel_interpret)
        if not bool((jnp.argmax(ref, -1) == jnp.argmax(got, -1)).all()):
            return False
        cids = jnp.asarray(rng.randint(1, self.cfg.vocab_size, (2, 2)),
                           jnp.int32)
        clen = jnp.asarray([2, 2], jnp.int32)
        ref, *_ = paged_prefill_chunk(
            self.params, self.cfg, cids, lens, clen, table, kp, vp,
            return_all=True)
        got, *_ = paged_prefill_chunk(
            self.params, self.cfg, cids, lens, clen, table, kp, vp,
            return_all=True, attention_kernel="paged",
            kernel_interpret=self.kernel_interpret)
        return bool((jnp.argmax(ref, -1) == jnp.argmax(got, -1)).all())

    def _init_pools(self):
        """Fresh KV page pools, placed with their tensor-parallel sharding
        under a mesh (KV heads over ``tp``; replicated otherwise)."""
        kp, vp = init_page_pool(self.cfg, self.num_pages, self.page_size)
        if self._kv_io_sharding is not None:
            kp = jax.device_put(kp, self._kv_io_sharding)
            vp = jax.device_put(vp, self._kv_io_sharding)
        return kp, vp

    def _build_jitted(self) -> None:
        """(Re)build the four jitted steps. Under a mesh every step carries
        explicit in/out shardings: the KV pools split over KV heads on
        ``tp``, everything else (token ids, lengths, page tables, keys) is
        replicated — page-table gathers stay static-shaped, so the layer
        scan lowers to plain GSPMD collectives with no dynamic shapes."""
        from arkflow_tpu.models.decoder import select_token
        from arkflow_tpu.models.paged_decode import paged_prefill_chunk

        cfg = self.cfg
        kv_layer = self._kv_layer_sharding
        kern = dict(attention_kernel=self.decode_kernel,
                    kernel_interpret=self.kernel_interpret)

        def _pick(logits, key):
            return select_token(logits, key, self.temperature, self.top_k)

        # donate the KV pools: they are pure in->out state, so XLA updates
        # them in place instead of copying hundreds of MB per decode step
        def _decode(tok, lens, act, table, kp, vp, key):
            logits, kp, vp = paged_decode_step(
                self.params, cfg, tok, lens, act, table, kp, vp,
                return_logits=True, kv_sharding=kv_layer, **kern)
            return _pick(logits, key), kp, vp

        def _prefill(ids, lens, table, kp, vp, key):
            logits, kp, vp = paged_prefill(
                self.params, cfg, ids, lens, table, kp, vp, return_logits=True,
                kv_sharding=kv_layer)
            return _pick(logits, key), kp, vp

        def _chunk(ids, off, clen, table, kp, vp):
            return paged_prefill_chunk(self.params, cfg, ids, off, clen,
                                       table, kp, vp, kv_sharding=kv_layer,
                                       **kern)

        def _verify(ids, off, clen, table, kp, vp):
            return paged_prefill_chunk(self.params, cfg, ids, off, clen,
                                       table, kp, vp, return_all=True,
                                       kv_sharding=kv_layer, **kern)

        if self.mesh is None:
            self._decode = jax.jit(_decode, donate_argnums=(4, 5))
            self._prefill = jax.jit(_prefill, donate_argnums=(3, 4))
            self._chunk = jax.jit(_chunk, donate_argnums=(4, 5))
            self._verify = jax.jit(_verify, donate_argnums=(4, 5))
            return
        r, kv = self._repl_sharding, self._kv_io_sharding
        self._decode = jax.jit(_decode, donate_argnums=(4, 5),
                               in_shardings=(r, r, r, r, kv, kv, r),
                               out_shardings=(r, kv, kv))
        self._prefill = jax.jit(_prefill, donate_argnums=(3, 4),
                                in_shardings=(r, r, r, kv, kv, r),
                                out_shardings=(r, kv, kv))
        self._chunk = jax.jit(_chunk, donate_argnums=(4, 5),
                              in_shardings=(r, r, r, r, kv, kv),
                              out_shardings=(r, kv, kv))
        self._verify = jax.jit(_verify, donate_argnums=(4, 5),
                               in_shardings=(r, r, r, r, kv, kv),
                               out_shardings=(r, kv, kv))

    def _rebuild_after_incident(self) -> None:
        """Core rebuild hook (runs inside the heal gate, before the recovery
        probe): executables cached across a hung step are not trusted —
        recompile everything from scratch under the first-compile budget."""
        self._seen_steps.clear()
        self._build_jitted()
        logger.warning("generation server rebuilt its jitted steps after a "
                       "deadline miss")

    def _reset_device_state(self) -> None:
        """Fresh pools + host page accounting after a crashed/abandoned step:
        a zombie step still owns the donated pool buffers, and the prefix
        cache's KV content died with them. Every future admission starts
        from a clean pool (leaked refs would wedge admission forever)."""
        self._pipeline = None  # a zombie step's tokens are never applied
        self._gen_inflight = 0
        self._prefix_cache.clear()
        self._cache_pages.clear()
        self._prefix_lengths.clear()
        self._page_refs.clear()
        self._free_pages = list(range(1, self.num_pages))
        self.k_pages, self.v_pages = self._init_pools()

    # -- live hot-swap surface (tpu/swap.py) --------------------------------

    async def swap_params(self, placed, drain_timeout_s: float = 30.0):
        """Adopt a new (pre-placed) param tree with zero dropped requests.

        Unlike the batch runner — whose params ride the jitted step as an
        argument — the four generation jits close over ``self.params`` as
        traced constants, so a flip must rebuild them. The sequence: pause
        admission, let the lockstep slot grid run dry (queued requests WAIT,
        they are never failed), flip params, rebuild the jits (the cleared
        ``_seen_steps`` grants the next step the first-compile budget), and
        reset the page pools + prefix cache — cached KV against new weights
        is a silent correctness bug. Returns the prior tree (the rollback
        token); raises ``SwapError`` (old params untouched, still serving)
        when the grid does not drain within ``drain_timeout_s``.
        """
        from arkflow_tpu.errors import SwapError

        self._draining = True
        try:
            deadline = time.monotonic() + drain_timeout_s
            while any(r is not None for r in self._slot_req):
                if time.monotonic() >= deadline:
                    raise SwapError(
                        f"slot grid did not drain within {drain_timeout_s:.3g}s "
                        f"({sum(1 for r in self._slot_req if r is not None)} "
                        "slots still busy); old params still serving")
                await asyncio.sleep(0.01)
            old, self.params = self.params, placed
            self._seen_steps.clear()
            self._build_jitted()
            self._reset_device_state()
            return old
        finally:
            self._draining = False

    # -- self-healing surface (fault plugin / engine /health) ---------------

    def inject_step_fault(self, kind: str, duration_s: float = 0.0) -> None:
        """Arm a one-shot ``hang``/``oom`` on the next device step (the fault
        plugin's processor wrapper drives this, same as for ModelRunner).
        ``bitflip`` corrupts a param leaf in place — the generation-tier SDC
        vector. ``sdc`` is rejected: decode picks tokens ON DEVICE (the
        logits never reach the host), so post-fetch output negation cannot
        model corruption honestly here; use ``bitflip`` instead."""
        if kind == "sdc":
            raise ConfigError(
                "chaos: 'sdc' is not supported on the generation server — "
                "decode argmax/sampling happens on device, so host-side "
                "output corruption would be a lie; arm 'bitflip' instead")
        if kind == "bitflip":
            self._bitflip_params()
            return
        self.core.inject_step_fault(kind, duration_s)

    def _bitflip_params(self) -> None:
        """Corrupt the largest float leaf of ``self.params`` in place. The
        generation jits close over params as traced constants, so the flip
        must also rebuild them (same sequence as ``swap_params``, minus the
        drain — arming and the serve loop share the event loop, and a
        corrupted tree mid-decode is exactly what real HBM corruption does).
        Nothing on the serving path notices by itself; only the integrity
        monitor's golden probe / digest verify can catch it."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(self.params)
        best: Optional[int] = None
        for i, (_, leaf) in enumerate(flat):
            dt = getattr(leaf, "dtype", None)
            if (dt is not None and jnp.issubdtype(dt, jnp.floating)
                    and getattr(leaf, "size", 0)
                    and (best is None or leaf.size > flat[best][1].size)):
                best = i
        if best is None:
            raise ConfigError(
                "bitflip: model has no float param leaf to corrupt")
        path, leaf = flat[best]
        host = np.asarray(jax.device_get(leaf))
        garbled = (np.asarray(host, np.float32) * -1000.0 + 3.7).astype(
            host.dtype)
        placed = jax.device_put(garbled, leaf.sharding)
        leaves = [l for _, l in flat]
        leaves[best] = placed
        self.params = jax.tree_util.tree_unflatten(treedef, leaves)
        self._seen_steps.clear()
        self._build_jitted()
        logger.warning("chaos: bitflip corrupted generation param leaf %s",
                       jax.tree_util.keystr(path))

    def health_report(self) -> dict:
        """JSON-able snapshot for the engine's ``/health``: health state +
        the serving detail that says whether the server is keeping up."""
        rep = self.core.health_report()
        rep["serving"] = "continuous"
        rep["decode_kernel"] = self.decode_kernel
        rep["dispatch_depth"] = self.dispatch_depth
        rep["draining"] = self._draining
        rep["slots"] = self.slots
        rep["slots_busy"] = sum(1 for r in self._slot_req if r is not None)
        total = self.num_pages - 1
        rep["page_pool_occupancy"] = (
            round((total - len(self._free_pages)) / total, 4) if total else 0.0)
        rep["prefix_cache"] = {
            "entries": len(self._prefix_cache),
            "pages": self._cache_held,
            "capacity_pages": self.prefix_cache_pages,
        }
        rep["tokens_per_sec"] = round(float(self.m_tps.value), 1)
        if self._ttft_count:
            ordered = sorted(self._ttft_samples)

            def _pct(q: float) -> float:
                i = min(len(ordered) - 1, int(q * len(ordered)))
                return round(ordered[i] * 1000.0, 3)

            rep["ttft"] = {"count": self._ttft_count,
                           "p50_ms": _pct(0.50), "p99_ms": _pct(0.99)}
        if self.mesh is not None:
            from arkflow_tpu.parallel.mesh import tp_size

            rep["mesh"] = {"tp": tp_size(self.mesh)}
        return rep

    # -- gated device step --------------------------------------------------

    def _note_step(self, key: tuple) -> bool:
        """True when this (kind, shape) jitted step has not run yet — it will
        compile, so the watchdog grants the first-compile budget."""
        if key in self._seen_steps:
            return False
        self._seen_steps.add(key)
        return True

    def _track_gen_dispatch(self) -> None:
        """Device-idle-gap bookkeeping at step launch: an open idle window
        (no step in flight, or a drained device queue detected by the
        pipelined path via ``is_ready`` — see ``_step_pipelined``) closes
        here and records its gap."""
        if self._gen_idle_since is not None:
            self.m_idle_gap.observe(time.monotonic() - self._gen_idle_since)
            self._gen_idle_since = None
        self._gen_inflight += 1

    def _track_gen_complete(self) -> None:
        self._gen_inflight = max(0, self._gen_inflight - 1)
        # keep the EARLIER start when the drained-queue check already
        # opened the window (the device has been idle since then)
        if self._gen_inflight == 0 and self._gen_idle_since is None:
            self._gen_idle_since = time.monotonic()

    async def _run_device_step(self, key: tuple, fn):
        """One health-gated jitted call: the same admission gate pool
        dispatch uses, a first-compile-aware deadline watchdog, and the
        chaos hook. A deadline miss marks the server UNHEALTHY, schedules a
        rebuild, and raises — the serve loop fails every in-flight request,
        so their batches nack for redelivery; the next step waits out the
        probe backoff and runs as the recovery probe."""
        core = self.core
        await core.heal_gate()
        deadline = core.deadline_for(self._note_step(key))

        def blocking():
            core.apply_chaos()
            return jax.block_until_ready(fn())

        self._track_gen_dispatch()
        try:
            if deadline is None:
                out = await asyncio.get_running_loop().run_in_executor(
                    None, blocking)
            else:
                out = await core.run_deadlined(blocking, deadline)
        except StepDeadlineExceeded:
            raise  # the core already marked UNHEALTHY + scheduled rebuild
        except Exception as e:
            core.health.mark_unhealthy(f"generate step failed: {e}")
            raise
        finally:
            # an abandoned step counts complete: the device stopped doing
            # useful work, and the reset path rebuilds from fresh pools
            self._track_gen_complete()
        core.health.mark_success()
        return out

    # -- public API --------------------------------------------------------

    async def generate(self, prompt_ids: list[int],
                       max_new_tokens: int = 64) -> list[int]:
        """Submit one request; resolves with generated token ids (no EOS)."""
        if self._closed:
            raise ConfigError("generation server is closed")
        if len(prompt_ids) == 0:
            return []
        if len(prompt_ids) + max_new_tokens > self.max_seq:
            raise ConfigError(
                f"prompt({len(prompt_ids)}) + max_new({max_new_tokens}) exceeds "
                f"max_seq={self.max_seq}")
        req = _Request(list(prompt_ids), max_new_tokens,
                       asyncio.get_running_loop().create_future(),
                       submitted_at=time.monotonic())
        self._pending.append(req)
        self.m_waiting.set(len(self._pending))
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._serve_loop())
        return await req.future

    async def prefill_export(self, prompt_ids: list[int],
                             max_new_tokens: int = 64) -> dict:
        """Disaggregated prefill: run (chunked) prefill for one prompt, then
        stop and resolve with a KV-page export instead of decoding — the
        prefill half of a prefill/decode role split.

        The export carries the prompt's KV pages as host numpy slabs, split
        one-per-tp-shard along the kv_heads axis so a host-mesh receiver can
        frame each shard separately, plus the first decoded token (prefill
        produces it for free). When generation is already complete at the
        first token (EOS, or ``max_new_tokens <= 1``) the export is marked
        ``done`` and ships no pages. Pages are unreffed (and donated to the
        prefix cache) locally once exported — the scratch pool recycles.
        """
        if self._closed:
            raise ConfigError("generation server is closed")
        if len(prompt_ids) == 0:
            return {"done": True, "tokens": [], "prompt": [],
                    "max_new_tokens": int(max_new_tokens)}
        if len(prompt_ids) + max_new_tokens > self.max_seq:
            raise ConfigError(
                f"prompt({len(prompt_ids)}) + max_new({max_new_tokens}) exceeds "
                f"max_seq={self.max_seq}")
        req = _Request(list(prompt_ids), max_new_tokens,
                       asyncio.get_running_loop().create_future(),
                       submitted_at=time.monotonic(), prefill_only=True)
        self._pending.append(req)
        self.m_waiting.set(len(self._pending))
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._serve_loop())
        return await req.future

    async def generate_from_pages(self, export: Mapping) -> list[int]:
        """Disaggregated decode: adopt a KV-page export produced by a
        prefill worker's :meth:`prefill_export` and decode to completion.

        Fresh pages are reserved from this server's pool and the slabs are
        uploaded through the same ``.at[pages].set`` path prefill writes
        through (re-sharded to the pool's kv io sharding under a mesh), so
        the paged kernel decodes from them with no relayout — the page
        table it is handed just points at the adopted pages. Returns the
        full token list including the shipped first token, exactly what
        :meth:`generate` would have returned locally."""
        if self._closed:
            raise ConfigError("generation server is closed")
        if export.get("done"):
            return [int(t) for t in export.get("tokens") or []]
        prompt = [int(t) for t in export["prompt"]]
        max_new = int(export["max_new_tokens"])
        if not prompt:
            return []
        if len(prompt) + max_new > self.max_seq:
            raise ConfigError(
                f"adopted prompt({len(prompt)}) + max_new({max_new}) exceeds "
                f"max_seq={self.max_seq}")
        if int(export["page_size"]) != self.page_size:
            raise ConfigError(
                f"adopted pages have page_size={export['page_size']}, "
                f"pool uses {self.page_size} (geometry must match end to end)")
        k_shards = export["k"]
        slab_shape = tuple(k_shards[0].shape)
        pool_shape = tuple(self.k_pages.shape)
        kv_total = sum(int(s.shape[3]) for s in k_shards)
        expect = (pool_shape[0], self._pages_needed(len(prompt)),
                  pool_shape[2], pool_shape[3], pool_shape[4])
        if (slab_shape[0], slab_shape[1], slab_shape[2], kv_total,
                slab_shape[4]) != expect:
            raise ConfigError(
                f"adopted page slabs {slab_shape} x{len(k_shards)} shards do "
                f"not match pool geometry {pool_shape} for a "
                f"{len(prompt)}-token prompt")
        first = int(export["first_token"])
        req = _Request(prompt, max_new,
                       asyncio.get_running_loop().create_future(),
                       tokens=[first], submitted_at=time.monotonic(),
                       ttft_stamped=True, adopt=dict(export))
        if first == self.eos_id or max_new <= 1:
            # complete at the first token: nothing to decode, don't touch
            # the pool (mirrors _handle_token's EOS/budget handling)
            return [] if first == self.eos_id else [first]
        self._pending.append(req)
        self.m_waiting.set(len(self._pending))
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._serve_loop())
        return await req.future

    async def close(self) -> None:
        self._closed = True
        if self._loop_task is not None:
            await self._loop_task

    # -- page accounting ---------------------------------------------------

    def _pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def _alloc_page(self) -> Optional[int]:
        """One fresh page (ref=1); evicts LRU prefix entries under pressure."""
        while not self._free_pages:
            if not self._evict_one():
                return None
        p = self._free_pages.pop()
        self._page_refs[p] = 1
        return p

    def _ref_page(self, p: int) -> None:
        self._page_refs[p] += 1

    def _unref_page(self, p: int) -> None:
        self._page_refs[p] -= 1
        if self._page_refs[p] == 0:
            del self._page_refs[p]
            self._free_pages.append(p)

    @property
    def _cache_held(self) -> int:
        """Physical pages currently held by the prefix cache."""
        return len(self._cache_pages)

    def _evict_one(self) -> bool:
        if not self._prefix_cache:
            return False
        self.m_prefix_evictions.inc()
        key, pages = self._prefix_cache.popitem(last=False)  # LRU
        self._prefix_lengths[len(key)] -= 1
        if self._prefix_lengths[len(key)] == 0:
            del self._prefix_lengths[len(key)]
        for p in pages:
            self._cache_pages[p] -= 1
            if self._cache_pages[p] == 0:
                del self._cache_pages[p]
            self._unref_page(p)
        return True

    def _lookup_prefix(self, prompt: list[int]) -> Optional[tuple]:
        """Key of the longest cached full-page prefix (no side effects).
        At least one prompt token is always left to prefill (the last
        position's logits seed generation)."""
        if not self._prefix_cache:
            return None
        limit = ((len(prompt) - 1) // self.page_size) * self.page_size
        for length in sorted(self._prefix_lengths, reverse=True):
            if length > limit:
                continue
            key = tuple(prompt[:length])
            if key in self._prefix_cache:
                return key
        return None

    def _cache_prefix(self, req: _Request, pages: list[int]) -> None:
        """Donate the prompt's full pages to the cache (called at finish,
        before the slot's refs drop)."""
        if not self.prefix_cache_pages:
            return
        count = min(len(req.prompt) // self.page_size, len(pages))
        if count == 0:
            return
        key = tuple(req.prompt[:count * self.page_size])
        if key in self._prefix_cache:
            self._prefix_cache.move_to_end(key)
            return
        held = pages[:count]
        for p in held:
            self._ref_page(p)
            self._cache_pages[p] = self._cache_pages.get(p, 0) + 1
        self._prefix_cache[key] = list(held)
        self._prefix_lengths[len(key)] = self._prefix_lengths.get(len(key), 0) + 1
        while self._cache_held > self.prefix_cache_pages:
            if not self._evict_one():
                break

    def _evictable_pages(self, keep: Optional[tuple]) -> int:
        """DISTINCT pages the cache could free by evicting every entry
        other than ``keep``: pages whose refs all come from those entries
        (nested prefixes share pages — count physical pages once)."""
        keep_pages = set(self._prefix_cache.get(keep, ())) if keep is not None else set()
        counts: dict[int, int] = {}
        for key, pages in self._prefix_cache.items():
            if key == keep:
                continue
            for p in pages:
                counts[p] = counts.get(p, 0) + 1
        return sum(1 for p, c in counts.items()
                   if p not in keep_pages and self._page_refs.get(p) == c)

    def _try_reserve(self, req: _Request) -> Optional[tuple[list[int], int]]:
        """Reserve every page the request needs: aliased prefix pages plus
        fresh ones. Infeasible reservations return None WITHOUT side
        effects (no cache eviction, no metric counts) — a head-of-line
        stall must not wipe the cache's future savings."""
        n = len(req.prompt)
        # adopted page sets upload the FULL prompt KV: aliasing cached
        # prefix pages would scatter the upload into shared pages — fresh
        # pages only (the finished request still donates to the cache)
        key = None if req.adopt is not None else self._lookup_prefix(req.prompt)
        shared = list(self._prefix_cache[key]) if key is not None else []
        fresh_needed = self._pages_needed(n + 1) - len(shared)
        if len(self._free_pages) + self._evictable_pages(key) < fresh_needed:
            return None
        if key is not None:
            self._prefix_cache.move_to_end(key)
            for p in shared:
                self._ref_page(p)
        pages = list(shared)
        for _ in range(fresh_needed):
            p = self._alloc_page()
            if p is None:  # shouldn't happen after the feasibility check
                for q in pages:
                    self._unref_page(q)
                return None
            pages.append(p)
        return pages, len(shared) * self.page_size

    # -- scheduler ---------------------------------------------------------

    def _table_array(self) -> jnp.ndarray:
        table = np.zeros((self.slots, self.pages_per_slot), np.int32)
        for s, pages in enumerate(self._slot_pages):
            table[s, :len(pages)] = pages
        return jnp.asarray(table)

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.prompt_buckets[-1]

    async def _admit_one(self, slot: int, req: _Request,
                         pages: list[int], shared_len: int) -> None:
        """Seed the slot with its reserved pages and start prefill."""
        # register FIRST: if anything below throws, the loop's crash handler
        # fails this future instead of leaving its caller hanging
        self._slot_req[slot] = req
        n = len(req.prompt)
        self._slot_pages[slot] = pages
        if req.adopt is not None:
            await self._admit_adopted(slot, req)
            return
        if shared_len > 0:
            self.m_prefix_hits.inc()
            self.m_prefix_pages.inc(shared_len // self.page_size)
        if shared_len > 0 or (self.prefill_chunk and n > self.prefill_chunk):
            # cooperative admission: the serve loop interleaves prefill
            # steps with decode; the slot joins decode once fully prefilled.
            # A cached prefix starts prefill at its boundary — only the
            # remainder is ever computed.
            self._prefill_pos[slot] = shared_len
            return
        bucket = self._bucket(n)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = req.prompt
        # single-row table padded to the slot width
        table = np.zeros((1, self.pages_per_slot), np.int32)
        table[0, :len(pages)] = pages
        self._key, sub = jax.random.split(self._key)
        # off-loop + gated: first call per bucket compiles (seconds on TPU)
        # pools bound EAGERLY: a deadline-abandoned zombie step waking after
        # a pool reset must consume the pools it already owned, never the
        # fresh ones. The jitted fn resolves LAZILY at call time: the heal
        # gate's rebuild runs before the probe step executes, and the probe
        # must use the rebuilt executable, not the distrusted cached one.
        # (Same for the other three step kinds below.)
        nxt, self.k_pages, self.v_pages = await self._run_device_step(
            ("prefill", bucket),
            lambda kp=self.k_pages, vp=self.v_pages: self._prefill(
                jnp.asarray(ids), jnp.asarray([n], jnp.int32), jnp.asarray(table),
                kp, vp, sub))
        self._lengths[slot] = n
        self._cur_tokens[slot] = int(nxt[0])
        if req.prefill_only:
            await self._export_and_finish(slot)
            return
        self._handle_token(slot, int(nxt[0]))

    async def _admit_adopted(self, slot: int, req: _Request) -> None:
        """Seed the slot from a received KV-page export: upload the slabs
        into this pool's reserved pages and join decode directly — no
        prefill compute. The first token rode in with the pages."""
        exp = req.adopt
        n = len(req.prompt)
        pages = self._slot_pages[slot]
        idx = np.asarray(pages[: self._pages_needed(n)], np.int32)
        k_slab = np.concatenate([np.asarray(s) for s in exp["k"]], axis=3)
        v_slab = np.concatenate([np.asarray(s) for s in exp["v"]], axis=3)

        def upload(kp=self.k_pages, vp=self.v_pages):
            k = jnp.asarray(k_slab).astype(kp.dtype)
            v = jnp.asarray(v_slab).astype(vp.dtype)
            kp = kp.at[:, jnp.asarray(idx)].set(k)
            vp = vp.at[:, jnp.asarray(idx)].set(v)
            if self._kv_io_sharding is not None:
                kp = jax.device_put(kp, self._kv_io_sharding)
                vp = jax.device_put(vp, self._kv_io_sharding)
            return jax.block_until_ready(kp), jax.block_until_ready(vp)

        self.k_pages, self.v_pages = (
            await asyncio.get_running_loop().run_in_executor(None, upload))
        # drop the heavy slabs now that they're on device
        req.adopt = None
        self._lengths[slot] = n
        self._cur_tokens[slot] = int(exp["first_token"])
        # the first token is pre-seeded in req.tokens (counted on the
        # prefill side); the slot decodes from position n next step

    def _stamp_ttft(self, req: _Request) -> None:
        """First decoded token for this request: record TTFT exactly once
        (EOS-as-first-token still counts — the model answered)."""
        if req.ttft_stamped or req.submitted_at <= 0.0:
            return
        req.ttft_stamped = True
        dt = time.monotonic() - req.submitted_at
        self.m_ttft.observe(dt)
        self._ttft_samples.append(dt)
        self._ttft_count += 1

    def _handle_token(self, slot: int, token: int) -> None:
        """Record one generated token; completes the request on EOS/limit."""
        req = self._slot_req[slot]
        if req is None:
            return
        self._stamp_ttft(req)
        if token == self.eos_id:
            self._finish(slot)
            return
        req.tokens.append(token)
        self.m_tokens.inc()
        self._tokens_emitted += 1
        if len(req.tokens) >= req.max_new_tokens:
            self._finish(slot)

    def _finish(self, slot: int) -> None:
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        fully_prefilled = slot not in self._prefill_pos
        self._prefill_pos.pop(slot, None)
        if req is not None and fully_prefilled:
            # donate the prompt's full pages before the slot's refs drop
            self._cache_prefix(req, self._slot_pages[slot])
        for p in self._slot_pages[slot]:
            self._unref_page(p)
        self._slot_pages[slot] = []
        self._lengths[slot] = 0
        self._cur_tokens[slot] = 0
        if req is not None and not req.future.done():
            req.future.set_result(
                req.tokens if req.export is None else req.export)

    async def _prefill_one_chunk(self, slot: int) -> None:
        """One fixed-size prefill chunk for an admitting slot (one device
        call); seeds the slot for decode after the final chunk."""
        req = self._slot_req[slot]
        if req is None:
            self._prefill_pos.pop(slot, None)
            return
        off = self._prefill_pos[slot]
        n = len(req.prompt)
        # chunk width: the configured chunk size, or (prefix-cache remainder
        # with chunking off) one bucketed span covering the rest
        c = self.prefill_chunk if self.prefill_chunk else self._bucket(n - off)
        chunk = req.prompt[off:off + c]
        ids = np.zeros((1, c), np.int32)
        ids[0, :len(chunk)] = chunk
        table = np.zeros((1, self.pages_per_slot), np.int32)
        table[0, :len(self._slot_pages[slot])] = self._slot_pages[slot]
        logits, self.k_pages, self.v_pages = await self._run_device_step(
            ("chunk", c),
            lambda kp=self.k_pages, vp=self.v_pages: self._chunk(
                jnp.asarray(ids), jnp.asarray([off], jnp.int32),
                jnp.asarray([len(chunk)], jnp.int32), jnp.asarray(table),
                kp, vp))
        new_off = off + len(chunk)
        if new_off < n:
            self._prefill_pos[slot] = new_off
            return
        # final chunk: sample the first generated token and join decode
        del self._prefill_pos[slot]
        from arkflow_tpu.models.decoder import select_token

        self._key, sub = jax.random.split(self._key)
        nxt = select_token(logits, sub, self.temperature, self.top_k)
        self._lengths[slot] = n
        self._cur_tokens[slot] = int(nxt[0])
        if req.prefill_only:
            await self._export_and_finish(slot)
            return
        self._handle_token(slot, int(nxt[0]))

    async def _export_and_finish(self, slot: int) -> None:
        """Prefill-only completion: fetch the prompt's KV pages to host,
        attach the export to the request, and finish the slot (which still
        donates the prompt pages to the prefix cache — repeat prefixes on
        this prefill worker skip their shared span like any local request).

        Only the pages covering prompt positions ``0..n-1`` ship: the page
        holding position ``n`` (where the first decode step writes) may be
        prefix-shared or unwritten, and the receiver allocates it fresh."""
        req = self._slot_req[slot]
        if req is None:
            return
        n = len(req.prompt)
        first = int(self._cur_tokens[slot])
        self._stamp_ttft(req)
        done = first == self.eos_id or req.max_new_tokens <= 1
        if not done:
            req.tokens.append(first)
            self.m_tokens.inc()
            self._tokens_emitted += 1
            pages = self._slot_pages[slot][: self._pages_needed(n)]
            idx = jnp.asarray(np.asarray(pages, np.int32))
            shards = 1
            if self.mesh is not None:
                from arkflow_tpu.parallel.mesh import tp_size

                shards = tp_size(self.mesh)

            def fetch(kp=self.k_pages, vp=self.v_pages):
                return (np.asarray(jax.device_get(kp[:, idx])),
                        np.asarray(jax.device_get(vp[:, idx])))

            k_slab, v_slab = (
                await asyncio.get_running_loop().run_in_executor(None, fetch))
            req.export = {
                "prompt": list(req.prompt),
                "max_new_tokens": int(req.max_new_tokens),
                "first_token": first,
                "page_size": int(self.page_size),
                "shards": int(shards),
                "dtype": str(k_slab.dtype),
                "tokens": [first],
                # shard-per-frame along kv_heads (axis 3): each entry is
                # exactly one tp shard's slab, framed separately on the wire
                "k": np.split(k_slab, shards, axis=3),
                "v": np.split(v_slab, shards, axis=3),
            }
        else:
            req.export = {
                "done": True,
                "prompt": list(req.prompt),
                "max_new_tokens": int(req.max_new_tokens),
                "first_token": first,
                "tokens": [] if first == self.eos_id else [first],
            }
            if first != self.eos_id:
                self.m_tokens.inc()
                self._tokens_emitted += 1
        self._finish(slot)

    def _ensure_page_capacity(self, slot: int, total: Optional[int] = None) -> bool:
        """Grow the slot's page list to cover positions < ``total``
        (default: the next write position, lengths+1)."""
        if total is None:
            total = int(self._lengths[slot]) + 1
        need = self._pages_needed(total)
        while len(self._slot_pages[slot]) < need:
            p = self._alloc_page()
            if p is None:
                return False
            self._slot_pages[slot].append(p)
        return True

    def _reserve_or_truncate(self, s: int, act: np.ndarray) -> None:
        """Ensure slot ``s`` can write its next position; when the pool is
        dry, finish the longest active sequence (its tokens so far are its
        result) and RETRY, so the starved slot never scatters into the
        scratch page and silently corrupts its context."""
        while act[s] and not self._ensure_page_capacity(s):
            candidates = [i for i in range(self.slots)
                          if act[i] and self._slot_req[i] is not None]
            if not candidates:
                break
            longest = max(candidates, key=lambda i: int(self._lengths[i]))
            req = self._slot_req[longest]
            logger.warning(
                "page pool exhausted: truncating slot %d at %d tokens "
                "(%d/%d generated) — size num_pages for the workload",
                longest, int(self._lengths[longest]),
                len(req.tokens) if req else 0,
                req.max_new_tokens if req else 0)
            self.m_truncated.inc()
            self._finish(longest)
            act[longest] = False

    def _update_gauges(self, busy: int) -> None:
        self.m_active.set(busy)
        self.m_slots_busy.set(busy)
        self.m_waiting.set(len(self._pending))
        total = self.num_pages - 1
        if total:
            self.m_pool_occupancy.set((total - len(self._free_pages)) / total)
        # windowed tokens/sec: cheap enough to refresh every loop pass
        now = time.monotonic()
        if self._rate_window is None:
            self._rate_window = (now, self._tokens_emitted)
            return
        t0, tok0 = self._rate_window
        if now - t0 >= 0.25:
            self.m_tps.set((self._tokens_emitted - tok0) / (now - t0))
            self._rate_window = (now, self._tokens_emitted)

    async def _serve_loop(self) -> None:
        try:
            while not self._closed:
                admitted = await self._admit_pending()
                prefilling = [s for s in range(self.slots)
                              if s in self._prefill_pos and self._slot_req[s]]
                active = [s for s in range(self.slots)
                          if self._slot_req[s] and s not in self._prefill_pos]
                self._update_gauges(len(active) + len(prefilling))
                if not active and not prefilling:
                    # a pipelined successor can outlive its lanes (every
                    # request EOS'd on the step that was applied AFTER it
                    # was dispatched): apply it before idling or exiting,
                    # or its step would leak in-flight accounting and only
                    # be fetched by some future wave's admission drain
                    await self._drain_pipeline()
                    if not self._pending:
                        return  # drained; next generate() restarts the loop
                    if not admitted:
                        await asyncio.sleep(0.01)  # waiting on pages
                    continue
                # interleave under contention: alternate one prefill chunk
                # with one decode step so neither starves the other
                if prefilling and (not active or self._turn_prefill):
                    self._turn_prefill = False
                    await self._drain_pipeline()
                    await self._prefill_one_chunk(prefilling[0])
                    continue
                self._turn_prefill = True
                if self.speculative_tokens > 0:
                    await self._step_speculative(active)
                else:
                    await self._step(active)
            # closed with work in flight: fail it rather than hang awaiters
            self._fail_all(ConfigError("generation server closed"))
        except Exception as e:  # fail all in-flight requests, don't hang them
            logger.exception("generation serve loop failed")
            self._fail_all(e)
            # a crashed/abandoned step leaves the pools untrustworthy (a
            # deadline-missed zombie still owns the donated buffers): start
            # the next admission from fresh pools and a clean page ledger
            self._reset_device_state()

    def _fail_all(self, err: Exception) -> None:
        # both in-flight pipelined steps (the un-applied one and any just
        # dispatched successor) die with their requests: their tokens are
        # never applied, and the reset below rebuilds from fresh pools
        self._pipeline = None
        self._gen_inflight = 0
        self._prefill_pos.clear()
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is not None and not req.future.done():
                req.future.set_exception(err)
            self._slot_req[s] = None
            # return the slot's pages: a crash must not shrink the pool
            # (leaked refs would eventually wedge every future admission)
            for p in self._slot_pages[s]:
                self._unref_page(p)
            self._slot_pages[s] = []
            self._lengths[s] = 0
            self._cur_tokens[s] = 0
        while self._pending:
            req = self._pending.popleft()
            if not req.future.done():
                req.future.set_exception(err)

    async def _admit_pending(self) -> bool:
        if self._draining:  # hot-swap in progress: let the slot grid run dry
            return False
        admitted = False
        for slot in range(self.slots):
            if self._slot_req[slot] is not None or not self._pending:
                continue
            req = self._pending[0]  # peek
            reserved = self._try_reserve(req)
            if reserved is None:
                break  # head-of-line waits for pages (FIFO fairness)
            self._pending.popleft()
            pages, shared_len = reserved
            # catch host state up before the admission prefill dispatches:
            # its (possibly first-compile) deadline must not also cover an
            # in-flight decode step queued ahead of it on the device
            await self._drain_pipeline()
            await self._admit_one(slot, req, pages, shared_len)
            admitted = True
        return admitted

    async def _step(self, active: list[int]) -> None:
        """One lockstep decode over all slots (inactive lanes masked).

        At ``dispatch_depth`` 2 the pipelined path runs instead: step N+1
        is dispatched from step N's device-resident tokens before N's
        outputs reach the host, then N is applied — host bookkeeping and
        device compute overlap. Cold/recovering states (first compile,
        probe steps, page-pool pressure) fall back to this classic path."""
        if self.dispatch_depth > 1 and await self._step_pipelined(active):
            return
        await self._drain_pipeline()
        # the drains above may have APPLIED a pending step whose tokens
        # finished requests in `active` (slot freed, pages returned):
        # recompute from host truth, or _reserve_or_truncate would feed a
        # ghost lane — allocating a page the next admission leaks, or
        # truncating a live request to serve a slot with no request
        active = [s for s in active if self._slot_req[s] is not None]
        if not active:
            return
        act = np.zeros(self.slots, bool)
        act[active] = True
        for s in active:
            self._reserve_or_truncate(s, act)
        cur = jnp.asarray(self._cur_tokens)
        lens = jnp.asarray(self._lengths)
        act_dev = jnp.asarray(act)
        table = self._table_array()
        self._key, sub = jax.random.split(self._key)
        # off-loop + gated: one device-step of wall time (plus first compile)
        nxt, self.k_pages, self.v_pages = await self._run_device_step(
            ("decode",),
            lambda kp=self.k_pages, vp=self.v_pages: self._decode(
                cur, lens, act_dev, table, kp, vp, sub))
        self.m_steps.inc()
        nxt_host = np.asarray(nxt)
        for s in range(self.slots):
            if not act[s] or self._slot_req[s] is None:
                continue
            self._lengths[s] += 1
            self._cur_tokens[s] = nxt_host[s]
            self._handle_token(s, int(nxt_host[s]))

    # -- pipelined dispatch (dispatch_depth 2) -------------------------------

    async def _step_pipelined(self, active: list[int]) -> bool:
        """Dispatch decode step N+1, THEN apply the in-flight step N.

        The data dependency between consecutive decode steps (next step's
        token ids are this step's outputs) is left ON the device: the
        dispatch consumes the in-flight step's un-fetched next-token array,
        so the device queue always holds the successor before the host
        fetches, and host-side page accounting / EOS checks overlap device
        compute instead of serializing with it.

        What the host cannot know one step early is EOS: a lane whose
        pending token turns out to be EOS still rides the speculative
        dispatch; its token is dropped at apply (request identity is
        snapshotted). Budget exhaustion IS host-known, so those lanes are
        masked out up front. Greedy-only (validated at construction), so
        the emitted token streams are bitwise identical to depth 1.

        Returns False when the classic path should run instead: cold
        decode jit (first-compile budget), non-HEALTHY core (probe steps
        take the gated path), or page-pool pressure (truncation policy
        lives in the classic path)."""
        from arkflow_tpu.tpu.health import HEALTHY

        if ("decode",) not in self._seen_steps \
                or self.core.health.state != HEALTHY:
            await self._drain_pipeline()
            return False
        act = np.zeros(self.slots, bool)
        act[active] = True
        pend = self._pipeline
        eff_lens = self._lengths.copy()
        if pend is not None:
            eff_lens += pend.act.astype(np.int32)
            for s in active:
                req = self._slot_req[s]
                if req is None or (pend.act[s] and req is not pend.reqs[s]):
                    act[s] = False
                elif pend.act[s] and len(req.tokens) + 1 >= req.max_new_tokens:
                    # the pending token completes this lane's budget: it
                    # must not ride the next dispatch
                    act[s] = False
        if not act.any():
            # every lane is finishing on the pending step: apply it and let
            # the loop re-evaluate (admission / drain / exit)
            await self._drain_pipeline()
            return True
        for s in np.flatnonzero(act):
            if not self._ensure_page_capacity(int(s), int(eff_lens[s]) + 1):
                await self._drain_pipeline()
                return False  # classic path owns the truncation policy
        cur = pend.nxt if pend is not None else jnp.asarray(self._cur_tokens)
        lens = jnp.asarray(eff_lens)
        act_dev = jnp.asarray(act)
        table = self._table_array()
        self._key, sub = jax.random.split(self._key)
        loop = asyncio.get_running_loop()
        self._track_gen_dispatch()

        # pools bound eagerly (same zombie discipline as the classic path);
        # the dispatch only ENQUEUES — the jit returns device futures, all
        # waiting happens in _apply_pipeline under the per-step deadline
        def enqueue(kp=self.k_pages, vp=self.v_pages):
            return self._decode(cur, lens, act_dev, table, kp, vp, sub)

        nxt, self.k_pages, self.v_pages = await loop.run_in_executor(
            None, enqueue)
        rec = _InFlightDecode(nxt=nxt, act=act, reqs=list(self._slot_req),
                              dispatched_at=time.monotonic())
        self._pipelined_dispatches += 1
        if pend is not None:
            self._pipeline = None
            await self._apply_pipeline(pend)
            # honest idle accounting under pipelining: the in-flight count
            # alone can't see a drained device (one step is always nominally
            # in flight). If the successor's outputs are ALREADY computed,
            # the device finished its whole queue during our apply and sits
            # idle until the next enqueue — open the idle window so the gap
            # records instead of silently reading as perfect overlap.
            if self._gen_idle_since is None:
                try:
                    drained = bool(rec.nxt.is_ready())
                except Exception:
                    drained = False
                if drained:
                    self._gen_idle_since = time.monotonic()
        self._pipeline = rec
        return True

    async def _drain_pipeline(self) -> None:
        """Fetch + apply the in-flight decode step, if any: every non-decode
        event (admission prefill, chunked prefill, speculative steps, swap
        drain, loop exit) runs against caught-up host state."""
        if self._pipeline is None:
            return
        pend, self._pipeline = self._pipeline, None
        await self._apply_pipeline(pend)

    async def _apply_pipeline(self, rec: _InFlightDecode) -> None:
        """Fetch one in-flight step's tokens (deadlined from ITS dispatch
        time — serving_core.deadline_remaining) and apply them to host
        state. A lane whose request finished or was replaced since dispatch
        drops its token (wasted compute, never wrong tokens)."""
        core = self.core

        def blocking():
            core.apply_chaos()
            return np.asarray(jax.device_get(rec.nxt))

        deadline = core.deadline_for(False)  # pipelined steps are warm
        try:
            if deadline is None:
                nxt_host = await asyncio.get_running_loop().run_in_executor(
                    None, blocking)
            else:
                nxt_host = await core.run_deadlined(
                    blocking, core.deadline_remaining(
                        deadline, rec.dispatched_at))
        except StepDeadlineExceeded:
            raise  # core marked UNHEALTHY; the serve loop fails + resets
        except Exception as e:
            core.health.mark_unhealthy(f"generate step failed: {e}")
            raise
        finally:
            self._track_gen_complete()
        core.health.mark_success()
        self.m_steps.inc()
        for s in range(self.slots):
            if not rec.act[s]:
                continue
            req = self._slot_req[s]
            if req is None or req is not rec.reqs[s]:
                continue
            self._lengths[s] += 1
            self._cur_tokens[s] = nxt_host[s]
            self._handle_token(s, int(nxt_host[s]))

    # -- speculative decode -------------------------------------------------

    @staticmethod
    def _draft(req: _Request, n: int) -> list[int]:
        """n draft tokens by 2-gram lookup over the sequence's own history
        (prompt-lookup decoding): find the most recent earlier occurrence
        of the trailing bigram and copy what followed it. Falls back to
        repeating the last token — a wrong draft costs nothing, the verify
        step degenerates to a plain decode for that slot."""
        hist = req.prompt + req.tokens
        out: list[int] = []
        if len(hist) >= 2 and n > 0:
            a, b = hist[-2], hist[-1]
            for i in range(len(hist) - 3, -1, -1):
                if hist[i] == a and hist[i + 1] == b:
                    out = hist[i + 2:i + 2 + n]
                    break
        while len(out) < n:
            out.append(hist[-1] if hist else 0)
        return out[:n]

    async def _step_speculative(self, active: list[int]) -> None:
        """One verify step: each active slot scores its current token plus
        up to ``speculative_tokens`` drafts in a single chunk call; the
        accepted prefix (argmax-consistent) all lands this step."""
        k = self.speculative_tokens + 1
        act = np.zeros(self.slots, bool)
        act[active] = True
        clen = np.zeros(self.slots, np.int32)
        ids = np.zeros((self.slots, k), np.int32)
        for s in active:
            # width-1 capacity first (truncation policy identical to _step)
            self._reserve_or_truncate(s, act)
            if not act[s] or self._slot_req[s] is None:
                continue
            req = self._slot_req[s]
            remaining = req.max_new_tokens - len(req.tokens)
            room = self.max_seq - int(self._lengths[s])
            c = max(1, min(k, remaining, room))
            # widen only as far as free pages allow (never truncate for width)
            while c > 1 and not self._ensure_page_capacity(
                    s, int(self._lengths[s]) + c):
                c -= 1
            clen[s] = c
            ids[s, 0] = self._cur_tokens[s]
            if c > 1:
                ids[s, 1:c] = self._draft(req, c - 1)
        table = self._table_array()
        logits, self.k_pages, self.v_pages = await self._run_device_step(
            ("verify", k),
            lambda kp=self.k_pages, vp=self.v_pages: self._verify(
                jnp.asarray(ids), jnp.asarray(self._lengths),
                jnp.asarray(clen), table, kp, vp))
        self.m_steps.inc()
        lg = np.asarray(logits)
        for s in range(self.slots):
            if not act[s] or self._slot_req[s] is None or clen[s] == 0:
                continue
            c = int(clen[s])
            outs = lg[s, :c].argmax(-1).astype(np.int32)
            accepted = 0
            while accepted < c - 1 and ids[s, accepted + 1] == outs[accepted]:
                accepted += 1
            self.m_spec_drafted.inc(c - 1)
            self.m_spec_accepted.inc(accepted)
            self._lengths[s] += accepted + 1
            self._cur_tokens[s] = int(outs[accepted])
            for t in outs[:accepted + 1]:
                self._handle_token(s, int(t))
                if self._slot_req[s] is None:
                    break
