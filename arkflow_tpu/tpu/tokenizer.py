"""Tokenization for streaming text models.

Prefers a real HuggingFace fast tokenizer when its files are cached locally
(this image has no network egress); otherwise falls back to a deterministic
hashing tokenizer so every pipeline stays hermetic. Throughput note: host-side
tokenization is the classic bottleneck ahead of the TPU (SURVEY.md section 7
hard part (d)) — the HF fast path releases the GIL and batches internally; the
fallback is vectorised regex + stable hashing.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

import numpy as np

from arkflow_tpu import native

_WORD = re.compile(rb"[a-z0-9]+|[^\sa-z0-9]")


def _fnv1a32(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class HashTokenizer:
    """Deterministic hashing tokenizer: whitespace/punct split, stable ids.

    ids: 0=pad, 1=cls, 2=sep, 3=unk; tokens FNV-1a-hash into [4, vocab).
    Uses the native C++ batch kernel when available (identical semantics);
    the Python path is the reference implementation.
    """

    def __init__(self, vocab_size: int = 30522):
        self.vocab_size = vocab_size
        self.pad_id, self.cls_id, self.sep_id = 0, 1, 2
        self._cache: dict[bytes, int] = {}

    def _token_id(self, tok: bytes) -> int:
        tid = self._cache.get(tok)
        if tid is None:
            tid = 4 + _fnv1a32(tok) % (self.vocab_size - 4)
            if len(self._cache) < 1_000_000:
                self._cache[tok] = tid
        return tid

    def encode_batch(self, texts: Sequence[bytes], max_len: int) -> tuple[np.ndarray, np.ndarray]:
        raw = [t if isinstance(t, bytes) else t.encode() for t in texts]
        nat = native.hash_tokenize_batch(raw, max_len, self.vocab_size)
        if nat is not None:
            return nat
        return self._encode_rows(raw, max_len)

    def encode_batch_view(self, values: np.ndarray, offsets: np.ndarray,
                          max_len: int) -> tuple[np.ndarray, np.ndarray]:
        """Tokenize straight off an Arrow payload view (``MessageBatch.
        payload_view``): the native kernel reads the values buffer in place —
        zero per-row Python objects on the fast path. The pure-Python
        fallback slices rows out of the buffer lazily."""
        nat = native.hash_tokenize_view(values, offsets, max_len, self.vocab_size)
        if nat is not None:
            return nat
        n = len(offsets) - 1
        base = int(offsets[0]) if n else 0
        buf = values[base : int(offsets[n]) if n else 0].tobytes()
        return self._encode_rows(
            [buf[offsets[i] - base : offsets[i + 1] - base] for i in range(n)],
            max_len)

    def _encode_rows(self, raw: Sequence[bytes], max_len: int) -> tuple[np.ndarray, np.ndarray]:
        n = len(raw)
        ids = np.zeros((n, max_len), np.int32)
        mask = np.zeros((n, max_len), np.int32)
        for i, t in enumerate(raw):
            toks = _WORD.findall(t.lower())
            row = [self.cls_id] + [self._token_id(tok) for tok in toks[: max_len - 2]] + [self.sep_id]
            ids[i, : len(row)] = row
            mask[i, : len(row)] = 1
        return ids, mask

    def decode(self, ids: Sequence[int]) -> str:
        """Hashing has no inverse vocabulary; render ids as text verbatim."""
        return " ".join(str(i) for i in ids)

    def decode_column(self, flat: np.ndarray, offsets: np.ndarray):
        """Vectorized decode of a ragged id column (flat values + offsets,
        the shape ``tpu_generate``'s flat gather produces): ids cast to
        their decimal strings and space-joined per row with two Arrow
        kernels — zero per-row Python. HF tokenizers have a real inverse
        vocabulary and decode row-wise instead (no ``decode_column``)."""
        import pyarrow as pa
        import pyarrow.compute as pc

        lst = pa.ListArray.from_arrays(
            pa.array(np.asarray(offsets, np.int32), pa.int32()),
            pc.cast(pa.array(np.asarray(flat)), pa.string()))
        return pc.binary_join(lst, " ")


class HFTokenizer:
    """transformers fast-tokenizer wrapper (local files only)."""

    def __init__(self, name: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name, local_files_only=True, use_fast=True)

    def encode_batch(self, texts: Sequence[bytes], max_len: int) -> tuple[np.ndarray, np.ndarray]:
        decoded = [t.decode("utf-8", "replace") if isinstance(t, bytes) else t for t in texts]
        enc = self._tok(
            decoded, padding="max_length", truncation=True, max_length=max_len,
            return_tensors="np", return_attention_mask=True,
        )
        return enc["input_ids"].astype(np.int32), enc["attention_mask"].astype(np.int32)

    def encode_batch_view(self, values: np.ndarray, offsets: np.ndarray,
                          max_len: int) -> tuple[np.ndarray, np.ndarray]:
        """HF tokenizers want ``str`` rows; decode them off the buffer view
        (one big decode + string slicing beats per-row bytes round trips).
        Only the window the rows reference is materialized (sliced batches
        share a larger parent buffer)."""
        n = len(offsets) - 1
        base = int(offsets[0]) if n else 0
        buf = values[base : int(offsets[n]) if n else 0].tobytes()
        text = buf.decode("utf-8", "replace")
        # byte offsets only index the decoded str when every byte decoded to
        # one char (pure ASCII); otherwise decode per row
        if len(text) == len(buf):
            rows = [text[offsets[i] - base : offsets[i + 1] - base] for i in range(n)]
        else:
            rows = [buf[offsets[i] - base : offsets[i + 1] - base].decode("utf-8", "replace")
                    for i in range(n)]
        return self.encode_batch(rows, max_len)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def build_tokenizer(name: Optional[str], vocab_size: int = 30522):
    """HF tokenizer when cached locally; hashing fallback otherwise."""
    if name:
        try:
            return HFTokenizer(name)
        except Exception:
            pass
    return HashTokenizer(vocab_size)
