"""Runner health state machine: the device-path analogue of the circuit breaker.

PRs 1-3 made the I/O path fail gracefully (retries, breakers, reconnect); this
module gives the DEVICE path the same property. Every ``ModelRunner`` owns a
``RunnerHealth`` that tracks whether its chip is trustworthy:

    HEALTHY   -- serving normally
    DEGRADED  -- serving, but at reduced capability (e.g. the bucket grid was
                 capped after a device OOM); transient — the next successful
                 step promotes back to HEALTHY (the permanent cap is visible
                 on the ``arkflow_tpu_bucket_cap`` gauge instead)
    UNHEALTHY -- a step hung past its deadline or kept failing; the runner is
                 skipped by pool dispatch until a recovery probe is due, with
                 exponential backoff between probes
    DEAD      -- ``dead_after`` consecutive incidents without one success;
                 terminal — never probed again, reported on ``/health``
    CORRUPT   -- quarantined for a PROVEN integrity failure (param-digest
                 mismatch confirmed by a failed golden probe, tpu/integrity.py):
                 DEAD-adjacent — skipped by dispatch and NEVER re-admitted by
                 the probe/backoff schedule alone, because a corrupt chip can
                 pass a liveness probe while still answering wrongly. Only an
                 explicit ``mark_repaired`` (after re-adopting known-good
                 params, re-verifying digests, and passing the golden probe)
                 returns it to HEALTHY.

Transitions are driven by step outcomes (``mark_success`` / ``mark_unhealthy``
/ ``mark_degraded``); recovery probes are REAL traffic batches: when a probe
is due, dispatch routes one batch to the suspect runner (``try_begin_probe``
claims the slot so concurrent workers don't pile on), and that batch's own
step deadline bounds the damage if the device is still hung — at-least-once
delivery is preserved because a failed probe batch nacks like any other
failure.

The state is exported on the ``arkflow_tpu_runner_health`` gauge
(0 healthy / 1 degraded / 2 unhealthy / 3 dead) so "which chip is limping"
is answerable from the metrics endpoint.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from arkflow_tpu.errors import ConfigError

logger = logging.getLogger("arkflow.tpu.health")

HEALTHY = "healthy"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
DEAD = "dead"
CORRUPT = "corrupt"

#: gauge encoding for ``arkflow_tpu_runner_health``
GAUGE_VALUE = {HEALTHY: 0, DEGRADED: 1, UNHEALTHY: 2, DEAD: 3, CORRUPT: 4}


@dataclass(frozen=True)
class HealthConfig:
    """Knobs for the recovery-probe schedule (config: ``health:`` on the
    ``tpu_inference`` processor)."""

    #: first probe delay after an incident; doubles per consecutive incident
    probe_backoff_s: float = 0.5
    #: cap on the probe backoff
    probe_backoff_cap_s: float = 30.0
    #: consecutive incidents (no success in between) before the runner is
    #: declared DEAD; 0 = never give up
    dead_after: int = 8

    @classmethod
    def from_config(cls, cfg: Optional[dict]) -> "HealthConfig":
        if not cfg:
            return cls()
        if not isinstance(cfg, dict):
            raise ConfigError("tpu_inference 'health' must be a mapping")
        from arkflow_tpu.utils.duration import parse_duration

        def dur(key: str, default: float) -> float:
            raw = cfg.get(key)
            if raw is None:
                return default
            val = parse_duration(raw)
            if val <= 0:
                raise ConfigError(f"health.{key} must be positive")
            return val

        dead_after = cfg.get("dead_after", cls.dead_after)
        if not isinstance(dead_after, int) or dead_after < 0:
            raise ConfigError("health.dead_after must be an int >= 0")
        return cls(
            probe_backoff_s=dur("probe_backoff", cls.probe_backoff_s),
            probe_backoff_cap_s=dur("probe_backoff_cap", cls.probe_backoff_cap_s),
            dead_after=dead_after,
        )


class RunnerHealth:
    """Thread-safe health tracker (marks arrive from executor threads and the
    event loop alike). ``clock`` is injectable for deterministic tests."""

    def __init__(self, config: Optional[HealthConfig] = None, *,
                 gauge=None, name: str = "runner",
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = config or HealthConfig()
        self.name = name
        self._clock = clock
        self._gauge = gauge
        self._lock = threading.Lock()
        self._state = HEALTHY
        self._consecutive_failures = 0
        self._next_probe_at = 0.0
        self._probing = False
        #: set when a dispatcher (pool ``_pick``) claimed the probe for a
        #: batch that will re-enter through the runner's own gate — exactly
        #: ONE joiner may consume the claim; everyone else waits
        self._probe_handoff = False
        self._last_reason = ""
        if gauge is not None:
            gauge.set(GAUGE_VALUE[HEALTHY])

    # -- inspection --------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def report(self) -> dict:
        """JSON-able snapshot for ``/health``."""
        with self._lock:
            rep = {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
            }
            if self._last_reason:
                rep["last_reason"] = self._last_reason
            if self._state == UNHEALTHY:
                rep["next_probe_in_s"] = round(
                    max(0.0, self._next_probe_at - self._clock()), 3)
            return rep

    def probe_due(self, now: Optional[float] = None) -> bool:
        return (self._state == UNHEALTHY
                and (self._clock() if now is None else now) >= self._next_probe_at)

    def seconds_until_probe(self, now: Optional[float] = None) -> float:
        with self._lock:
            if self._state != UNHEALTHY:
                return 0.0
            return max(0.0, self._next_probe_at - (self._clock() if now is None else now))

    def available(self, now: Optional[float] = None) -> bool:
        """May a batch be dispatched here right now? HEALTHY/DEGRADED always;
        UNHEALTHY only when a probe is due and nobody is already probing."""
        s = self._state
        if s in (HEALTHY, DEGRADED):
            return True
        if s == UNHEALTHY:
            return not self._probing and self.probe_due(now)
        return False  # DEAD / CORRUPT

    # -- transitions -------------------------------------------------------

    def _set(self, state: str) -> None:
        self._state = state
        if self._gauge is not None:
            self._gauge.set(GAUGE_VALUE[state])

    def try_begin_probe(self, now: Optional[float] = None) -> bool:
        """Claim the recovery-probe slot. True when the caller should
        dispatch now: the runner is serving normally, or it just claimed the
        due probe. False while DEAD, mid-backoff, or already being probed."""
        with self._lock:
            if self._state in (HEALTHY, DEGRADED):
                return True
            if self._state in (DEAD, CORRUPT):
                return False
            now = self._clock() if now is None else now
            if self._probing or now < self._next_probe_at:
                return False
            self._probing = True
            self._probe_handoff = True
            return True

    def join_or_begin_probe(self, now: Optional[float] = None) -> bool:
        """Like ``try_begin_probe`` but honors an upstream claim: when pool
        dispatch claimed the probe for the very batch now arriving at the
        runner's own gate, that ONE batch joins; every other concurrent
        caller waits instead of piling onto a maybe-still-hung device (a
        pile-up would blow N deadlines at once and race the incident
        counter toward DEAD)."""
        with self._lock:
            if self._state in (HEALTHY, DEGRADED):
                return True
            if self._state in (DEAD, CORRUPT):
                return False
            if self._probing:
                if self._probe_handoff:
                    self._probe_handoff = False
                    return True
                return False
            now = self._clock() if now is None else now
            if now < self._next_probe_at:
                return False
            self._probing = True
            return True

    def mark_success(self) -> None:
        """A step completed: clear the incident streak; re-admit a suspect.
        CORRUPT is NOT cleared here: a quarantined member may still complete
        steps (that is the failure mode — plausible-but-wrong answers), so
        only the explicit repair path (``mark_repaired``) re-admits it."""
        with self._lock:
            if self._state in (DEAD, CORRUPT):
                return  # terminal / quarantined
            self._probing = False
            self._probe_handoff = False
            self._consecutive_failures = 0
            if self._state != HEALTHY:
                logger.info("[%s] runner recovered -> HEALTHY", self.name)
                self._last_reason = ""
                self._set(HEALTHY)

    def mark_degraded(self, reason: str) -> None:
        """Serving continues at reduced capability (bucket grid capped)."""
        with self._lock:
            if self._state == HEALTHY:
                logger.warning("[%s] runner DEGRADED: %s", self.name, reason)
                self._last_reason = reason
                self._set(DEGRADED)

    def mark_unhealthy(self, reason: str) -> None:
        """An incident (deadline miss, repeated step failure): stop receiving
        traffic, schedule a recovery probe with exponential backoff."""
        with self._lock:
            if self._state in (DEAD, CORRUPT):
                return  # CORRUPT outranks: repair owns the exit transition
            self._probing = False
            self._probe_handoff = False
            self._consecutive_failures += 1
            self._last_reason = reason
            if (self.cfg.dead_after
                    and self._consecutive_failures >= self.cfg.dead_after):
                logger.error("[%s] runner DEAD after %d consecutive incidents "
                             "(last: %s)", self.name,
                             self._consecutive_failures, reason)
                self._set(DEAD)
                return
            backoff = min(
                self.cfg.probe_backoff_s
                * (2.0 ** min(self._consecutive_failures - 1, 32)),
                self.cfg.probe_backoff_cap_s,
            )
            self._next_probe_at = self._clock() + backoff
            logger.warning("[%s] runner UNHEALTHY (%s); probe in %.2fs "
                           "(incident %d)", self.name, reason, backoff,
                           self._consecutive_failures)
            self._set(UNHEALTHY)

    def mark_corrupt(self, reason: str) -> None:
        """Quarantine for a PROVEN integrity failure (tpu/integrity.py): the
        member answered the golden probe wrongly or its param digests drifted.
        DEAD-adjacent — dispatch skips it and no step success or probe
        schedule ever re-admits it; only ``mark_repaired`` (after re-adopting
        known-good params and re-passing the probe) exits this state."""
        with self._lock:
            if self._state in (DEAD, CORRUPT):
                return
            self._probing = False
            self._probe_handoff = False
            self._last_reason = reason
            logger.error("[%s] runner CORRUPT — quarantined: %s",
                         self.name, reason)
            self._set(CORRUPT)

    def mark_repaired(self) -> bool:
        """Exit quarantine after a verified repair: the integrity monitor
        re-adopted known-good params, re-verified the digests, and the golden
        probe passed again. Returns False (no-op) from any other state — the
        repair path must never resurrect a DEAD member."""
        with self._lock:
            if self._state != CORRUPT:
                return False
            self._probing = False
            self._probe_handoff = False
            self._consecutive_failures = 0
            self._last_reason = ""
            logger.info("[%s] runner repaired -> HEALTHY", self.name)
            self._set(HEALTHY)
            return True
