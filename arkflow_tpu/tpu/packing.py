"""Token packing: padding-free execution for ragged token streams.

Padding to a seq bucket burns MXU FLOPs on dead tokens: at the flagship
seq-32 BERT shape a realistic length distribution fills ~50-60% of the
bucket, so nearly half the compute is waste. Packing bin-packs several
short examples into each model row (the Graphcore "packed BERT" recipe,
done TPU-style with static shapes):

- ``segment_ids`` keep attention block-diagonal — tokens only attend within
  their own example (0 marks dead positions);
- ``position_ids`` restart at 0 per example so position embeddings match
  the unpacked layout;
- ``example_row``/``example_pos`` locate each original example's first
  token ([CLS]) in the packed layout, so per-example outputs gather back
  into the original row order.

FLOPs per packed row equal a padded row's, but the row count drops to
~ceil(total_tokens / seq): flops/row tracks real token count. The packer is
a host-side first-fit-decreasing pass (O(N) python loop with a vectorized
first-fit scan) — the model-side contract is pure static-shape arrays, so
the packed step jits like any other bucket.

The reference has no analog (its model slot is user Python, ref
crates/arkflow-plugin/src/processor/python.rs:46-102); this is TPU-native
headroom on the same BASELINE north-star workload.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from arkflow_tpu import native


@dataclass
class PackedTokens:
    """Static-shape packed layout. P packed rows of width ``seq``; E original
    examples (E >= P; each packed row holds >= 1 example)."""

    input_ids: np.ndarray    # [P, seq] int32, 0 on dead positions
    segment_ids: np.ndarray  # [P, seq] int32, 1..k per example, 0 = dead
    position_ids: np.ndarray  # [P, seq] int32, restarts at 0 per example
    example_row: np.ndarray  # [E] int32: packed row of example i's first token
    example_pos: np.ndarray  # [E] int32: column of example i's first token

    @property
    def num_rows(self) -> int:
        return self.input_ids.shape[0]

    @property
    def num_examples(self) -> int:
        return self.example_row.shape[0]

    @property
    def fill_ratio(self) -> float:
        total = self.input_ids.shape[0] * self.input_ids.shape[1]
        return float((self.segment_ids > 0).sum()) / total if total else 0.0


def pack_tokens(ids: np.ndarray, lengths: np.ndarray, seq: int) -> PackedTokens:
    """First-fit-decreasing pack of N ragged examples into rows of width
    ``seq``. Examples longer than ``seq`` are truncated (callers pick
    ``seq`` as the bucket of the longest example, so that is the same
    truncation padding would apply). Example order is preserved in the
    ``example_*`` index arrays: entry i is original row i.
    """
    ids = np.asarray(ids)
    if ids.ndim != 2 or (ids.shape[0] > 0 and ids.shape[1] == 0):
        raise ValueError(f"pack_tokens: ids must be [n, smax>0], got shape {ids.shape}")
    n = ids.shape[0]
    # clamp to the bucket AND the ids row width: a length beyond the row
    # would read garbage in the native tier / raise in the Python one
    lengths = np.minimum(np.asarray(lengths, np.int64), min(seq, ids.shape[1]))
    lengths = np.maximum(lengths, 1)  # empty text still occupies its [CLS] slot
    if n == 0:
        z = np.zeros((0, seq), np.int32)
        e = np.zeros((0,), np.int32)
        return PackedTokens(z, z.copy(), z.copy(), e, e.copy())

    nat = native.pack_tokens_native(ids, lengths, seq)
    if nat is not None:  # hot path: ~7ms/1024 rows in Python, us-scale in C++
        return PackedTokens(*nat)

    order = np.argsort(-lengths, kind="stable")
    bin_free = np.empty(n, np.int64)  # capacity left per bin; at most n bins
    n_bins = 0
    bin_of = np.empty(n, np.int64)
    start_of = np.empty(n, np.int64)
    for i in order:
        length = lengths[i]
        fits = bin_free[:n_bins] >= length
        if fits.any():
            b = int(np.argmax(fits))  # first fit
        else:
            b = n_bins
            n_bins += 1
            bin_free[b] = seq
        bin_of[i] = b
        start_of[i] = seq - bin_free[b]
        bin_free[b] -= length

    out_ids = np.zeros((n_bins, seq), np.int32)
    seg = np.zeros((n_bins, seq), np.int32)
    pos = np.zeros((n_bins, seq), np.int32)
    seg_next = np.ones(n_bins, np.int64)
    ex_row = np.empty(n, np.int32)
    ex_pos = np.empty(n, np.int32)
    for i in range(n):
        b, st, length = bin_of[i], start_of[i], lengths[i]
        out_ids[b, st:st + length] = ids[i, :length]
        seg[b, st:st + length] = seg_next[b]
        seg_next[b] += 1
        pos[b, st:st + length] = np.arange(length)
        ex_row[i] = b
        ex_pos[i] = st
    return PackedTokens(out_ids, seg, pos, ex_row, ex_pos)
