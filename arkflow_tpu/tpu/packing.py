"""Token packing: padding-free execution for ragged token streams.

Padding to a seq bucket burns MXU FLOPs on dead tokens: at the flagship
seq-32 BERT shape a realistic length distribution fills ~50-60% of the
bucket, so nearly half the compute is waste. Packing bin-packs several
short examples into each model row (the Graphcore "packed BERT" recipe,
done TPU-style with static shapes):

- ``segment_ids`` keep attention block-diagonal — tokens only attend within
  their own example (0 marks dead positions);
- ``position_ids`` restart at 0 per example so position embeddings match
  the unpacked layout;
- ``example_row``/``example_pos`` locate each original example's first
  token ([CLS]) in the packed layout, so per-example outputs gather back
  into the original row order.

FLOPs per packed row equal a padded row's, but the row count drops to
~ceil(total_tokens / seq): flops/row tracks real token count. The packer is
a host-side first-fit-decreasing pass (O(N) python loop with a vectorized
first-fit scan) — the model-side contract is pure static-shape arrays, so
the packed step jits like any other bucket.

The reference has no analog (its model slot is user Python, ref
crates/arkflow-plugin/src/processor/python.rs:46-102); this is TPU-native
headroom on the same BASELINE north-star workload.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from arkflow_tpu import native


@dataclass
class PackedTokens:
    """Static-shape packed layout. P packed rows of width ``seq``; E original
    examples (E >= P; each packed row holds >= 1 example)."""

    input_ids: np.ndarray    # [P, seq] int32, 0 on dead positions
    segment_ids: np.ndarray  # [P, seq] int32, 1..k per example, 0 = dead
    position_ids: np.ndarray  # [P, seq] int32, restarts at 0 per example
    example_row: np.ndarray  # [E] int32: packed row of example i's first token
    example_pos: np.ndarray  # [E] int32: column of example i's first token

    @property
    def num_rows(self) -> int:
        return self.input_ids.shape[0]

    @property
    def num_examples(self) -> int:
        return self.example_row.shape[0]

    @property
    def fill_ratio(self) -> float:
        total = self.input_ids.shape[0] * self.input_ids.shape[1]
        return float((self.segment_ids > 0).sum()) / total if total else 0.0


def pack_tokens(ids: np.ndarray, lengths: np.ndarray, seq: int) -> PackedTokens:
    """First-fit-decreasing pack of N ragged examples into rows of width
    ``seq``. Examples longer than ``seq`` are truncated (callers pick
    ``seq`` as the bucket of the longest example, so that is the same
    truncation padding would apply). Example order is preserved in the
    ``example_*`` index arrays: entry i is original row i.
    """
    ids = np.asarray(ids)
    if ids.ndim != 2 or (ids.shape[0] > 0 and ids.shape[1] == 0):
        raise ValueError(f"pack_tokens: ids must be [n, smax>0], got shape {ids.shape}")
    n = ids.shape[0]
    # clamp to the bucket AND the ids row width: a length beyond the row
    # would read garbage in the native tier / raise in the Python one
    lengths = np.minimum(np.asarray(lengths, np.int64), min(seq, ids.shape[1]))
    lengths = np.maximum(lengths, 1)  # empty text still occupies its [CLS] slot
    if n == 0:
        z = np.zeros((0, seq), np.int32)
        e = np.zeros((0,), np.int32)
        return PackedTokens(z, z.copy(), z.copy(), e, e.copy())

    nat = native.pack_tokens_native(ids, lengths, seq)
    if nat is not None:  # hot path: ~7ms/1024 rows in Python, us-scale in C++
        return PackedTokens(*nat)

    order = np.argsort(-lengths, kind="stable")
    bin_free = np.empty(n, np.int64)  # capacity left per bin; at most n bins
    n_bins = 0
    bin_of = np.empty(n, np.int64)
    start_of = np.empty(n, np.int64)
    for i in order:
        length = lengths[i]
        fits = bin_free[:n_bins] >= length
        if fits.any():
            b = int(np.argmax(fits))  # first fit
        else:
            b = n_bins
            n_bins += 1
            bin_free[b] = seq
        bin_of[i] = b
        start_of[i] = seq - bin_free[b]
        bin_free[b] -= length

    out_ids = np.zeros((n_bins, seq), np.int32)
    seg = np.zeros((n_bins, seq), np.int32)
    pos = np.zeros((n_bins, seq), np.int32)
    seg_next = np.ones(n_bins, np.int64)
    ex_row = np.empty(n, np.int32)
    ex_pos = np.empty(n, np.int32)
    for i in range(n):
        b, st, length = bin_of[i], start_of[i], lengths[i]
        out_ids[b, st:st + length] = ids[i, :length]
        seg[b, st:st + length] = seg_next[b]
        seg_next[b] += 1
        pos[b, st:st + length] = np.arange(length)
        ex_row[i] = b
        ex_pos[i] = st
    return PackedTokens(out_ids, seg, pos, ex_row, ex_pos)


def carve_row_windows(
    pk: PackedTokens, max_rows: int, max_examples: int,
    row_buckets: "tuple[int, ...] | None" = None,
) -> list[tuple[dict, np.ndarray]]:
    """Slice a packed layout into independent row windows that fit the
    compiled grid: at most ``max_rows`` packed rows and ``max_examples``
    examples per window.

    Rows are independent after packing (attention is block-diagonal within a
    row and every example's tokens live in exactly one row), so a window is
    a pure row slice plus the examples whose [CLS] sits in it — packing once
    and carving after is what lets a token-budget emission fill the largest
    compiled ``(rows, seq)`` shape exactly. With ``row_buckets`` the window
    sizes CASCADE down the compiled grid (a 1139-row layout against
    [...,512,1024] carves 1024 + 64 + 32 + ...): every window lands
    bucket-exact, so the only bucket-padding left is the sub-minimum
    residue — the per-dispatch waste stays at the packer's fill ratio
    instead of whatever the emission size happened to round up to. Returns
    ``(inputs, example_idx)`` pairs: ``inputs`` feeds the packed apply
    directly (``example_row`` re-based to the window), ``example_idx``
    scatters the window's outputs back into original example order. All
    index work is numpy (one argsort + two searchsorteds per window); no
    per-row or per-example Python.
    """
    if max_rows < 1 or max_examples < 1:
        raise ValueError(
            f"carve_row_windows: max_rows/max_examples must be >= 1, "
            f"got ({max_rows}, {max_examples})")
    total_rows = pk.num_rows
    if total_rows == 0:
        return []
    buckets = sorted(b for b in (row_buckets or ()) if b <= max_rows)
    order = np.argsort(pk.example_row, kind="stable")
    row_sorted = pk.example_row[order]
    windows: list[tuple[dict, np.ndarray]] = []
    lo = 0
    b0 = 0
    while lo < total_rows:
        remaining = total_rows - lo
        step = min(max_rows, remaining)
        if buckets:
            fitting = [b for b in buckets if b <= step]
            # bucket-exact cascade; the sub-minimum residue emits as-is
            # (the runner rounds it up to the smallest compiled bucket)
            if fitting and remaining > fitting[-1]:
                step = fitting[-1]
        hi = lo + step
        b1 = int(np.searchsorted(row_sorted, hi, side="left"))
        if b1 - b0 > max_examples:
            # the (b0 + max_examples)-th example's row doesn't fully fit;
            # end the window before it (a row's examples are inseparable)
            hi = int(row_sorted[b0 + max_examples])
            b1 = int(np.searchsorted(row_sorted, hi, side="left"))
            if hi <= lo:
                # one row alone holds > max_examples examples (possible only
                # when the policy's example grid was overridden below seq/2):
                # emit it solo and let the runner's bucket check surface it
                hi = lo + 1
                b1 = int(np.searchsorted(row_sorted, hi, side="left"))
        idx = order[b0:b1]
        windows.append((
            {
                "input_ids": pk.input_ids[lo:hi],
                "segment_ids": pk.segment_ids[lo:hi],
                "position_ids": pk.position_ids[lo:hi],
                "example_row": (pk.example_row[idx] - lo).astype(np.int32),
                "example_pos": pk.example_pos[idx],
            },
            idx,
        ))
        lo = hi
        b0 = b1
    return windows
