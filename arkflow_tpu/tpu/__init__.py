from arkflow_tpu.tpu.bucketing import BucketPolicy  # noqa: F401
from arkflow_tpu.tpu.runner import ModelRunner  # noqa: F401
