"""Model checkpoint save/restore (orbax).

The reference has no state checkpointing (SURVEY.md section 5 — delivery
relies on broker acks); model parameters are new state this engine owns, so
they get first-class checkpointing: ``save``/``restore`` wrap orbax's
StandardCheckpointer and the ``tpu_inference``/``tpu_generate`` processors
accept a ``checkpoint:`` path at build. The same paths feed the live
hot-swap manager (``tpu/swap.py``), so their failure modes must be clean:

- ``save`` is **crash-atomic**: orbax writes into a hidden temp sibling
  directory which is renamed into place only once fully written and synced.
  A reader (a later ``restore``, a hot-swap on another process) therefore
  sees the old checkpoint, the new checkpoint, or — in the narrow replace
  window — no checkpoint at all (a loud, detectable state), but **never a
  half-written tree** it would restore garbage from.
- ``restore`` maps orbax's raw tree-structure mismatch tracebacks to a
  ``ConfigError`` that names the offending leaves (what the model expects
  vs what the checkpoint holds), so a wrong-architecture checkpoint fails
  with an actionable message instead of a stack of orbax internals.
- ``save`` additionally writes a **digest manifest** beside the tree (one
  blake2b per leaf, tpu/integrity.py) with the same crash-atomic
  discipline, and ``restore`` verifies the restored tree against it when
  present — so corruption AT REST (truncated/mangled bytes that orbax can
  still deserialize, a half-synced copy) fails loudly naming the drifted
  leaves, not just corruption in HBM. Manifest-less checkpoints (older
  saves, foreign writers) restore unverified, as before.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path

from arkflow_tpu.errors import ConfigError

#: digest-manifest sibling suffix (a FILE next to the checkpoint dir)
_MANIFEST_SUFFIX = ".digests.json"


def _manifest_path(p: Path) -> Path:
    return p.parent / f"{p.name}{_MANIFEST_SUFFIX}"


def _tmp_sibling(p: Path, tag: str) -> Path:
    """Hidden sibling on the SAME filesystem (os.rename must not cross
    devices); pid-suffixed so concurrent savers to DIFFERENT paths under
    one parent never collide. (Concurrent savers to the SAME path are
    unsupported — last rename wins.)"""
    return p.parent / f".{p.name}.{tag}-{os.getpid()}"


def _clean_stale_siblings(p: Path) -> None:
    """Remove temp/old siblings left by CRASHED earlier saves of this path,
    from any pid — a crashed process never cleans its own, so without the
    glob full-size checkpoint copies would leak on disk forever."""
    for stale in p.parent.glob(f".{p.name}.tmp-*"):
        shutil.rmtree(stale, ignore_errors=True)
    for stale in p.parent.glob(f".{p.name}.old-*"):
        shutil.rmtree(stale, ignore_errors=True)
    for stale in p.parent.glob(f".{p.name}{_MANIFEST_SUFFIX}.tmp-*"):
        stale.unlink(missing_ok=True)


def save(path: str, params) -> None:
    """Write ``params`` to ``path`` atomically (temp sibling + rename).

    Replacing an existing checkpoint renames the old tree aside before the
    new one lands, then deletes it — a crash anywhere in the sequence leaves
    either a complete old tree, a complete new tree, or a missing path
    (which ``restore`` rejects loudly), never a partial one.
    """
    import orbax.checkpoint as ocp

    p = Path(path).absolute()
    p.parent.mkdir(parents=True, exist_ok=True)
    _clean_stale_siblings(p)  # crashed saves (any pid) never half-read
    # the digest manifest must never describe a DIFFERENT tree than the one
    # on disk: drop the old manifest BEFORE the tree flips, write the new
    # one after — every crash window leaves a tree without a manifest
    # (restore skips verification, the pre-manifest behavior), never a tree
    # with the WRONG manifest (which would fail a legitimate restore)
    from arkflow_tpu.tpu.integrity import tree_digests

    digests = tree_digests(params)
    manifest = _manifest_path(p)
    manifest.unlink(missing_ok=True)
    tmp = _tmp_sibling(p, "tmp")
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, params)
    ckptr.wait_until_finished()
    if p.exists():
        old = _tmp_sibling(p, "old")
        if old.exists():
            shutil.rmtree(old)
        os.rename(p, old)
        os.rename(tmp, p)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(tmp, p)
    mtmp = manifest.parent / f".{manifest.name}.tmp-{os.getpid()}"
    mtmp.write_text(json.dumps({"digests": digests}, indent=0))
    os.rename(mtmp, manifest)


def _mismatch_hint(ckptr, p: Path, like_params) -> str:
    """Best-effort diff of the checkpoint's tree structure against the
    model's: names the first offending leaves. Returns "" when the saved
    metadata itself is unreadable (corrupt checkpoint)."""
    try:
        import jax.tree_util as jtu

        saved = {jtu.keystr(k)
                 for k, _ in jtu.tree_flatten_with_path(ckptr.metadata(p))[0]}
        want = {jtu.keystr(k)
                for k, _ in jtu.tree_flatten_with_path(like_params)[0]}
        missing = sorted(want - saved)
        extra = sorted(saved - want)
        parts = []
        if missing:
            parts.append(f"model expects leaves the checkpoint lacks: "
                         f"{missing[:3]}{'...' if len(missing) > 3 else ''}")
        if extra:
            parts.append(f"checkpoint holds leaves the model lacks: "
                         f"{extra[:3]}{'...' if len(extra) > 3 else ''}")
        return "; ".join(parts)
    except Exception:
        return ""


def restore(path: str, like_params, *, verify: bool = True):
    """Restore ``path`` into the structure/dtypes of ``like_params``.

    Raises ``ConfigError`` (never a raw orbax traceback) when the path is
    missing, the tree structure does not match the model's, or the
    checkpoint bytes are unreadable (truncated / mangled files). When a
    digest manifest sits beside the tree (written by :func:`save`) and
    ``verify`` is on, the restored tree is hashed against it and a drift
    raises a ``ConfigError`` naming the mismatched leaves — the
    corrupt-at-rest defense: bytes orbax can still deserialize but that
    are not the bytes ``save`` wrote must never reach a serving tree.
    """
    import orbax.checkpoint as ocp

    p = Path(path).absolute()
    if not p.exists():
        raise ConfigError(f"checkpoint path {p} does not exist")
    ckptr = ocp.StandardCheckpointer()
    try:
        restored = ckptr.restore(p, like_params)
    except ConfigError:
        raise
    except Exception as e:
        hint = _mismatch_hint(ckptr, p, like_params)
        raise ConfigError(
            f"failed to restore checkpoint {p}: "
            f"{hint if hint else f'{type(e).__name__}: {e}'}") from e
    manifest = _manifest_path(p)
    if verify and manifest.exists():
        from arkflow_tpu.tpu.integrity import diff_digests, tree_digests

        try:
            want = json.loads(manifest.read_text())["digests"]
        except Exception as e:
            raise ConfigError(
                f"checkpoint digest manifest {manifest} is unreadable "
                f"({type(e).__name__}: {e}); delete it to restore "
                "unverified") from e
        drifted = diff_digests(want, tree_digests(restored))
        if drifted:
            preview = drifted[:3] + (["..."] if len(drifted) > 3 else [])
            raise ConfigError(
                f"checkpoint {p} failed digest verification: {len(drifted)} "
                f"leaves drifted from the manifest: {preview} — the bytes "
                "on disk are not the bytes save() wrote (corrupt at rest), "
                "or the checkpoint was overwritten by a foreign writer")
    return restored
