"""Model checkpoint save/restore (orbax).

The reference has no state checkpointing (SURVEY.md section 5 — delivery
relies on broker acks); model parameters are new state this engine owns, so
they get first-class checkpointing: ``save``/``restore`` wrap orbax's
StandardCheckpointer and the ``tpu_inference``/``tpu_generate`` processors
accept a ``checkpoint:`` path at build.
"""

from __future__ import annotations

from pathlib import Path

from arkflow_tpu.errors import ConfigError


def save(path: str, params) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(Path(path).absolute(), params)
    ckptr.wait_until_finished()


def restore(path: str, like_params):
    import orbax.checkpoint as ocp

    p = Path(path).absolute()
    if not p.exists():
        raise ConfigError(f"checkpoint path {p} does not exist")
    return ocp.StandardCheckpointer().restore(p, like_params)
