"""Arrow column -> model-input ndarray extraction, shared by the device
processors (tpu_inference / tpu_train) so the list/binary/scalar handling
can't drift between them.

This is the host side of the infeed hot path, so every column kind has a
vectorized, allocation-lean implementation: binary payloads are gathered
straight out of the Arrow values buffer with offset arithmetic (one ragged
numpy gather builds the whole ``[B, prod(want)]`` matrix — no per-row
``as_py()``/``np.pad``/``np.stack``), and (nested) list columns reshape
zero-copy views of their flattened values.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from arkflow_tpu.batch import MessageBatch, binary_column_view
from arkflow_tpu.errors import ProcessError


#: below this mean payload size the flat fancy-index gather beats a per-row
#: slice-copy loop (index arithmetic amortizes; loop overhead dominates);
#: above it each row is one bulk memcpy and the loop wins (measured on the
#: 4096x784 image and 8192x20 sensor shapes)
_GATHER_MAX_MEAN_LEN = 128


#: byte-class lookup tables over the raw payload buffer, mirroring the hash
#: tokenizer's ``[a-z0-9]+|[^\sa-z0-9]`` split after ``.lower()``: WORD bytes
#: extend a token, SINGLE bytes are one token each, the rest is whitespace
_TOK_WORD = np.zeros(256, np.bool_)
for _r in (range(ord("a"), ord("z") + 1), range(ord("A"), ord("Z") + 1),
           range(ord("0"), ord("9") + 1)):
    _TOK_WORD[list(_r)] = True
_TOK_SPACE = np.zeros(256, np.bool_)
_TOK_SPACE[[ord(c) for c in " \t\n\r\x0b\x0c"]] = True
_TOK_SINGLE = ~(_TOK_WORD | _TOK_SPACE)


def payload_token_estimates(col: pa.Array, *, token_bytes: Optional[float] = None,
                            max_tokens: Optional[int] = None) -> np.ndarray:
    """Per-row token-count estimates for a binary/string payload column —
    the token-budget coalescer's sizing signal (one vectorized pass over the
    Arrow buffers, zero per-row Python).

    Default mode mirrors the hash tokenizer exactly: tokens = alnum runs +
    standalone punctuation bytes, counted with byte-class lookup tables and
    a cumsum over run starts, plus 2 specials ([CLS]/[SEP]). ``token_bytes``
    switches to a bytes-per-token divisor (``ceil(len/token_bytes) + 2``) —
    the right estimate for subword (HF/BPE) tokenizers, where splits don't
    follow whitespace. ``max_tokens`` clamps rows to the serving truncation
    width so one huge payload can't starve an emission's budget.
    """
    values, offsets = binary_column_view(col)
    n = len(col)
    if n == 0:
        return np.zeros(0, np.int64)
    starts = offsets[:-1]
    lens = (offsets[1:] - starts).astype(np.int64)
    if col.null_count:
        # nulls estimate as empty payloads (their byte range may be garbage)
        lens = np.where(col.is_null().to_numpy(zero_copy_only=False), 0, lens)
    if token_bytes is not None:
        est = np.ceil(lens / float(token_bytes)).astype(np.int64) + 2
    else:
        lo = int(starts[0])
        hi = int(offsets[-1])
        window = values[lo:hi]
        word = _TOK_WORD[window]
        # a word-run start: WORD byte not preceded by a WORD byte; row starts
        # always begin a run (the previous byte belongs to another row)
        run_start = word.copy()
        run_start[1:] &= ~word[:-1]
        within = starts - lo
        run_start[within[within < len(window)]] = word[within[within < len(window)]]
        counts = run_start.astype(np.int64) + _TOK_SINGLE[window]
        cs = np.concatenate(([0], np.cumsum(counts)))
        ends = np.minimum(starts - lo + lens, len(window))
        est = cs[ends] - cs[np.minimum(within, len(window))] + 2
    est = np.maximum(est, 2)  # empty text still tokenizes to [CLS][SEP]
    if max_tokens is not None:
        est = np.minimum(est, int(max_tokens))
    return est


def _binary_matrix(col: pa.Array, n: int, size: int) -> np.ndarray:
    """Binary column -> ``[n, size]`` uint8, zero-padded/truncated per row.

    Works on the Arrow buffers directly (no per-row ``as_py``/``np.pad``):

    - uniform row length (the image-payload case): the values buffer IS the
      matrix — one ``reshape`` view, zero copies (or one bulk memcpy when
      rows are shorter than ``size``);
    - ragged short rows: one flat fancy-index gather, O(total bytes);
    - ragged long rows: per-row numpy slice copies (bulk memcpy each).
    """
    values, offsets = binary_column_view(col)
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    if n == 0:
        return np.zeros((0, size), np.uint8)
    if col.null_count:
        # nulls read as empty payloads (matches the old ``as_py() or b""``)
        lens = np.where(col.is_null().to_numpy(zero_copy_only=False), 0, lens)
    elif lens.min() == lens.max():
        # uniform rows sit back-to-back in the values buffer (Arrow offsets
        # leave no gaps): the whole [n, L] matrix is a reshape of the buffer
        length = int(lens[0])
        base = int(offsets[0])
        mat = values[base : base + n * length].reshape(n, length)
        if length >= size:
            return mat[:, :size]  # truncation: a strided view, still no copy
        out = np.zeros((n, size), np.uint8)
        out[:, :length] = mat
        return out
    lens = np.minimum(lens, size)  # truncation: only the first ``size`` bytes land
    out = np.zeros((n, size), np.uint8)
    total = int(lens.sum())
    if not total:
        return out
    if total <= n * _GATHER_MAX_MEAN_LEN:
        # ragged gather: for each row i, copy values[starts[i] : starts[i]+lens[i]]
        # into out[i, :lens[i]] — expressed as one flat src/dst index pair
        row_of = np.repeat(np.arange(n, dtype=np.int64), lens)
        within = np.arange(total, dtype=np.int64) - np.repeat(
            np.concatenate(([0], np.cumsum(lens[:-1]))), lens)
        out.reshape(-1)[row_of * size + within] = values[
            np.repeat(starts, lens) + within]
    else:
        for i in range(n):
            length = lens[i]
            start = starts[i]
            out[i, :length] = values[start : start + length]
    return out


def extract_tensor(batch: MessageBatch, field: str, name: str, dtype: str,
                   want: tuple, *, who: str) -> np.ndarray:
    """One column -> [B, *want] ndarray.

    - binary columns: raw bytes, zero-padded/truncated to prod(want) per
      row, reshaped; float32 targets are normalized from uint8 (images);
    - (nested) list columns: flattened fully and reshaped;
    - plain numeric columns: allowed only when want is scalar-compatible.
    """
    if not batch.has_column(field):
        raise ProcessError(f"{who}: column {field!r} not found for model input {name!r}")
    col = batch.column(field)
    n = batch.num_rows
    want = tuple(int(d) for d in want)
    if pa.types.is_binary(col.type) or pa.types.is_large_binary(col.type):
        size = int(np.prod(want))
        out = _binary_matrix(col, n, size).reshape(n, *want)
        if dtype == "float32":
            # uint8/f32 divides straight to float32 (identical values to
            # astype-then-divide) — skips a whole intermediate copy
            return out / np.float32(255.0)
        # copy=False keeps the uniform-payload case a true zero-copy view of
        # the Arrow buffer end to end (consumers only read model inputs)
        return out.astype(dtype, copy=False)
    if (pa.types.is_list(col.type) or pa.types.is_fixed_size_list(col.type)
            or pa.types.is_large_list(col.type)):
        flat = col.flatten()
        while isinstance(flat, (pa.ListArray, pa.LargeListArray,
                                pa.FixedSizeListArray)):
            flat = flat.flatten()
        try:
            # nullless numeric values come back as a zero-copy buffer view
            arr = flat.to_numpy(zero_copy_only=True)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            arr = flat.to_numpy(zero_copy_only=False)
        arr = arr.astype(dtype, copy=False)
        try:
            return arr.reshape(n, *want)
        except ValueError as e:
            raise ProcessError(
                f"{who}: column {field!r} does not reshape to {want} per row: {e}"
            ) from e
    arr = col.to_numpy(zero_copy_only=False).astype(dtype, copy=False)
    if want and int(np.prod(want)) != 1:
        raise ProcessError(
            f"{who}: column {field!r} is scalar per row but input {name!r} wants {want}"
        )
    return arr.reshape(n, *([1] * len(want)))
