"""Arrow column -> model-input ndarray extraction, shared by the device
processors (tpu_inference / tpu_train) so the list/binary/scalar handling
can't drift between them.

This is the host side of the infeed hot path, so every column kind has a
vectorized, allocation-lean implementation: binary payloads are gathered
straight out of the Arrow values buffer with offset arithmetic (one ragged
numpy gather builds the whole ``[B, prod(want)]`` matrix — no per-row
``as_py()``/``np.pad``/``np.stack``), and (nested) list columns reshape
zero-copy views of their flattened values.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from arkflow_tpu.batch import MessageBatch, binary_column_view
from arkflow_tpu.errors import ProcessError


#: below this mean payload size the flat fancy-index gather beats a per-row
#: slice-copy loop (index arithmetic amortizes; loop overhead dominates);
#: above it each row is one bulk memcpy and the loop wins (measured on the
#: 4096x784 image and 8192x20 sensor shapes)
_GATHER_MAX_MEAN_LEN = 128


def _binary_matrix(col: pa.Array, n: int, size: int) -> np.ndarray:
    """Binary column -> ``[n, size]`` uint8, zero-padded/truncated per row.

    Works on the Arrow buffers directly (no per-row ``as_py``/``np.pad``):

    - uniform row length (the image-payload case): the values buffer IS the
      matrix — one ``reshape`` view, zero copies (or one bulk memcpy when
      rows are shorter than ``size``);
    - ragged short rows: one flat fancy-index gather, O(total bytes);
    - ragged long rows: per-row numpy slice copies (bulk memcpy each).
    """
    values, offsets = binary_column_view(col)
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    if n == 0:
        return np.zeros((0, size), np.uint8)
    if col.null_count:
        # nulls read as empty payloads (matches the old ``as_py() or b""``)
        lens = np.where(col.is_null().to_numpy(zero_copy_only=False), 0, lens)
    elif lens.min() == lens.max():
        # uniform rows sit back-to-back in the values buffer (Arrow offsets
        # leave no gaps): the whole [n, L] matrix is a reshape of the buffer
        length = int(lens[0])
        base = int(offsets[0])
        mat = values[base : base + n * length].reshape(n, length)
        if length >= size:
            return mat[:, :size]  # truncation: a strided view, still no copy
        out = np.zeros((n, size), np.uint8)
        out[:, :length] = mat
        return out
    lens = np.minimum(lens, size)  # truncation: only the first ``size`` bytes land
    out = np.zeros((n, size), np.uint8)
    total = int(lens.sum())
    if not total:
        return out
    if total <= n * _GATHER_MAX_MEAN_LEN:
        # ragged gather: for each row i, copy values[starts[i] : starts[i]+lens[i]]
        # into out[i, :lens[i]] — expressed as one flat src/dst index pair
        row_of = np.repeat(np.arange(n, dtype=np.int64), lens)
        within = np.arange(total, dtype=np.int64) - np.repeat(
            np.concatenate(([0], np.cumsum(lens[:-1]))), lens)
        out.reshape(-1)[row_of * size + within] = values[
            np.repeat(starts, lens) + within]
    else:
        for i in range(n):
            length = lens[i]
            start = starts[i]
            out[i, :length] = values[start : start + length]
    return out


def extract_tensor(batch: MessageBatch, field: str, name: str, dtype: str,
                   want: tuple, *, who: str) -> np.ndarray:
    """One column -> [B, *want] ndarray.

    - binary columns: raw bytes, zero-padded/truncated to prod(want) per
      row, reshaped; float32 targets are normalized from uint8 (images);
    - (nested) list columns: flattened fully and reshaped;
    - plain numeric columns: allowed only when want is scalar-compatible.
    """
    if not batch.has_column(field):
        raise ProcessError(f"{who}: column {field!r} not found for model input {name!r}")
    col = batch.column(field)
    n = batch.num_rows
    want = tuple(int(d) for d in want)
    if pa.types.is_binary(col.type) or pa.types.is_large_binary(col.type):
        size = int(np.prod(want))
        out = _binary_matrix(col, n, size).reshape(n, *want)
        if dtype == "float32":
            # uint8/f32 divides straight to float32 (identical values to
            # astype-then-divide) — skips a whole intermediate copy
            return out / np.float32(255.0)
        # copy=False keeps the uniform-payload case a true zero-copy view of
        # the Arrow buffer end to end (consumers only read model inputs)
        return out.astype(dtype, copy=False)
    if (pa.types.is_list(col.type) or pa.types.is_fixed_size_list(col.type)
            or pa.types.is_large_list(col.type)):
        flat = col.flatten()
        while isinstance(flat, (pa.ListArray, pa.LargeListArray,
                                pa.FixedSizeListArray)):
            flat = flat.flatten()
        try:
            # nullless numeric values come back as a zero-copy buffer view
            arr = flat.to_numpy(zero_copy_only=True)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            arr = flat.to_numpy(zero_copy_only=False)
        arr = arr.astype(dtype, copy=False)
        try:
            return arr.reshape(n, *want)
        except ValueError as e:
            raise ProcessError(
                f"{who}: column {field!r} does not reshape to {want} per row: {e}"
            ) from e
    arr = col.to_numpy(zero_copy_only=False).astype(dtype, copy=False)
    if want and int(np.prod(want)) != 1:
        raise ProcessError(
            f"{who}: column {field!r} is scalar per row but input {name!r} wants {want}"
        )
    return arr.reshape(n, *([1] * len(want)))
