"""Arrow column -> model-input ndarray extraction, shared by the device
processors (tpu_inference / tpu_train) so the list/binary/scalar handling
can't drift between them."""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from arkflow_tpu.batch import MessageBatch
from arkflow_tpu.errors import ProcessError


def extract_tensor(batch: MessageBatch, field: str, name: str, dtype: str,
                   want: tuple, *, who: str) -> np.ndarray:
    """One column -> [B, *want] ndarray.

    - binary columns: raw bytes, zero-padded/truncated to prod(want) per
      row, reshaped; float32 targets are normalized from uint8 (images);
    - (nested) list columns: flattened fully and reshaped;
    - plain numeric columns: allowed only when want is scalar-compatible.
    """
    if not batch.has_column(field):
        raise ProcessError(f"{who}: column {field!r} not found for model input {name!r}")
    col = batch.column(field)
    n = batch.num_rows
    want = tuple(int(d) for d in want)
    if pa.types.is_binary(col.type) or pa.types.is_large_binary(col.type):
        size = int(np.prod(want))
        rows = []
        for v in col:
            buf = v.as_py() or b""
            arr = np.frombuffer(buf, dtype=np.uint8)
            if arr.size < size:
                arr = np.pad(arr, (0, size - arr.size))
            rows.append(arr[:size].reshape(want).astype(dtype))
        out = np.stack(rows) if rows else np.zeros((0, *want), dtype)
        if dtype == "float32":
            out = out / np.float32(255.0)
        return out
    if (pa.types.is_list(col.type) or pa.types.is_fixed_size_list(col.type)
            or pa.types.is_large_list(col.type)):
        flat = col.flatten()
        while isinstance(flat, (pa.ListArray, pa.LargeListArray,
                                pa.FixedSizeListArray)):
            flat = flat.flatten()
        arr = flat.to_numpy(zero_copy_only=False).astype(dtype)
        try:
            return arr.reshape(n, *want)
        except ValueError as e:
            raise ProcessError(
                f"{who}: column {field!r} does not reshape to {want} per row: {e}"
            ) from e
    arr = col.to_numpy(zero_copy_only=False).astype(dtype)
    if want and int(np.prod(want)) != 1:
        raise ProcessError(
            f"{who}: column {field!r} is scalar per row but input {name!r} wants {want}"
        )
    return arr.reshape(n, *([1] * len(want)))
