"""Shared serving-runner core: the substrate every device-serving path sits on.

PRs 4-7 grew the ``tpu_inference`` runner a self-healing layer — health state
machine, step-deadline watchdog on abandonable threads, jit-rebuild
scheduling after an incident, chaos fault hooks, and the ``/health`` report
surface. All of it lived inside ``ModelRunner``, so the generation path
(``tpu/serving.py``) had none of it. This module extracts that layer into a
``ServingRunnerCore`` both the batch runner and the continuous-batching
``GenerationServer`` compose:

- **health**: a ``RunnerHealth`` state machine + the admission gates
  (``heal_gate`` / ``heal_gate_sync``) that wait out probe backoff, claim the
  recovery probe, and run a scheduled rebuild before the probe step.
- **deadlines**: ``run_deadlined`` / ``run_deadlined_sync`` execute one
  blocking device step on a borrowed dedicated watchdog thread and abandon it
  on a miss (the wedged thread goes with its discarded executor — never the
  shared default executor). A miss counts, marks UNHEALTHY, schedules a
  rebuild, and raises ``StepDeadlineExceeded`` so the batch NACKS for
  redelivery.
- **dispatch bookkeeping**: ``note_external_failure`` is the health marking a
  dispatcher (the device pool, or any future multi-runner front) applies to a
  member step that raised — shared policy instead of pool-local knowledge.
- **chaos**: ``inject_step_fault``/``apply_chaos`` arm one-shot hang/oom
  faults consumed inside the next step (the fault plugin's processor wrapper
  drives this through the owner's ``runner`` attribute).

The owner supplies ``rebuild_fn`` — how to distrust cached executables after
a hang (the runner rebuilds its jitted step and clears seen shapes; the
generation server rebuilds its four jitted steps).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Mapping, Optional

from arkflow_tpu.errors import ConfigError, RunnerDead, StepDeadlineExceeded
from arkflow_tpu.obs import global_registry
from arkflow_tpu.tpu.health import CORRUPT as HEALTH_CORRUPT
from arkflow_tpu.tpu.health import DEAD as HEALTH_DEAD
from arkflow_tpu.tpu.health import HealthConfig, RunnerHealth

logger = logging.getLogger("arkflow.tpu")

#: an unseen shape compiles before it executes; the watchdog scales the step
#: deadline by this factor unless ``step_deadline_first`` pins an absolute
#: budget for first-compile steps
FIRST_COMPILE_DEADLINE_SCALE = 10.0


class InjectedOom(RuntimeError):
    """Chaos-injected device OOM (``inject_step_fault('oom')``): carries the
    RESOURCE_EXHAUSTED signature so it walks the real degradation path."""

    def __init__(self, msg: str = "RESOURCE_EXHAUSTED: chaos: injected device OOM"):
        super().__init__(msg)


#: substrings identifying an XLA allocation failure across backends/versions
_OOM_SIGNATURES = ("resource_exhausted", "resource exhausted", "out of memory", "oom")


def is_oom_error(e: BaseException) -> bool:
    """Device allocation failure? Matched on the message because jaxlib's
    ``XlaRuntimeError`` carries the gRPC status only as text (and the chaos
    layer fabricates the same signature). Word-boundary match: a bare
    substring test would classify any message containing e.g. "boom" as an
    OOM and route it into the degradation path."""
    if isinstance(e, InjectedOom):
        return True
    if isinstance(e, MemoryError):
        return True
    import re

    msg = str(e).lower()
    return any(re.search(rf"\b{re.escape(sig)}\b", msg) for sig in _OOM_SIGNATURES)


def on_tpu_backend(devices=None) -> bool:
    """Is the (first) execution device a TPU? The one backend probe the
    auto-resolved fast paths share (runner auto-flash, serving auto decode
    kernel) — a device_kind fix lands once, not per copy."""
    try:
        import jax

        dev = devices[0] if devices else jax.devices()[0]
        return (dev.platform == "tpu"
                or "tpu" in getattr(dev, "device_kind", "").lower())
    except Exception:
        return False


def parse_core_config(config: Mapping[str, Any]) -> dict:
    """Parse the shared self-healing keys a device processor config carries
    (``step_deadline`` / ``step_deadline_first`` / ``health``) into the
    kwargs ``ServingRunnerCore`` (and the runners that wrap it) accept.
    Shared by the ``tpu_inference`` and ``tpu_generate`` builders so both
    paths read the same knobs the same way."""
    from arkflow_tpu.utils.duration import parse_duration

    step_deadline = config.get("step_deadline")
    step_deadline_first = config.get("step_deadline_first")
    return dict(
        step_deadline_s=(parse_duration(step_deadline)
                         if step_deadline is not None else None),
        step_deadline_first_s=(parse_duration(step_deadline_first)
                               if step_deadline_first is not None else None),
        health_config=HealthConfig.from_config(config.get("health")),
    )


class ServingRunnerCore:
    """Health + deadline + chaos + rebuild substrate for one serving runner.

    Thread-safe where it must be: deadline misses arrive from executor
    threads and the event loop alike, watchdog executors are borrowed under a
    lock, and the rebuild flag is double-checked.
    """

    def __init__(
        self,
        *,
        name: str,
        labels: Optional[dict[str, str]] = None,
        step_deadline_s: Optional[float] = None,
        step_deadline_first_s: Optional[float] = None,
        health_config: Optional[HealthConfig] = None,
        rebuild_fn: Optional[Callable[[], None]] = None,
    ):
        if step_deadline_s is not None and step_deadline_s <= 0:
            raise ConfigError(f"step_deadline must be positive, got {step_deadline_s}")
        if step_deadline_first_s is not None and step_deadline_first_s <= 0:
            raise ConfigError(
                f"step_deadline_first must be positive, got {step_deadline_first_s}")
        self.name = name
        self.step_deadline_s = step_deadline_s
        #: first-compile steps trace + compile before executing; they get
        #: their own (much larger) budget so a cold bucket isn't misread as a
        #: hung device
        self.step_deadline_first_s = (
            step_deadline_first_s
            if step_deadline_first_s is not None
            else (step_deadline_s * FIRST_COMPILE_DEADLINE_SCALE
                  if step_deadline_s is not None else None))
        #: how the owner distrusts cached executables after a hang
        self.rebuild_fn = rebuild_fn

        reg = global_registry()
        self.health = RunnerHealth(
            health_config,
            gauge=reg.gauge(
                "arkflow_tpu_runner_health",
                "runner health state (0 healthy, 1 degraded, 2 unhealthy, 3 dead)",
                labels),
            name=name)
        self.m_deadline_miss = reg.counter(
            "arkflow_tpu_step_deadline_misses",
            "device steps abandoned after exceeding step_deadline", labels)
        self.m_rebuilds = reg.counter(
            "arkflow_tpu_runner_rebuilds_total",
            "jitted-step rebuilds after a deadline miss", labels)

        #: armed chaos faults consumed by the next device steps (fault plugin)
        self._chaos: deque = deque()
        #: persistent silent-data-corruption fault (``inject_step_fault('sdc')``):
        #: unlike the one-shot hang/oom, corruption keeps corrupting every
        #: step until the integrity repair path clears it
        self.sdc_armed = False
        #: set on a deadline miss: the jitted step(s) are rebuilt before the
        #: next dispatch (stale executables on a wedged device aren't trusted)
        self._needs_rebuild = False
        self._rebuild_lock = threading.Lock()
        #: recycled single-thread watchdog executors for deadlined steps —
        #: NEVER the shared default executor: an abandoned (hung) step would
        #: wedge a thread everyone else needs. A miss discards the executor
        #: with its wedged thread; the no-miss path reuses them.
        self._watchdog_free: list = []
        self._watchdog_lock = threading.Lock()

    # -- chaos hook ---------------------------------------------------------

    def inject_step_fault(self, kind: str, duration_s: float = 0.0) -> None:
        """Arm a fault on the device-step path: ``hang`` wedges the next step
        for ``duration_s`` of dead time (as a stuck device sync would) so the
        deadline watchdog fires; ``oom`` raises a fabricated
        RESOURCE_EXHAUSTED on the next step so the degradation path runs;
        ``sdc`` arms PERSISTENT silent data corruption — every step's float
        outputs are perturbed until the integrity repair path clears it
        (``clear_sdc``), because a corrupting chip doesn't stop after one
        wrong answer. ``bitflip`` is owner-level (it mutates the param tree,
        which the core doesn't hold) — runners intercept it before
        delegating here."""
        if kind == "sdc":
            self.sdc_armed = True
            return
        if kind not in ("hang", "oom"):
            raise ConfigError(f"unknown step fault kind {kind!r} (hang/oom/sdc)")
        self._chaos.append((kind, float(duration_s)))

    def apply_chaos(self) -> None:
        """Executor-thread side of ``inject_step_fault``."""
        try:
            kind, duration_s = self._chaos.popleft()
        except IndexError:
            return
        if kind == "hang":
            time.sleep(duration_s if duration_s > 0 else 30.0)
        else:
            raise InjectedOom()

    def corrupt_outputs(self, out):
        """Apply the armed ``sdc`` fault to fetched step outputs (executor
        thread): float arrays (logits and their kin) are negated so every
        downstream argmax flips, and integer arrays (device-computed labels
        / token ids — already argmaxed BEFORE this host-side hook could
        touch their logits) are shifted by one — wrong answers that look
        structurally healthy, which is exactly what the golden probe exists
        to catch. Identity when no fault is armed."""
        if not self.sdc_armed:
            return out
        import jax.numpy as jnp
        import numpy as np

        def _garble(v):
            arr = np.asarray(v)
            if arr.ndim < 1:
                return v
            # jnp.issubdtype: bfloat16 (ml_dtypes, numpy kind 'V') must
            # count as float — bf16 logits are the common serving case
            if jnp.issubdtype(arr.dtype, jnp.floating):
                return -arr
            if jnp.issubdtype(arr.dtype, jnp.integer):
                return arr + 1
            return v

        if isinstance(out, dict):
            return {k: _garble(v) for k, v in out.items()}
        import jax

        return jax.tree_util.tree_map(_garble, out)

    def clear_sdc(self) -> None:
        """Integrity-repair side: the corrupting 'hardware' was replaced."""
        self.sdc_armed = False

    # -- deadlines ----------------------------------------------------------

    def deadline_for(self, first_compile: bool) -> Optional[float]:
        """Per-step watchdog budget; first-compile shapes get the scaled-up
        budget so a cold bucket isn't misread as a hung device."""
        if self.step_deadline_s is None:
            return None
        return self.step_deadline_first_s if first_compile else self.step_deadline_s

    @staticmethod
    def deadline_remaining(deadline_s: float, dispatched_at: float,
                           *, floor: float = 0.05) -> float:
        """Watchdog budget left for an ALREADY-DISPATCHED step (pipelined
        dispatch, ``dispatch_depth`` > 1): each in-flight step's deadline
        runs from the moment IT was enqueued on the device, not from when
        the host gets around to fetching its outputs — otherwise a hung
        step N would silently spend step N+1's budget too, and a miss
        would be detected one full step late. Floored so host bookkeeping
        jitter between dispatch and fetch can never turn an on-time step
        into a spurious zero-budget miss."""
        return max(deadline_s - (time.monotonic() - dispatched_at), floor)

    def _borrow_watchdog(self):
        """A single-thread executor for one deadlined step: reused across
        steps in the no-miss steady state, discarded (with its wedged
        thread) on a miss. Concurrent steps each borrow their own, so the
        watchdog never serializes in-flight work."""
        import concurrent.futures

        with self._watchdog_lock:
            if self._watchdog_free:
                return self._watchdog_free.pop()
        return concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="arkflow-step-watchdog")

    def _return_watchdog(self, ex) -> None:
        with self._watchdog_lock:
            self._watchdog_free.append(ex)

    def _deadline_miss(self, fut, deadline: float,
                       on_zombie: Optional[Callable[[], None]]) -> StepDeadlineExceeded:
        """Bookkeeping for an abandoned step: count the miss, mark the runner
        UNHEALTHY (recovery probes re-admit it), schedule a rebuild, and wire
        the zombie future so its eventual exception is retrieved — and the
        owner's cleanup (``on_zombie``, e.g. staging-buffer recycling) runs —
        whenever the wedged step finally ends."""
        self.m_deadline_miss.inc()
        self.schedule_rebuild()
        self.health.mark_unhealthy(f"step exceeded its {deadline:.3g}s deadline")

        def _reap(f) -> None:
            try:
                f.exception()
            except Exception:
                pass
            if on_zombie is not None:
                on_zombie()

        fut.add_done_callback(_reap)
        return StepDeadlineExceeded(
            f"device step exceeded its {deadline:.3g}s deadline "
            "(runner marked unhealthy; batch nacked for redelivery)")

    def run_deadlined_sync(self, fn: Callable[[], Any], deadline: float,
                           on_zombie: Optional[Callable[[], None]] = None):
        """Run ``fn`` on a dedicated watchdog thread so a hang can be
        abandoned (the thread itself cannot be killed — its executor is
        dropped and the thread left to finish or leak; the shared default
        executor is never at risk)."""
        import concurrent.futures

        ex = self._borrow_watchdog()
        fut = ex.submit(fn)
        try:
            out = fut.result(timeout=deadline)
        except concurrent.futures.TimeoutError:
            ex.shutdown(wait=False)  # abandon: the wedged thread goes with it
            raise self._deadline_miss(fut, deadline, on_zombie) from None
        except Exception:
            self._return_watchdog(ex)  # step ended: its thread is idle again
            raise
        self._return_watchdog(ex)
        return out

    async def run_deadlined(self, fn: Callable[[], Any], deadline: float,
                            on_zombie: Optional[Callable[[], None]] = None):
        """Async twin: wait for the step, not forever, on a borrowed
        DEDICATED thread. On a miss the thread cannot be interrupted: its
        executor is dropped with it and the miss handler reaps the step's
        eventual result."""
        loop = asyncio.get_running_loop()
        ex = self._borrow_watchdog()
        cfut = ex.submit(fn)
        fut = asyncio.wrap_future(cfut, loop=loop)
        done, _ = await asyncio.wait({fut}, timeout=deadline)
        if not done:
            ex.shutdown(wait=False)
            raise self._deadline_miss(cfut, deadline, on_zombie)
        self._return_watchdog(ex)  # step ended; thread idle
        return fut.result()

    # -- rebuild scheduling -------------------------------------------------

    def schedule_rebuild(self) -> None:
        self._needs_rebuild = True

    def rebuild_if_needed(self) -> None:
        """Run the owner's rebuild after a deadline miss: executables cached
        across a device hang are not trusted, so the next (probe) step
        recompiles from scratch. Double-checked so concurrent probes rebuild
        once."""
        if not self._needs_rebuild or self.rebuild_fn is None:
            return
        with self._rebuild_lock:
            if not self._needs_rebuild:
                return
            self._needs_rebuild = False
            self.rebuild_fn()
        self.m_rebuilds.inc()

    # -- admission gates ----------------------------------------------------

    def heal_gate_sync(self) -> None:
        """Admission control for the runner's own callers (pool dispatch has
        its own health-aware pick): DEAD fails fast; UNHEALTHY waits out the
        probe backoff, claims the probe, and rebuilds if needed — the step
        that follows IS the recovery probe."""
        h = self.health
        while True:
            if h.state == HEALTH_DEAD:
                raise RunnerDead(f"runner {h.name} is DEAD; not serving")
            if h.state == HEALTH_CORRUPT:
                raise RunnerDead(
                    f"runner {h.name} is quarantined (CORRUPT) pending "
                    "integrity repair; not serving")
            if h.join_or_begin_probe():
                break
            time.sleep(min(max(h.seconds_until_probe(), 0.01), 0.5))
        self.rebuild_if_needed()

    async def heal_gate(self) -> None:
        """Async twin of ``heal_gate_sync`` (never blocks the event loop)."""
        h = self.health
        while True:
            if h.state == HEALTH_DEAD:
                raise RunnerDead(f"runner {h.name} is DEAD; not serving")
            if h.state == HEALTH_CORRUPT:
                raise RunnerDead(
                    f"runner {h.name} is quarantined (CORRUPT) pending "
                    "integrity repair; not serving")
            if h.join_or_begin_probe():
                break
            await asyncio.sleep(min(max(h.seconds_until_probe(), 0.01), 0.5))
        self.rebuild_if_needed()

    # -- dispatcher-side bookkeeping ----------------------------------------

    def note_external_failure(self, e: Exception) -> None:
        """Health bookkeeping a DISPATCHER applies to a step that raised.
        Deadline misses and OOMs self-mark inside the step (which also
        releases a probe claim); anything else — a raw XLA fault, a generic
        probe failure — must mark HERE, unconditionally: ``mark_unhealthy``
        both stops dispatch feeding the chip and clears the probing flag, so
        a FAILED probe re-arms its backoff instead of fencing the member
        forever."""
        if isinstance(e, (StepDeadlineExceeded, RunnerDead)) or is_oom_error(e):
            return
        self.health.mark_unhealthy(f"step failed: {e}")

    # -- /health surface ----------------------------------------------------

    def health_report(self) -> dict:
        """JSON-able snapshot for the engine's ``/health`` endpoint; owners
        extend it with their own serving detail."""
        rep = self.health.report()
        rep["deadline_misses"] = int(self.m_deadline_miss.value)
        if self.sdc_armed:
            rep["sdc_armed"] = True
        return rep
