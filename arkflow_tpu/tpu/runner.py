"""ModelRunner: the XLA execution provider for streaming inference.

This is the TPU-native replacement for the reference's PyO3 Python-processor
slot (ref: crates/arkflow-plugin/src/processor/python.rs; SURVEY.md section
3.4): same pipeline position, but batch -> pad-to-bucket -> XLA-compiled
model -> unpad -> batch.

Responsibilities:
- Resolve a model family + config, init or restore params.
- Optionally shard params over a ``Mesh`` (tensor parallel serving). With a
  ``dp`` axis the dispatch is data-parallel for real: inputs/outputs carry
  explicit ``NamedSharding``s splitting the batch dim over dp, buckets scale
  by dp so per-chip shards stay bucket-exact, and the single-device wins
  (eager sharded prefetch, input donation) stay enabled under the mesh.
- Keep one compiled executable per (batch, seq) bucket warm; ``jax.jit``
  owns the cache, ``warmup()`` precompiles the bucket grid so steady-state
  never hits a compile.
- Run inference off the event loop (``asyncio`` executor) so device sync
  never stalls the stream's other stages.
- Keep the device pipeline full (SURVEY.md section 7.5): ``infer()`` splits
  host prep (pad, off-loop) from the non-blocking XLA dispatch, and bounds
  in-flight device steps with a semaphore — with >=2 stream workers, step
  n+1's infeed/dispatch overlaps step n's compute (double buffering), and
  duty-cycle / infeed-stall metrics report how full the device stayed.
"""

from __future__ import annotations

import asyncio
import logging
import os
from functools import partial
from typing import Any, Optional

import jax
import numpy as np

from arkflow_tpu.errors import ConfigError, RunnerDead, StepDeadlineExceeded
from arkflow_tpu.models import get_model
from arkflow_tpu.obs import global_registry
from arkflow_tpu.obs.trace import record_stage
from arkflow_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    create_mesh,
    dp_size,
    param_shardings,
    shard_params,
)
from arkflow_tpu.tpu.bucketing import BucketPolicy, bucket_cap_bus, pad_batch_dim, pad_seq_dim
from arkflow_tpu.tpu.health import HealthConfig
# the self-healing substrate (health gates, deadline watchdog, chaos hooks)
# lives in the shared serving core now; these re-exports keep the historical
# import surface (tests, fault plugin) stable
from arkflow_tpu.tpu.serving_core import (  # noqa: F401  (re-exported)
    FIRST_COMPILE_DEADLINE_SCALE,
    InjectedOom,
    ServingRunnerCore,
    is_oom_error,
)

logger = logging.getLogger("arkflow.tpu")


def _env_int(name: str, default: int, minimum: Optional[int] = None) -> int:
    """Tolerant int env knob: malformed or out-of-range values log a warning
    and fall back to the default (like the ARKFLOW_FLASH kill switch, a bad
    env value must not crash runner setup; explicit config values DO raise)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        logger.warning("%s=%r is not an int; using %d", name, raw, default)
        return default
    if minimum is not None and val < minimum:
        logger.warning("%s=%d is below %d; using %d", name, val, minimum, default)
        return default
    return val


def _env_flash_floor(default: int = 128) -> int:
    return _env_int("ARKFLOW_FLASH_MIN_SEQ", default)


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


def convert_for_serving(params, serving_dtype: Optional[str], family_name: str = ""):
    """Cast/quantize a host param tree for the serving dtype.

    - ``int8``: W8A8 dynamic quantization — dense weights to per-channel int8
      (doubles the MXU roofline vs bf16), everything else to bf16.
    - ``bfloat16``/``float16``: full-tree float cast — halves param HBM +
      host->device transfer and keeps matmuls on the MXU's native dtype;
      logits/softmax layers still accumulate/cast to f32 inside the model.

    Shared by ``ModelRunner`` and the device pool, which converts ONCE and
    hands the result to N members (the walk over a large checkpoint is the
    expensive part, not the per-member device transfer)."""
    if serving_dtype == "int8":
        from arkflow_tpu.models.quantize import quantize_for_serving

        params, n_q = quantize_for_serving(params)
        logger.info("[%s] int8 serving: %d dense layers quantized",
                    family_name, n_q)
    elif serving_dtype and serving_dtype != "float32":
        import jax.numpy as jnp

        target = getattr(jnp, serving_dtype)
        params = jax.tree_util.tree_map(
            lambda a: a.astype(target)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            params,
        )
    return params


def init_host_params(family, cfg, seed: int, checkpoint: Optional[str] = None):
    """Init (and optionally restore) a param tree on host CPU — op-by-op init
    over a remote-TPU tunnel is pathological, so the tree is built locally
    and transferred to the execution device(s) in one hop. Shared by
    ``ModelRunner`` and the device pool (which inits once for N members)."""
    try:
        # local_devices, not devices: under multi-host ``jax.distributed``
        # the global list leads with process 0's device, and pinning an
        # eager init op to a non-addressable device is a hard error.
        cpus = jax.local_devices(backend="cpu")
        cpu = cpus[0] if cpus else None
    except RuntimeError:
        cpu = None
    with jax.default_device(cpu) if cpu is not None else _nullcontext():
        params = family.init(jax.random.PRNGKey(seed), cfg)
    if checkpoint:
        from arkflow_tpu.tpu.checkpoint import restore

        try:
            params = restore(checkpoint, params)
            logger.info("restored checkpoint from %s", checkpoint)
        except ConfigError:
            raise
        except Exception as e:
            raise ConfigError(
                f"failed to restore checkpoint {checkpoint!r}: {e}") from e
    return params


class _StagingPool:
    """Recycled host-side staging buffers, keyed by padded shape signature.

    ``np.pad`` allocates a fresh bucket-sized array per input per step; in
    steady state every step lands in an already-seen bucket, so the padded
    arrays are recycled instead — zero fresh allocations on the hot path.
    Buffers are checked out during prep and returned only after the step
    fully completes (outputs fetched), so on backends where ``device_put``
    may alias host memory a recycled buffer can never race an in-flight
    transfer. Thread-safe: prep runs on executor threads.

    Sizing invariant: ``max_per_key`` must cover every buffer set that can
    be simultaneously checked out on one key — the dispatched-not-fetched
    steps (``dispatch_depth`` of them at depth > 1, in-flight steps
    otherwise) plus one set in prep. The pool itself can NEVER deadlock —
    ``acquire`` returns None on an empty stack and the caller allocates
    fresh — but an undersized cap silently reintroduces a per-step
    allocation on the hot path (release drops buffers beyond the cap), so
    the runner asserts the derived size at construction instead of finding
    out from an allocation profile.
    """

    def __init__(self, max_per_key: int, min_required: int = 1):
        import threading

        # ``min_required`` is the owner's statement of how many sets can be
        # simultaneously checked out on one key (in-flight steps + one in
        # prep). The assert relates the CAP to that bound, so a future
        # change to the sizing formula that forgets the dispatch-depth term
        # fails here at construction instead of silently regressing the hot
        # path to one fresh bucket-sized allocation per step (release()
        # drops buffers beyond the cap; acquire() never blocks).
        assert max_per_key >= min_required >= 1, (
            f"staging max_per_key={max_per_key} cannot cover the "
            f"{min_required} concurrently-held buffer sets per key")
        self._free: dict[tuple, list[dict[str, np.ndarray]]] = {}
        self._max = max_per_key
        self._lock = threading.Lock()

    def acquire(self, key: tuple) -> Optional[dict[str, np.ndarray]]:
        with self._lock:
            stack = self._free.get(key)
            return stack.pop() if stack else None

    def release(self, key: tuple, bufs: dict[str, np.ndarray]) -> None:
        with self._lock:
            stack = self._free.setdefault(key, [])
            if len(stack) < self._max:
                stack.append(bufs)


class ModelRunner:
    def __init__(
        self,
        model: str,
        model_config: Optional[dict] = None,
        *,
        buckets: Optional[BucketPolicy] = None,
        mesh_spec: Optional[MeshSpec] = None,
        checkpoint: Optional[str] = None,
        seed: int = 0,
        devices=None,
        serving_dtype: Optional[str] = None,
        max_in_flight: Optional[int] = None,
        dispatch_depth: Optional[int] = None,
        packed: bool = False,
        host_params=None,
        device_label: Optional[str] = None,
        step_deadline_s: Optional[float] = None,
        step_deadline_first_s: Optional[float] = None,
        health_config: Optional[HealthConfig] = None,
        pp_microbatch_rows: Optional[int] = None,
        pp_layer_costs: Optional[list] = None,
    ):
        from arkflow_tpu.tpu.jaxcache import enable_persistent_cache

        enable_persistent_cache()
        self.family = get_model(model)
        self.cfg = self.family.make_config(**(model_config or {}))
        raw_flash = getattr(self.cfg, "use_flash_attention", False)
        self.cfg = self._resolve_auto_flags(self.cfg, devices, mesh_spec,
                                            packed=packed)
        #: flash explicitly requested in user config (never mutated): only
        #: then does an unservable mask raise; auto-chosen flash falls back
        #: to XLA instead of failing the stream. Immutable so concurrent
        #: _prep threads can't race a fallback into a spurious raise.
        self._flash_user_forced = raw_flash is True
        import threading

        self._flash_lock = threading.Lock()
        self.buckets = buckets or BucketPolicy()
        self.packed = packed
        if packed:
            # packed execution (tpu/packing.py): the family must publish a
            # packed apply + its input spec; rows carry several examples, so
            # flops/row tracks real token count instead of bucket padding
            extras = self.family.extras or {}
            if "apply_packed" not in extras:
                raise ConfigError(
                    f"model {model!r} has no packed execution support "
                    "(family extras lack apply_packed/packed_input_spec)")
            self.spec = extras["packed_input_spec"](self.cfg)
        else:
            self.spec = self.family.input_spec(self.cfg)
        if serving_dtype not in (None, "float32", "bfloat16", "float16", "int8"):
            raise ConfigError(
                f"serving_dtype {serving_dtype!r} invalid "
                "(float32/bfloat16/float16/int8)")
        self.serving_dtype = serving_dtype

        if host_params is not None:
            # shared host tree (device pool): the pool inits/restores AND
            # dtype-converts once; every member transfers the SAME finished
            # weights to its own chip — replication by construction, and no
            # N-fold init or full-tree cast/quantize walks
            params = host_params
        else:
            params = convert_for_serving(
                init_host_params(self.family, self.cfg, seed, checkpoint),
                self.serving_dtype, self.family.name)

        #: retained CONVERTED host tree — the known-good repair source the
        #: integrity plane (tpu/integrity.py) re-adopts from when a member
        #: is quarantined, and the reference tree its golden signature is
        #: computed against. Pool members share ONE tree (the pool passes
        #: ``host_params`` in), so retention costs one host copy per model,
        #: not per chip. Captured BEFORE placement: the pp path repacks the
        #: layer stack below, and ``place_params`` knows how to redo that.
        self.host_params = params

        self.mesh = None
        self._device = None
        self._input_sharding = None
        #: PartitionSpecs the params were placed with (None off-mesh) — kept
        #: so a hot-swap (tpu/swap.py) can place a candidate tree EXACTLY
        #: like the original, including the int8 spec rewrite
        self._pspecs = None
        #: pipelined-parallel serving state (mesh {pp: N}): the profiled
        #: stage plan, the microbatch row count the GPipe schedule streams,
        #: and the per-seq-bucket measured tick time the bubble gauge uses
        self._pp_plan = None
        self._pp_mb_rows = 0
        self._pp_tick_s: dict[int, float] = {}
        self._pp_tick_pending: set[int] = set()
        axes: dict[str, str] = {}
        if mesh_spec is not None and mesh_spec.pp > 1:
            params = self._init_pp(mesh_spec, params, devices,
                                   pp_microbatch_rows, pp_layer_costs)
            platform = next(iter(self.mesh.devices.flat)).platform
        elif mesh_spec is not None and mesh_spec.num_devices > 1:
            self.mesh = create_mesh(mesh_spec, devices=devices)
            axes = {name: name for name in self.mesh.axis_names}
            pspecs = self.family.param_specs(self.cfg, axes) if self.family.param_specs else None
            if pspecs is not None and self.serving_dtype == "int8":
                # int8 params carry {"w_q","w_scale"} where the float tree had
                # {"w"}; rewrite the spec tree the same way so tp/ep layouts
                # (and the doubled int8 MXU roofline) survive quantization
                from arkflow_tpu.models.quantize import quantize_param_specs

                pspecs = quantize_param_specs(pspecs)
            self._pspecs = pspecs
            params = shard_params(params, pspecs, self.mesh)
            # dp-sharded dispatch: the batch dim splits over the dp axis, so
            # every GLOBAL bucket scales by dp — per-chip shards stay exactly
            # on the configured bucket grid, and divisibility is structural
            self.buckets = self.buckets.dp_scaled(dp_size(self.mesh))
            self._input_sharding = batch_sharding(self.mesh)
            platform = next(iter(self.mesh.devices.flat)).platform
        else:
            target = (devices[0] if devices else jax.devices()[0])
            params = jax.device_put(params, target)
            self._device = target
            platform = target.platform
        self.params = params
        #: per-leaf blake2b baseline (tpu/integrity.py); None = not yet
        #: baselined, or invalidated by ``adopt_params`` — the integrity
        #: monitor recomputes it lazily off-path at its next digest pass
        #: (right after the adopt is the known-good moment)
        self.param_digests: Optional[dict[str, str]] = None
        self._axes = axes
        #: donate padded inputs to the jitted call so XLA reuses their HBM
        #: for outputs (input-output aliasing) — under a mesh the sharded
        #: input buffers donate per-chip the same way. Accelerator-only: the
        #: CPU backend has no donation and would warn per compile.
        #: ARKFLOW_DONATE=0 is the operator kill switch.
        self._donate = (
            platform in ("tpu", "gpu")
            and os.environ.get("ARKFLOW_DONATE", "1") != "0"
        )
        #: eager host->device prefetch (see _to_device): accelerator-only —
        #: on the CPU backend there is no transfer/compute overlap to win,
        #: only an extra executor hop per step. Under a mesh the prefetch is
        #: a sharded device_put (each chip receives only its dp shard).
        #: ARKFLOW_PREFETCH=1/0 forces.
        prefetch_env = os.environ.get("ARKFLOW_PREFETCH")
        self._prefetch = (
            prefetch_env != "0"
            and (platform in ("tpu", "gpu") or prefetch_env == "1")
        )

        if getattr(self.cfg, "use_ring_attention", False) and "sp" not in axes:
            raise ConfigError(
                "use_ring_attention requires a mesh with an 'sp' axis "
                "(set mesh: {sp: N} on the processor)"
            )
        self._build_jitted()

        reg = global_registry()
        # packed runners get their own metric family: fill/padding have
        # different semantics (token fill vs row fill), and sharing a
        # reservoir with an unpacked runner would mix the distributions.
        # Device-pool members add a ``device`` label so duty-cycle / stall /
        # throughput read PER CHIP instead of summing the pool into one line.
        labels = {"model": model, **({"packed": "1"} if packed else {}),
                  **({"device": device_label} if device_label is not None else {})}
        self.m_infer = reg.histogram("arkflow_tpu_infer_seconds", "device step latency", labels)
        self.m_rows = reg.counter("arkflow_tpu_rows_total", "rows inferred", labels)
        self.m_pad = reg.counter("arkflow_tpu_pad_rows_total", "padding rows (waste)", labels)
        self.m_fill = reg.histogram(
            "arkflow_tpu_batch_fill_ratio", "true rows / bucket rows", labels,
            buckets=[0.125, 0.25, 0.5, 0.75, 0.9, 1.0],
        )
        self.m_compiles = reg.counter("arkflow_tpu_compiles_total", "bucket compiles", labels)
        self.m_warm_compiles = reg.counter(
            "arkflow_tpu_warm_compiles_total",
            "bucket executables compiled OFF the serving path (shape-tuner "
            "warm/probe; compiles_total stays flat across a tuned flip)", labels)
        self.m_exec_rows = reg.counter(
            "arkflow_tpu_exec_rows_total",
            "bucket rows dispatched to the device, padding included (the "
            "honest FLOPs denominator; rows_total counts true examples)", labels)
        self.m_tokens = reg.counter(
            "arkflow_tpu_tokens_total",
            "true (non-padding) tokens dispatched — packed runners and "
            "unpacked token models (attention-mask sum) alike; the "
            "numerator of effective tokens/sec", labels)
        self.m_token_capacity = reg.counter(
            "arkflow_tpu_token_capacity_total",
            "token slots dispatched (bucket rows x padded seq): "
            "1 - tokens_total/capacity is the capacity-weighted padding "
            "waste INCLUDING seq padding — the honest aggregate; the "
            "per-step waste histogram over-weights small tail windows and "
            "reads row fill only for unpacked runners", labels)
        self.m_inflight = reg.gauge(
            "arkflow_tpu_steps_inflight", "device steps dispatched, not yet complete", labels)
        self.m_busy_s = reg.counter(
            "arkflow_tpu_device_busy_seconds_total",
            "wall seconds with >=1 step in flight (duty-cycle numerator)", labels)
        self.m_stall_s = reg.counter(
            "arkflow_tpu_infeed_stall_seconds_total",
            "wall seconds the device sat idle between steps (host-bound)", labels)
        # the per-gap distribution behind the stall total: the direct
        # before/after measurement for dispatch-depth / double-buffering
        # work (ROADMAP item 5) — p50 gap >> 0 means host prep serializes
        # with device compute
        self.m_idle_gap = reg.histogram(
            "arkflow_tpu_device_idle_gap_seconds",
            "gap between step N completing and step N+1 launching "
            "(device idle between consecutive steps)", labels)
        self.m_prep = reg.histogram(
            "arkflow_tpu_infeed_prep_seconds",
            "host-side infeed prep (pad/stage/validate) per step", labels)
        self.m_waste = reg.histogram(
            "arkflow_padding_waste_frac",
            "padding fraction of each dispatched bucket (pad rows / bucket rows; "
            "token padding frac for packed runners)", labels,
            buckets=[0.0, 0.125, 0.25, 0.5, 0.75, 0.9, 1.0],
        )
        # 0/1 gauges so "are the PR-2 wins actually on?" is answerable from
        # the metrics endpoint (and asserted by bench/tests) instead of
        # re-deriving the env/platform gates by hand
        self.m_prefetch_on = reg.gauge(
            "arkflow_tpu_prefetch_active",
            "1 when eager host->device prefetch is enabled for this runner", labels)
        self.m_prefetch_on.set(1 if self._prefetch else 0)
        self.m_donate_on = reg.gauge(
            "arkflow_tpu_donate_active",
            "1 when input donation (input-output aliasing) is enabled", labels)
        self.m_donate_on.set(1 if self._donate else 0)
        self._seen_shapes: set[tuple] = set()
        #: traffic dispatches per padded shape key (warmup excluded) — the
        #: shape tuner's observe-side ground truth for which compiled
        #: shapes live traffic actually lands on; guarded by the flash lock
        #: alongside _seen_shapes (same call site, same threads)
        self._dispatch_counts: dict[tuple, int] = {}
        self._in_warmup = False
        #: device queue depth. 2 = double buffering (prep/dispatch n+1
        #: overlaps compute of n) — enough when dispatch latency ~ 0. Over
        #: a remote/tunneled backend each step also pays a dispatch+sync
        #: round trip (~70ms measured on the axon tunnel vs ~30ms compute
        #: at b1024: tools/profile_step.py), so keeping ceil((rtt+c)/c)
        #: steps in flight is what actually saturates the chip. Config
        #: ``max_in_flight`` / env ARKFLOW_INFLIGHT override.
        if max_in_flight is None:
            # pp default: ONE GPipe schedule in flight. Concurrent pp steps
            # interleave their per-tick ppermute/psum collectives on the
            # same chips — on the CPU backend two in-flight schedules can
            # deadlock the ring outright (observed: a 4-layer tiny step
            # blowing a 5s deadline), and an interleaved second schedule
            # double-counts the measured bubble either way. An explicit
            # max_in_flight / ARKFLOW_INFLIGHT still overrides for
            # real-chip experiments.
            default_inflight = 1 if self._pp_plan is not None else 2
            max_in_flight = _env_int("ARKFLOW_INFLIGHT", default_inflight,
                                     minimum=1)
        if max_in_flight < 1:  # explicit config/kwarg values DO raise
            raise ConfigError(f"max_in_flight must be >= 1, got {max_in_flight}")
        self.max_in_flight = max_in_flight
        #: dispatch depth: at 1 (default) a step holds its in-flight permit
        #: through dispatch AND output fetch — the device queue drains to
        #: empty before the next worker's step can dispatch whenever the
        #: workers run at the in-flight bound. At 2 the permit is released
        #: once the step is ENQUEUED: the fetch (device sync + host copy)
        #: happens outside the in-flight window, so the next step's infeed +
        #: dispatch overlaps this step's compute even at max_in_flight 1,
        #: and staging is double-buffered per step (one set in flight, one
        #: in prep). Env ARKFLOW_DISPATCH_DEPTH overrides the default.
        if dispatch_depth is None:
            dispatch_depth = _env_int("ARKFLOW_DISPATCH_DEPTH", 1, minimum=1)
        if dispatch_depth < 1:  # explicit config/kwarg values DO raise
            raise ConfigError(f"dispatch_depth must be >= 1, got {dispatch_depth}")
        self.dispatch_depth = dispatch_depth
        self._inflight_sem: Optional[asyncio.Semaphore] = None
        #: loop the semaphores are bound to: a runner outliving its loop
        #: (bench/profile phases, engine restarts) must rebuild them, or the
        #: next infer() dies with "bound to a different event loop"
        self._sem_loop: Optional[asyncio.AbstractEventLoop] = None
        #: bounds DEVICE-RESIDENT prefetched input batches (held across the
        #: whole step): one more than the in-flight depth, so exactly one
        #: batch sits staged ahead of the compute queue — otherwise every
        #: stream worker could park a padded batch in HBM
        self._prefetch_sem: Optional[asyncio.Semaphore] = None
        #: bounds dispatched-not-fetched steps at dispatch_depth > 1 (held
        #: enqueue -> outputs fetched); see _ensure_sems
        self._depth_sem: Optional[asyncio.Semaphore] = None
        self._inflight = 0
        self._busy_start = 0.0
        self._last_idle_start: Optional[float] = None
        #: per-bucket recycled host staging buffers (unpacked path only —
        #: packed layouts have data-dependent shapes). One set per possible
        #: concurrent step plus one in prep; at dispatch_depth > 1 each
        #: dispatched-not-fetched step ALSO holds its set (released only
        #: after the fetch), so the cap grows with the depth — the
        #: _StagingPool docstring states the invariant, the assert below
        #: pins it so a future resize can't silently regress depth-2 to a
        #: fresh allocation per step. ARKFLOW_STAGING=0 disables.
        self._staging: Optional[_StagingPool] = None
        if not packed and os.environ.get("ARKFLOW_STAGING", "1") != "0":
            # held sets per key: at depth > 1 the depth semaphore bounds
            # dispatched-not-fetched steps to dispatch_depth (each holds
            # its set until the fetch), depth 1 holds max_in_flight inside
            # the permit; plus one set in prep either way
            self._staging = _StagingPool(
                max_per_key=self.max_in_flight + self.dispatch_depth,
                min_required=(self.dispatch_depth if self.dispatch_depth > 1
                              else self.max_in_flight) + 1)

        # -- self-healing device layer (step deadlines / OOM degradation /
        # -- health state machine) — shared serving core ---------------------
        self.device_label = device_label
        health_name = f"{model}" + (f"[dev {device_label}]" if device_label else "")
        self.core = ServingRunnerCore(
            name=health_name,
            labels=labels,
            step_deadline_s=step_deadline_s,
            step_deadline_first_s=step_deadline_first_s,
            health_config=health_config,
            rebuild_fn=self._rebuild_after_incident,
        )
        self.health = self.core.health
        self.m_deadline_miss = self.core.m_deadline_miss
        self.m_rebuilds = self.core.m_rebuilds
        self.m_oom = reg.counter(
            "arkflow_tpu_oom_total",
            "device RESOURCE_EXHAUSTED / OOM failures observed in steps", labels)
        #: largest batch bucket this runner will still dispatch; shrinks
        #: permanently when the device OOMs on a bucket
        self.m_bucket_cap = reg.gauge(
            "arkflow_tpu_bucket_cap",
            "largest batch bucket currently served (shrinks after device OOM)",
            labels)
        self.m_bucket_cap.set(self.buckets.max_batch())
        #: measured pipeline bubble (pp serving only): 1 - useful-tick time /
        #: step wall time, against the per-seq-bucket tick time measured by a
        #: single-microbatch probe — the analytic floor is (S-1)/(M+S-1)
        self.m_pp_bubble = (
            reg.gauge(
                "arkflow_pp_bubble_frac",
                "measured pipeline-bubble fraction of the last pp step "
                "(1 - M*tick/step; analytic floor (S-1)/(M+S-1))", labels)
            if self._pp_plan is not None else None)

    @staticmethod
    def _resolve_auto_flags(cfg, devices, mesh_spec, packed: bool = False):
        """``use_flash_attention=None`` means auto: the ragged Pallas kernel
        on single-device TPU serving (it skips the fully-padded K tiles XLA
        attention burns MXU cycles on), XLA attention elsewhere (Pallas on
        CPU is interpret-only — orders of magnitude slower; under a mesh the
        kernel would need a shard_map wrapper, so sharded serving keeps the
        GSPMD-partitionable XLA path). ``ARKFLOW_FLASH=0`` is the operator
        kill switch: it forces the XLA path even over an explicit
        ``use_flash_attention: true`` in config — including the packed
        segment kernel. Packed mode: ``ARKFLOW_PACKED_FLASH=1`` opts packed
        serving into the segment flash kernel on TPU backends (cfg field
        ``packed_flash``, single-device only like auto flash)."""
        if not hasattr(cfg, "use_flash_attention"):
            return cfg
        import dataclasses

        def _on_tpu() -> bool:
            from arkflow_tpu.tpu.serving_core import on_tpu_backend

            return on_tpu_backend(devices)

        if (packed and hasattr(cfg, "packed_flash")
                and not cfg.packed_flash
                and os.environ.get("ARKFLOW_PACKED_FLASH", "0") == "1"
                and os.environ.get("ARKFLOW_FLASH", "1") != "0"
                and (mesh_spec is None or mesh_spec.num_devices <= 1)
                and (_on_tpu() or cfg.flash_interpret)):
            cfg = dataclasses.replace(cfg, packed_flash=True)

        if os.environ.get("ARKFLOW_FLASH", "1") == "0":
            return dataclasses.replace(cfg, use_flash_attention=False,
                                       **({"packed_flash": False}
                                          if hasattr(cfg, "packed_flash") else {}))
        if packed and getattr(cfg, "packed_flash", False):
            # an EXPLICIT packed_flash in config must meet the same guards
            # the env grant enforces — fail at construction, not with a
            # Mosaic lowering error on the first packed step
            if mesh_spec is not None and mesh_spec.num_devices > 1:
                raise ConfigError(
                    "packed_flash is single-device for now (the segment "
                    "kernel needs a shard_map wrapper under a mesh)")
            if not (_on_tpu() or cfg.flash_interpret):
                raise ConfigError(
                    "packed_flash requires a TPU backend "
                    "(or flash_interpret for CPU tests)")
        if cfg.use_flash_attention is not None:
            # explicit config keeps its own floor; when config left the
            # floor unset, a set ARKFLOW_FLASH_MIN_SEQ fills it (a
            # config-pinned flash_min_seq still wins over the env var —
            # weaker than the ARKFLOW_FLASH=0 kill switch, which overrides
            # config unconditionally)
            if (cfg.use_flash_attention
                    and getattr(cfg, "flash_min_seq", 0) is None
                    and os.environ.get("ARKFLOW_FLASH_MIN_SEQ")):
                return dataclasses.replace(
                    cfg, flash_min_seq=_env_flash_floor())
            return cfg
        if mesh_spec is not None and mesh_spec.num_devices > 1:
            return dataclasses.replace(cfg, use_flash_attention=False)
        on_tpu = _on_tpu()
        extra = {}
        if on_tpu and getattr(cfg, "flash_min_seq", 0) is None:
            # auto-chosen flash only engages at seqs where the kernel wins:
            # short buckets tile below the MXU (tile=seq<128) and the grid
            # overhead dominates — v5e A/B at seq 32 measured XLA 47% faster
            # end-to-end; on-chip the two are within ~5% from seq 128 up
            # (tools/profile_attention.py), with Pallas ahead at low fill.
            # Only fills the floor when unset, so an operator-tuned
            # flash_min_seq in config survives auto-resolution.
            extra["flash_min_seq"] = _env_flash_floor()
        return dataclasses.replace(cfg, use_flash_attention=on_tpu, **extra)

    def _init_pp(self, mesh_spec: MeshSpec, params, devices,
                 pp_microbatch_rows: Optional[int],
                 pp_layer_costs: Optional[list]):
        """Pipelined-parallel serving setup (``mesh: {pp: N}``): cut the
        layer stack into cost-balanced stages (parallel/segment.py — from a
        measured per-layer profile when one is configured, uniform
        otherwise), repack the stacked layer params into the stage-padded
        layout, and shard them over the ``pp`` axis. Activations stream
        stage-to-stage inside the jitted step; dp composes (the batch dim
        splits over ``dp`` while each dp replica runs its own pipeline)."""
        from arkflow_tpu.parallel.pipeline import (
            pp_infer_param_specs,
            pp_repack_layers,
        )
        from arkflow_tpu.parallel.segment import plan_stages, uniform_plan

        if self.packed:
            raise ConfigError(
                "packing + mesh pp is not supported: the pp schedule streams "
                "fixed-shape microbatches, packed layouts are data-dependent "
                "(serve pp unpacked, or keep packing on dp/pool)")
        if mesh_spec.tp > 1 or mesh_spec.sp > 1 or mesh_spec.ep > 1:
            raise ConfigError(
                "mesh pp composes with dp only (dp x pp); tp/sp/ep alongside "
                "pp are not supported")
        extras = self.family.extras or {}
        if "pp_stage_fns" not in extras:
            raise ConfigError(
                f"model {self.family.name!r} has no pipeline-parallel serving "
                "support (family extras lack pp_stage_fns)")
        try:
            n_layers = int(jax.tree_util.tree_leaves(params["layers"])[0].shape[0])
        except (KeyError, IndexError, TypeError) as e:
            raise ConfigError(
                f"model {self.family.name!r} has no stacked 'layers' params "
                "to segment for pp serving") from e
        stages = mesh_spec.pp
        if stages > n_layers:
            raise ConfigError(
                f"mesh pp={stages} exceeds the model's {n_layers} layers "
                "(every stage needs at least one layer)")
        if pp_layer_costs is not None:
            if len(pp_layer_costs) != n_layers:
                raise ConfigError(
                    f"pp layer costs cover {len(pp_layer_costs)} layers but "
                    f"the model has {n_layers} — re-profile with the served "
                    "model_config")
            plan = plan_stages(pp_layer_costs, stages)
        else:
            plan = uniform_plan(n_layers, stages)
        mb = pp_microbatch_rows if pp_microbatch_rows is not None \
            else self.buckets.batch_buckets[0]
        if not isinstance(mb, int) or isinstance(mb, bool) or mb < 1:
            raise ConfigError(
                f"pp_microbatch_rows must be a positive int, got {mb!r}")
        for b in self.buckets.batch_buckets:
            # per-replica shapes: dp scaling multiplies the grid below, so
            # the per-replica bucket IS the configured one
            if b > mb and b % mb != 0:
                raise ConfigError(
                    f"batch bucket {b} does not divide by pp_microbatch_rows "
                    f"{mb} — the GPipe schedule needs bucket-exact "
                    "microbatches (pow2 grids with a pow2 microbatch always "
                    "divide)")
        self.mesh = create_mesh(mesh_spec, devices=devices)
        repacked = pp_repack_layers(params, plan)
        self._pspecs = pp_infer_param_specs(repacked)
        placed = shard_params(repacked, self._pspecs, self.mesh)
        self.buckets = self.buckets.dp_scaled(dp_size(self.mesh))
        self._input_sharding = batch_sharding(self.mesh)
        self._pp_plan = plan
        self._pp_mb_rows = mb
        logger.info(
            "[%s] pp serving: %d stages over %d layers (sizes %s, imbalance "
            "%.3f), microbatch %d rows", self.family.name, stages, n_layers,
            plan.sizes, plan.imbalance, mb)
        return placed

    def _build_jitted(self) -> None:
        """(Re)build the jitted step from the CURRENT self.cfg. jax.jit keys
        executables on the function object, so any cfg change that alters
        tracing (e.g. disabling flash attention) must rebuild — mutating
        self.cfg alone would keep serving stale executables for seen shapes."""
        if self._pp_plan is not None:
            self._build_jitted_pp()
            return
        apply_fn = (self.family.extras["apply_packed"] if self.packed
                    else self.family.apply)
        # thread mesh/axes into families whose apply understands sharded
        # execution (e.g. decoder ring attention); others get plain calls
        import inspect

        sig = inspect.signature(apply_fn)
        extra_kwargs: dict[str, Any] = {}
        if "axes" in sig.parameters and self._axes:
            extra_kwargs["axes"] = self._axes
        if "mesh" in sig.parameters and self.mesh is not None:
            extra_kwargs["mesh"] = self.mesh
        cfg = self.cfg

        def run(params, inputs):
            return apply_fn(params, cfg, **inputs, **extra_kwargs)

        # donate the padded inputs (argnum 1, never the params): XLA's
        # input-output aliasing reuses their device buffers for outputs,
        # trimming steady-state HBM churn on accelerator backends
        jit_kwargs: dict[str, Any] = {}
        if self._donate:
            jit_kwargs["donate_argnums"] = (1,)
        if self.mesh is not None:
            # dp-sharded dispatch: pin params to their placed shardings and
            # split every input/output batch dim over dp explicitly — host
            # numpy fed to jit is otherwise fully replicated, so each chip
            # would redundantly compute the whole batch. The single
            # ``_input_sharding`` is a pytree prefix: it broadcasts over the
            # inputs dict (all model inputs lead with the batch/example dim)
            # and over every output leaf.
            jit_kwargs["in_shardings"] = (param_shardings(self.params),
                                          self._input_sharding)
            jit_kwargs["out_shardings"] = self._input_sharding
        self._jitted = jax.jit(run, **jit_kwargs)

    def _build_jitted_pp(self) -> None:
        """Jit the pipelined-parallel step: shard_map over (dp, pp) with the
        GPipe microbatch schedule inside (parallel/pipeline.py). Params ride
        as an argument exactly like the plain path, so hot-swap flips and
        post-incident rebuilds work unchanged."""
        from arkflow_tpu.parallel.pipeline import make_pp_infer_step

        fn = make_pp_infer_step(
            self.family, self.cfg, self.mesh, plan=self._pp_plan,
            microbatch_rows=self._pp_mb_rows, param_specs=self._pspecs)
        jit_kwargs: dict[str, Any] = {}
        if self._donate:
            jit_kwargs["donate_argnums"] = (1,)
        jit_kwargs["in_shardings"] = (param_shardings(self.params),
                                      self._input_sharding)
        jit_kwargs["out_shardings"] = self._input_sharding
        self._jitted = jax.jit(fn, **jit_kwargs)

    def _disable_flash(self) -> None:
        """Auto-fallback: serve with XLA attention from now on (one
        recompile per bucket; prior flash executables are abandoned).
        Concurrent _prep threads may call this together; the lock makes
        the cfg flip + jit rebuild happen once."""
        import dataclasses

        with self._flash_lock:
            if not getattr(self.cfg, "use_flash_attention", False):
                return  # another thread already fell back
            self.cfg = dataclasses.replace(self.cfg, use_flash_attention=False)
            self._seen_shapes.clear()
            self._build_jitted()

    # -- shape plumbing ----------------------------------------------------

    def _pad_inputs_packed(self, inputs: dict[str, np.ndarray]) -> tuple[dict[str, Any], int]:
        """Pad a packed layout (tpu/packing.py): [P, S] row arrays pad P to a
        batch bucket (dead rows: segment 0), [E] example-index arrays pad E
        to its own EXAMPLE bucket (they point at row 0/pos 0, sliced off by
        the true-count return; the example grid extends ``example_scale``
        past the row grid because a full row bucket of short texts carries
        several examples per row). Fill metric reports TOKEN fill — the
        quantity packing exists to maximize."""
        p = inputs["input_ids"].shape[0]
        e = inputs["example_row"].shape[0]
        mb = self.buckets.max_batch()
        me = self.buckets.max_examples()
        if p > mb or e > me:
            raise ConfigError(
                f"packed batch ({p} rows / {e} examples) exceeds the grid "
                f"(max {mb} rows / {me} examples); carve row windows that "
                "fit before dispatch (tpu/packing.py carve_row_windows)")
        pb = self.buckets.batch_bucket(p)
        eb = self.buckets.example_bucket(e)
        out = {}
        for name, (dtype, trailing) in self.spec.items():
            arr = inputs.get(name)
            if arr is None:
                raise ConfigError(f"model {self.family.name!r} missing input {name!r}")
            arr = np.asarray(arr, dtype=dtype)
            if "seq" in trailing:
                arr = pad_seq_dim(arr, self.buckets.seq_bucket(arr.shape[1]), axis=1)
                arr = pad_batch_dim(arr, pb)
            else:
                arr = pad_batch_dim(arr, eb)
            out[name] = arr
        sb = out["input_ids"].shape[1]
        true_tokens = int((np.asarray(inputs["segment_ids"]) > 0).sum())
        if not self._in_warmup:  # warmup shapes are not traffic
            self.m_pad.inc(pb - p)
            fill = true_tokens / (pb * sb) if pb * sb else 0.0
            self.m_fill.observe(fill)
            self.m_waste.observe(1.0 - fill)
            self.m_exec_rows.inc(pb)
            self.m_tokens.inc(true_tokens)
            self.m_token_capacity.inc(pb * sb)
        return out, e

    def _pad_inputs(self, inputs: dict[str, np.ndarray]) -> tuple[dict[str, Any], int]:
        """Pad every input to its bucket; returns (padded, true_batch).

        Allocation-free in steady state: the padded arrays come from the
        per-bucket staging pool and are filled in place (rows, then zeroed
        padding regions); ``np.pad``'s fresh bucket-sized allocations only
        happen the first few times a bucket is seen. The buffers go back to
        the pool via ``_release_staging`` after the step completes.
        """
        if self.packed:
            return self._pad_inputs_packed(inputs)
        n = next(iter(inputs.values())).shape[0]
        bb = self.buckets.batch_bucket(n)
        arrs: dict[str, np.ndarray] = {}
        shapes: dict[str, tuple] = {}
        for name, (dtype, trailing) in self.spec.items():
            arr = inputs.get(name)
            if arr is None:
                raise ConfigError(f"model {self.family.name!r} missing input {name!r}")
            arr = np.asarray(arr, dtype=dtype)
            if "seq" in trailing:
                sb = self.buckets.seq_bucket(arr.shape[1])
                if arr.shape[1] > sb:  # over-long rows truncate to the top bucket
                    arr = pad_seq_dim(arr, sb, axis=1)
                shapes[name] = (bb, sb, *arr.shape[2:])
            else:
                shapes[name] = (bb, *arr.shape[1:])
            if arr.shape[0] > bb:
                raise ValueError(f"batch {arr.shape[0]} exceeds bucket {bb}")
            arrs[name] = arr
        out = self._acquire_staging(shapes)
        for name, arr in arrs.items():
            buf = out[name]
            if arr.ndim >= 2 and arr.shape[1] < buf.shape[1]:
                buf[:n, : arr.shape[1]] = arr
                buf[:n, arr.shape[1]:] = 0
            else:
                buf[:n] = arr
            buf[n:] = 0
        if not self._in_warmup:  # warmup shapes are not traffic
            self.m_pad.inc(bb - n)
            self.m_fill.observe(n / bb)
            self.m_waste.observe((bb - n) / bb if bb else 0.0)
            self.m_exec_rows.inc(bb)
            if "attention_mask" in arrs:
                # token models: true tokens vs dispatched token slots, so
                # 1 - tokens/capacity is the capacity-weighted padding waste
                # INCLUDING seq padding — the quantity the shape tuner's
                # seq-edge retuning moves, invisible to the row-only
                # histogram above (bench/soak read these counters)
                mask_shape = shapes["attention_mask"]
                self.m_tokens.inc(int(arrs["attention_mask"].sum()))
                self.m_token_capacity.inc(int(bb * mask_shape[1]))
        return out, n

    # -- staging buffer recycling ------------------------------------------

    @staticmethod
    def _staging_key(shapes: dict[str, tuple]) -> tuple:
        return tuple(sorted(shapes.items()))

    def _acquire_staging(self, shapes: dict[str, tuple]) -> dict[str, np.ndarray]:
        if self._staging is not None:
            bufs = self._staging.acquire(self._staging_key(shapes))
            if bufs is not None:
                return bufs
        return {name: np.empty(shape, dtype=self.spec[name][0])
                for name, shape in shapes.items()}

    def _release_staging(self, padded: dict[str, Any]) -> None:
        """Return a step's staging buffers once nothing can still read them
        (the step's outputs were fetched). No-op for packed layouts and for
        dicts whose values were swapped for device arrays upstream."""
        if self._staging is None or self.packed or not padded:
            return
        if not all(isinstance(v, np.ndarray) for v in padded.values()):
            return
        self._staging.release(
            self._staging_key({k: v.shape for k, v in padded.items()}), padded)

    def _shape_key(self, padded: dict[str, np.ndarray]) -> tuple:
        return tuple((k, v.shape) for k, v in sorted(padded.items()))

    def _note_shape(self, padded: dict[str, Any]) -> bool:
        """First-seen-shape accounting for the compile counter; returns True
        when the shape is new (the step will compile — the deadline watchdog
        grants it the first-compile budget). Guarded by the flash lock:
        ``infer_sync`` (executor threads) and ``infer`` (the event loop) race
        here, and an unsynchronized check-then-add both double-counts
        compiles and can miss ``_disable_flash``'s concurrent
        ``_seen_shapes.clear()`` (which holds the same lock)."""
        key = self._shape_key(padded)
        with self._flash_lock:
            if not self._in_warmup:
                self._dispatch_counts[key] = self._dispatch_counts.get(key, 0) + 1
            if key not in self._seen_shapes:
                self._seen_shapes.add(key)
                self.m_compiles.inc()
                return True
        return False

    def dispatch_counts(self) -> dict[tuple, int]:
        """Traffic dispatches per padded shape key (warmup excluded)."""
        with self._flash_lock:
            return dict(self._dispatch_counts)

    # -- pipelined-parallel bubble accounting -------------------------------

    def _pp_geometry(self, padded: dict[str, Any]) -> tuple[int, int, int]:
        """(seq bucket, microbatches, stages) of a padded pp step."""
        seq = 0
        for name, (_, trailing) in self.spec.items():
            if "seq" in trailing and name in padded:
                seq = int(padded[name].shape[1])
                break
        rows = int(next(iter(padded.values())).shape[0])
        local = max(1, rows // dp_size(self.mesh))
        mb = min(self._pp_mb_rows, local)
        return seq, max(1, local // mb), self._pp_plan.stages

    def _pp_ensure_tick(self, seq: int) -> None:
        """Measure this seq bucket's per-tick cost once, via a
        single-microbatch probe step (M=1 => the schedule is exactly S
        ticks, so tick = step/S). The probe is how the bubble gauge stays a
        MEASUREMENT: per-step bubble = 1 - M*tick/step against this
        reference, so ppermute latency, imbalance, and host stalls all show
        up instead of being assumed away by the analytic (S-1)/(M+S-1)."""
        if self._pp_plan is None:
            return
        with self._flash_lock:
            if seq in self._pp_tick_s or seq in self._pp_tick_pending:
                return
            self._pp_tick_pending.add(seq)
        try:
            import time

            rows = self._pp_mb_rows * dp_size(self.mesh)
            fake = {}
            for name, (dtype, trailing) in self.spec.items():
                dims = tuple(seq if d == "seq" else d for d in trailing)
                fake[name] = np.ones((rows, *dims), dtype=dtype)
            jax.device_get(self._dispatch(fake))  # compile
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.device_get(self._dispatch(fake))
                ts.append(time.perf_counter() - t0)
            ts.sort()
            # the M=1 probe pays every stage once, so step/S is the MEAN
            # stage cost; the steady-state tick is the MAX stage cost
            # (stages run in lockstep), so scale by the plan's imbalance —
            # an uneven-but-optimal cut must not read as extra bubble
            tick = (ts[len(ts) // 2] / self._pp_plan.stages
                    * self._pp_plan.imbalance)
            with self._flash_lock:
                self._pp_tick_s[seq] = max(tick, 1e-9)
        except Exception as e:  # pragma: no cover - probe must never kill serving
            logger.warning("[%s] pp tick probe failed at seq %d: %s",
                           self.family.name, seq, e)
        finally:
            with self._flash_lock:
                self._pp_tick_pending.discard(seq)

    async def _pp_probe_async(self, seq: int) -> None:
        """Lazy tick probe for a seq bucket warmup never saw: holds the
        in-flight permit across the probe steps so they serialize with live
        schedules instead of interleaving collectives with them."""
        self._ensure_sems()
        async with self._inflight_sem:
            await asyncio.get_running_loop().run_in_executor(
                None, self._pp_ensure_tick, seq)

    def _pp_observe(self, padded: dict[str, Any], dt: float) -> None:
        """Fold one pp step into the bubble gauge + trace spans:
        ``pp_bubble`` is the step's measured idle share (vs M useful ticks at
        the probed tick cost), ``pp_stage_wait`` the fill/drain ramp the
        first/last microbatches spend waiting on other stages."""
        if self._pp_plan is None or self._in_warmup or dt <= 0:
            return
        seq, m, s = self._pp_geometry(padded)
        tick = self._pp_tick_s.get(seq)
        if tick is None:
            # not probed yet (warmup skipped): probe UNDER the in-flight
            # permit so the probe's pipeline steps never interleave their
            # collectives with a live schedule (the deadlock the
            # one-schedule default exists to prevent). No loop => no safe
            # slot to serialize against: skip, warmup is the probe site.
            try:
                asyncio.get_running_loop().create_task(
                    self._pp_probe_async(seq))
            except RuntimeError:
                pass
            return
        bubble = min(1.0, max(0.0, 1.0 - (m * tick) / dt))
        self.m_pp_bubble.set(bubble)
        record_stage("pp_bubble", bubble * dt,
                     attrs={"stages": s, "microbatches": m, "seq": seq})
        record_stage("pp_stage_wait", min(dt, (s - 1) * tick),
                     attrs={"stages": s})

    def pp_report(self) -> Optional[dict]:
        """JSON-able pp-serving snapshot (stage plan + measured bubble) for
        /health and the bench detail; None off the pp path."""
        if self._pp_plan is None:
            return None
        return {
            **self._pp_plan.report(),
            "microbatch_rows": self._pp_mb_rows,
            "bubble_frac": (round(float(self.m_pp_bubble.value), 4)
                            if self.m_pp_bubble is not None else None),
            "tick_ms": {str(k): round(v * 1e3, 3)
                        for k, v in sorted(self._pp_tick_s.items())},
        }

    # -- self-healing: chaos hook / watchdog / OOM degradation --------------
    # (the health state machine, deadline watchdog, and chaos queue live in
    # the shared ServingRunnerCore; the runner keeps the OOM degradation
    # policy, which is bucket-grid-specific)

    def inject_step_fault(self, kind: str, duration_s: float = 0.0) -> None:
        """Arm a fault on this runner (fault plugin's processor wrapper):
        ``hang``/``oom`` are one-shot step faults consumed by the next
        device step, ``sdc`` persistently garbles step outputs until the
        integrity repair clears it (both live in the shared core), and
        ``bitflip`` corrupts one param leaf of the LIVE placed tree in
        place — the HBM bit-flip / defective-chip failure mode the
        integrity plane (tpu/integrity.py) exists to catch."""
        if kind == "bitflip":
            self._bitflip_params()
            return
        self.core.inject_step_fault(kind, duration_s)

    def _bitflip_params(self) -> None:
        """Corrupt the largest float leaf of ``self.params`` in place (the
        leaf most likely to be a weight matrix every forward touches). The
        corruption persists until the integrity monitor repairs the member
        by re-adopting ``host_params`` — exactly like real HBM corruption,
        nothing on the serving path notices by itself."""
        import jax.numpy as jnp

        flat, treedef = jax.tree_util.tree_flatten_with_path(self.params)
        best: Optional[int] = None
        for i, (_, leaf) in enumerate(flat):
            dt = getattr(leaf, "dtype", None)
            if (dt is not None and jnp.issubdtype(dt, jnp.floating)
                    and getattr(leaf, "size", 0)
                    and (best is None or leaf.size > flat[best][1].size)):
                best = i
        if best is None:
            raise ConfigError(
                "bitflip: model has no float param leaf to corrupt")
        path, leaf = flat[best]
        host = np.asarray(jax.device_get(leaf))
        garbled = (np.asarray(host, np.float32) * -1000.0 + 3.7).astype(
            host.dtype)
        placed = jax.device_put(garbled, leaf.sharding)
        leaves = [l for _, l in flat]
        leaves[best] = placed
        # one-assignment flip, like adopt_params — but WITHOUT invalidating
        # the digest baseline: the whole point is that the drift is silent
        self.params = jax.tree_util.tree_unflatten(treedef, leaves)
        logger.warning("[%s] chaos: bitflip corrupted param leaf %s",
                       self.family.name, jax.tree_util.keystr(path))

    @property
    def step_deadline_s(self) -> Optional[float]:
        return self.core.step_deadline_s

    @step_deadline_s.setter
    def step_deadline_s(self, v: Optional[float]) -> None:
        self.core.step_deadline_s = v

    @property
    def step_deadline_first_s(self) -> Optional[float]:
        return self.core.step_deadline_first_s

    @step_deadline_first_s.setter
    def step_deadline_first_s(self, v: Optional[float]) -> None:
        self.core.step_deadline_first_s = v

    def _step_blocking(self, padded: dict[str, Any]):
        """The full blocking device step (chaos hook -> dispatch -> fetch).
        Always runs on an executor/watchdog thread: warm shapes cost one
        sub-ms hop, cold shapes compile for seconds-to-minutes on remote
        backends — never on the event loop — and the deadline watchdog can
        abandon the thread if the device wedges."""
        self.core.apply_chaos()
        # corrupt_outputs: identity unless an sdc fault is armed (chaos)
        return self.core.corrupt_outputs(
            jax.device_get(self._dispatch(padded)))

    def _enqueue_step(self, padded: dict[str, Any]):
        """Dispatch half of a depth-split step (``dispatch_depth`` > 1):
        the jitted call only ENQUEUES on the device and returns its output
        futures — all waiting (and the chaos hook, so an injected hang is
        watched by the fetch deadline) happens in the fetch half. Runs on
        an executor thread: a warm dispatch is sub-ms, but a first-seen
        shape compiles synchronously here and must not block the loop."""
        return self._dispatch(padded)

    def _note_oom(self, bucket_rows: int) -> bool:
        """Device OOM on a ``bucket_rows`` bucket: permanently cap the batch
        grid below it (``arkflow_tpu_bucket_cap``) and announce the cap so
        live coalescers stop merging emissions the device can't hold.
        Returns True when a smaller bucket exists (the caller re-chunks and
        retries); False when even the smallest bucket OOMs — the runner goes
        UNHEALTHY and the failure surfaces."""
        self.m_oom.inc()
        with self._flash_lock:
            capped = self.buckets.capped(bucket_rows)
            if capped is None:
                self.health.mark_unhealthy(
                    f"device OOM at the smallest bucket ({bucket_rows} rows)")
                return False
            self.buckets = capped
        cap = capped.max_batch()
        self.m_bucket_cap.set(cap)
        bucket_cap_bus().announce(cap)
        self.health.mark_degraded(f"device OOM: batch buckets capped at {cap}")
        logger.warning(
            "[%s] device OOM on a %d-row bucket: batch grid capped at %d; "
            "splitting the batch and retrying", self.family.name, bucket_rows, cap)
        return True

    def _rebuild_after_incident(self) -> None:
        """Core rebuild hook (runs inside the heal gate after a deadline
        miss): executables cached across a device hang are not trusted, so
        the next (probe) step recompiles from scratch. Shares the flash lock
        with the other cfg-flip/rebuild paths."""
        with self._flash_lock:
            self._seen_shapes.clear()
            self._build_jitted()
        logger.warning("[%s] rebuilt jitted step after a deadline miss",
                       self.family.name)

    # -- live hot-swap surface (tpu/swap.py) --------------------------------

    def place_params(self, host_params):
        """Place a (converted) host param tree exactly like ``__init__``
        placed the original: sharded with the same PartitionSpecs under a
        mesh, a one-hop transfer to the runner's device otherwise (pp
        serving additionally repacks the layer stack into its stage-padded
        layout first, so a hot-swap candidate lands in the same shape the
        live tree serves from). Blocking (device transfer) — swap runs it
        on an executor thread, never the serving loop."""
        if self._pp_plan is not None:
            from arkflow_tpu.parallel.pipeline import pp_repack_layers

            host_params = pp_repack_layers(host_params, self._pp_plan)
        if self.mesh is not None:
            return shard_params(host_params, self._pspecs, self.mesh)
        return jax.device_put(host_params, self._device)

    def adopt_params(self, placed):
        """Atomically flip serving onto ``placed``; returns the prior tree
        (the rollback token). Params ride the jitted step as an ARGUMENT
        (never a traced constant), so the flip is one attribute assignment:
        in-flight steps finish on the tree they already read, the next
        dispatch serves the new weights, and — same structure/dtypes/
        shardings — no executable recompiles."""
        old, self.params = self.params, placed
        # the digest baseline described the OLD tree; the integrity monitor
        # re-baselines lazily at its next off-path pass (adopt must not pay
        # a synchronous full-tree device_get on the event loop)
        self.param_digests = None
        return old

    def swap_units(self) -> list[tuple[str, "ModelRunner"]]:
        """A single runner is one flippable unit (the pool overrides this
        with its per-member rolling order)."""
        return [("runner", self)]

    # -- integrity surface (tpu/integrity.py) -------------------------------

    def digest_params(self) -> dict[str, str]:
        """Per-leaf digests of the LIVE placed tree. Blocking (device_get
        of every leaf) — callers keep it off the event loop, holding the
        in-flight permit when serving (:meth:`verify_params_live`)."""
        from arkflow_tpu.tpu.integrity import tree_digests

        return tree_digests(self.params)

    def rebaseline_digests(self) -> dict[str, str]:
        """Recompute and store the digest baseline — at a known-good
        moment only (boot, committed swap, verified integrity repair).
        Blocking, like :meth:`digest_params`."""
        self.param_digests = self.digest_params()
        return self.param_digests

    async def verify_params_live(self) -> list[str]:
        """Off-path digest verification WHILE serving: fetch-and-hash on
        an executor thread holding the in-flight permit — serializing with
        live device schedules, the same discipline ``warm_shapes_live``
        follows — under the first-compile deadline so a wedged device
        abandons the verification instead of blocking the monitor forever.
        Returns the drifted leaf paths (empty = verified). The first call
        after boot/adopt takes the baseline instead (the tree was just
        placed from a known-good source)."""
        from arkflow_tpu.tpu.integrity import diff_digests

        self._ensure_sems()
        loop = asyncio.get_running_loop()
        async with self._inflight_sem:
            deadline = self.core.deadline_for(True)
            if deadline is None:
                digests = await loop.run_in_executor(None, self.digest_params)
            else:
                digests = await self.core.run_deadlined(
                    self.digest_params, deadline)
        if self.param_digests is None:
            self.param_digests = digests
            return []
        return diff_digests(self.param_digests, digests)

    # -- live shape retune surface (tpu/tuner.py) ---------------------------

    def grid_shapes(self, policy: BucketPolicy) -> list[dict[str, tuple]]:
        """Every padded-input shape signature ``policy`` can put on the
        device — the same reachable set ``warmup`` walks, but for an
        arbitrary (e.g. tuner-proposed) policy, without dispatching."""
        has_seq = any("seq" in t for _, t in self.spec.values())
        seqs = list(policy.seq_buckets) if has_seq else [None]
        if self.packed:
            pairs = [(pb, eb) for eb in policy.example_buckets()
                     for pb in policy.batch_buckets if pb <= eb]
        else:
            pairs = [(bb, bb) for bb in policy.batch_buckets]
        shapes: list[dict[str, tuple]] = []
        for pb, eb in pairs:
            for sl in seqs:
                shape: dict[str, tuple] = {}
                for name, (dtype, trailing) in self.spec.items():
                    lead = eb if self.packed and "seq" not in trailing else pb
                    dims = tuple(sl if d == "seq" else d for d in trailing)
                    shape[name] = (lead, *dims)
                shapes.append(shape)
        return shapes

    @staticmethod
    def _grid_shape_key(shape: dict[str, tuple]) -> tuple:
        # identical structure to _shape_key (name-sorted (name, shape)
        # pairs), so warm-marked shapes are exactly what _note_shape sees
        return tuple(sorted(shape.items()))

    def count_new_shapes(self, policy: BucketPolicy) -> int:
        """How many executables ``policy`` would still have to compile —
        the tuner's compile-cost gate reads this before proposing a flip."""
        shapes = self.grid_shapes(policy)
        with self._flash_lock:
            return sum(1 for s in shapes
                       if self._grid_shape_key(s) not in self._seen_shapes)

    def _compile_shape(self, shape: dict[str, tuple]) -> None:
        """Compile (and discard) one padded shape through the jitted step."""
        fake = {name: np.zeros(s, self.spec[name][0])
                for name, s in shape.items()}
        jax.device_get(self._dispatch(fake))

    def _mark_warmed(self, key: tuple) -> None:
        with self._flash_lock:
            if key not in self._seen_shapes:
                self._seen_shapes.add(key)
                self.m_warm_compiles.inc()

    def warm_shapes(self, policy: BucketPolicy) -> int:
        """Pre-compile every not-yet-seen shape of ``policy`` OFF the
        serving path (shape-tuner warm phase). Compiles go through the
        persistent XLA cache like any other, and each warmed shape is
        marked seen WITHOUT touching ``arkflow_tpu_compiles_total`` — so
        after the flip, live traffic on the new grid never compiles and
        the serving-path compile counter stays flat; warm compiles count in
        ``arkflow_tpu_warm_compiles_total`` instead. Blocking (XLA
        compiles) and un-deadlined: for use off live traffic (tests,
        tools); the tuner's cycle path uses :meth:`warm_shapes_live`."""
        count = 0
        for shape in self.grid_shapes(policy):
            key = self._grid_shape_key(shape)
            with self._flash_lock:
                if key in self._seen_shapes:
                    continue
            self._compile_shape(shape)
            self._mark_warmed(key)
            count += 1
        return count

    async def warm_shapes_live(self, policy: BucketPolicy) -> int:
        """``warm_shapes`` for use WHILE serving: each compile holds the
        in-flight permit — serializing with live device schedules, the same
        discipline the pp tick probe follows — and runs under the
        first-compile deadline on a watchdog thread, so a wedged compile is
        abandoned (the runner heals through its normal probe path) instead
        of blocking the caller forever."""
        self._ensure_sems()
        loop = asyncio.get_running_loop()
        count = 0
        for shape in self.grid_shapes(policy):
            key = self._grid_shape_key(shape)
            with self._flash_lock:
                if key in self._seen_shapes:
                    continue
            async with self._inflight_sem:
                deadline = self.core.deadline_for(True)
                if deadline is None:
                    await loop.run_in_executor(
                        None, self._compile_shape, shape)
                else:
                    await self.core.run_deadlined(
                        partial(self._compile_shape, shape), deadline)
            self._mark_warmed(key)
            count += 1
        return count

    def retarget_buckets(self, policy: BucketPolicy) -> BucketPolicy:
        """Atomically flip the serving bucket grid (shape-tuner flip);
        returns the prior policy (the rollback token). In-flight steps
        already padded keep their old shapes — both grids are compiled, so
        the transition window serves both without a recompile."""
        with self._flash_lock:
            old, self.buckets = self.buckets, policy
        self.m_bucket_cap.set(policy.max_batch())
        return old

    def health_report(self) -> dict:
        """JSON-able health snapshot for the engine's ``/health`` endpoint."""
        rep = self.core.health_report()
        rep["model"] = self.family.name
        if self.device_label is not None:
            rep["device"] = self.device_label
        rep["bucket_cap"] = self.buckets.max_batch()
        pp = self.pp_report()
        if pp is not None:
            # the stage plan rides /health so pipeline imbalance is
            # attributable to the profile that produced the cut
            rep["pp"] = pp
        return rep

    # -- execution ---------------------------------------------------------

    def infer_sync(self, inputs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        """Blocking inference: pad -> device -> unpad.

        Batches larger than the biggest bucket are chunked and the outputs
        re-concatenated (upstream buffers may over-merge under backpressure).
        With ``step_deadline`` set the step runs on a watchdog thread and is
        abandoned on a miss; a device OOM caps the bucket grid and retries
        the batch split to the next-smaller bucket.
        """
        import time

        n_total = next(iter(inputs.values())).shape[0]
        mb = self.buckets.max_batch()
        if n_total > mb and not self.packed:
            # (packed layouts can't be sliced uniformly — row and example
            # dims differ; the packer pre-chunks, _pad_inputs_packed raises)
            chunks = [
                self.infer_sync({k: v[i : i + mb] for k, v in inputs.items()})
                for i in range(0, n_total, mb)
            ]
            return {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}

        self.core.heal_gate_sync()
        padded, n = self._prep(inputs)
        first = self._note_shape(padded)
        bucket_rows = next(iter(padded.values())).shape[0]
        deadline = self.core.deadline_for(first)
        t0 = time.perf_counter()
        try:
            if deadline is None:
                out = self._step_blocking(padded)
            else:
                out = self.core.run_deadlined_sync(
                    partial(self._step_blocking, padded), deadline,
                    on_zombie=partial(self._release_staging, padded))
        except StepDeadlineExceeded:
            raise  # the zombie step still owns the staging buffers
        except Exception as e:
            # step ended (with an error) => the device consumed the inputs
            self._release_staging(padded)
            if is_oom_error(e):
                if not self.packed and self._note_oom(bucket_rows):
                    return self.infer_sync(inputs)  # re-chunk on the capped grid
                if self.packed:
                    # can't re-slice a packed layout here; cap the grid so the
                    # REDELIVERED batch repacks against servable buckets
                    self._note_oom(bucket_rows)
            raise
        # outputs fetched => the staging buffers are safe to recycle
        self._release_staging(padded)
        if not self._in_warmup:  # warmup compiles are not traffic latency
            dt = time.perf_counter() - t0
            self.m_infer.observe(dt)
            if not first:  # compile steps are not schedule timing
                self._pp_observe(padded, dt)
            self.m_rows.inc(n)
        self.health.mark_success()
        return {k: np.asarray(v)[:n] for k, v in out.items()}

    def _prep(self, inputs: dict[str, np.ndarray]) -> tuple[dict[str, Any], int]:
        """Host-side stage: pad to buckets + validate masks (CPU only)."""
        import time

        t0 = time.perf_counter()
        try:
            return self._prep_inner(inputs)
        finally:
            if not self._in_warmup:
                self.m_prep.observe(time.perf_counter() - t0)

    def _prep_inner(self, inputs: dict[str, np.ndarray]) -> tuple[dict[str, Any], int]:
        padded, n = self._pad_inputs(inputs)
        if getattr(self.cfg, "use_flash_attention", False) and "attention_mask" in padded:
            # sub-floor buckets compile the XLA path (models gate on the
            # static seq), which serves arbitrary masks — don't fail or
            # globally disable flash over a batch the kernel never sees
            m = padded["attention_mask"]
            if m.shape[1] < (getattr(self.cfg, "flash_min_seq", None) or 0):
                return padded, n
            # the ragged kernel reads row sums as prefix lengths; a
            # non-contiguous mask (left padding) would silently mis-attend
            lengths = m.sum(axis=1)
            prefix = (np.arange(m.shape[1])[None, :] < lengths[:, None]).astype(m.dtype)
            if not np.array_equal(prefix, m):
                if self._flash_user_forced:
                    raise ConfigError(
                        "use_flash_attention requires right-padded attention "
                        "masks (contiguous prefix of ones)"
                    )
                # flash was an auto choice, not user config: serve the
                # batch via XLA attention instead of failing the stream
                logger.warning(
                    "[%s] non-right-padded attention mask: disabling auto "
                    "flash attention (XLA path; one recompile per bucket)",
                    self.family.name)
                self._disable_flash()
        return padded, n

    def _dispatch(self, padded: dict[str, Any]):
        """Non-blocking XLA dispatch (async device futures)."""
        if self.mesh is not None:
            with self.mesh:
                return self._jitted(self.params, padded)
        return self._jitted(self.params, padded)

    def _to_device(self, padded: dict[str, Any]) -> dict[str, Any]:
        """Eager host->device transfer of a prepped batch: runs on an
        executor thread BEFORE the in-flight semaphore, so batch n+1's
        infeed overlaps batch n's compute instead of paying the transfer
        inside its own device window. Under a mesh this is a SHARDED
        device_put — each chip receives only its dp shard of the batch dim
        (the dp-scaled buckets guarantee divisibility), and the dispatch
        then consumes already-placed arrays with zero re-layout. Waits for
        the copies so the subsequent dispatch never blocks on them."""
        target = self._input_sharding if self.mesh is not None else self._device
        dev = jax.device_put(padded, target)
        jax.block_until_ready(dev)
        return dev

    # -- in-flight accounting (duty cycle / infeed stall) -------------------

    def _track_dispatch(self, now: float) -> None:
        if self._inflight == 0:
            if self._last_idle_start is not None:
                gap = now - self._last_idle_start
                self.m_stall_s.inc(gap)
                self.m_idle_gap.observe(gap)
            self._busy_start = now
        self._inflight += 1
        self.m_inflight.set(self._inflight)

    def _track_complete(self, now: float) -> None:
        self._inflight -= 1
        self.m_inflight.set(self._inflight)
        if self._inflight == 0:
            self.m_busy_s.inc(now - self._busy_start)
            self._last_idle_start = now

    def duty_cycle(self) -> float:
        """Busy fraction since the first dispatch (1.0 = device never idle)."""
        busy, stall = self.m_busy_s.value, self.m_stall_s.value
        total = busy + stall
        return busy / total if total > 0 else 0.0

    def _ensure_sems(self) -> None:
        """(Re)bind the in-flight/prefetch/depth semaphores to the CURRENT
        loop."""
        loop = asyncio.get_running_loop()
        if self._sem_loop is not loop:
            self._inflight_sem = asyncio.Semaphore(self.max_in_flight)
            self._prefetch_sem = asyncio.Semaphore(self.max_in_flight + 1)
            # depth > 1: bounds DISPATCHED-NOT-FETCHED steps (each holds a
            # permit from before its enqueue until its outputs are fetched)
            # — without it, concurrent callers releasing the in-flight
            # permit at dispatch could queue arbitrarily many steps on the
            # device and defeat both backpressure and the staging-pool cap
            self._depth_sem = asyncio.Semaphore(self.dispatch_depth)
            self._sem_loop = loop

    async def infer(self, inputs: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        """Pipelined inference: host prep off-loop, bounded async dispatch.

        Concurrent callers (the stream's processor workers) keep up to
        ``max_in_flight`` steps queued on the device, so step n+1's host
        prep + infeed overlaps step n's compute instead of serializing
        pad -> dispatch -> device_get per batch.
        """
        import time

        loop = asyncio.get_running_loop()
        n_total = next(iter(inputs.values())).shape[0]
        mb = self.buckets.max_batch()
        if n_total > mb and not self.packed:
            # concurrent chunks: the in-flight semaphore bounds device queue
            # depth, so chunk n+1 preps/dispatches while chunk n computes
            # (serial awaits would idle the device between chunks)
            chunks = await asyncio.gather(*[
                self.infer({k: v[i:i + mb] for k, v in inputs.items()})
                for i in range(0, n_total, mb)
            ])
            return {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}
        await self.core.heal_gate()
        t_prep0 = time.perf_counter()
        padded, n = await loop.run_in_executor(None, self._prep, inputs)
        record_stage("infeed_prep", time.perf_counter() - t_prep0)
        first = self._note_shape(padded)
        bucket_rows = next(iter(padded.values())).shape[0]
        deadline = self.core.deadline_for(first)
        staged = padded  # host staging buffers, recycled once the step ends

        self._ensure_sems()

        async def step(padded):
            t_sem = time.perf_counter()
            # first-seen shapes compile synchronously inside the dispatch;
            # they take the classic fully-watched path so the first-compile
            # deadline budget covers the compile, not just the fetch
            if self.dispatch_depth > 1 and not first:
                return await step_split(padded, t_sem)
            async with self._inflight_sem:
                t0 = time.perf_counter()
                if t0 - t_sem > 0.0005:
                    # waiting on the in-flight window is device queueing,
                    # not compute — its own stage so the breakdown shows it
                    record_stage("device_dispatch_wait", t0 - t_sem)
                self._track_dispatch(t0)
                try:
                    if deadline is None:
                        out = await loop.run_in_executor(
                            None, self._step_blocking, padded)
                    else:
                        # the shared core's watchdog: wait for the step, not
                        # forever, on a borrowed dedicated thread; on a miss
                        # the zombie's eventual end recycles the staging
                        # buffers (on_zombie)
                        out = await self.core.run_deadlined(
                            partial(self._step_blocking, padded), deadline,
                            on_zombie=partial(self._release_staging, staged))
                finally:
                    # an abandoned step counts as complete for duty-cycle
                    # accounting: the device is no longer doing useful work
                    self._track_complete(time.perf_counter())
                dt = time.perf_counter() - t0
                self.m_infer.observe(dt)
                if not first:
                    self._pp_observe(padded, dt)
                # first-compile steps get their own stage: one compile can
                # be 1000x a warm step, and mixing the two makes both the
                # p99 and the share-of-e2e unreadable
                record_stage("device_step_first" if first else "device_step",
                             dt, attrs={"bucket_rows": bucket_rows})
                return out

        async def step_split(padded, t_sem):
            # dispatch_depth > 1: the in-flight permit covers the DISPATCH
            # only — once the device queue holds the step, the permit frees
            # and the next worker's step dispatches while this one's output
            # fetch (device sync + host copy) proceeds off the critical
            # path. The outer DEPTH permit is held from before the enqueue
            # until the fetch completes, so dispatched-not-fetched steps
            # never exceed dispatch_depth no matter how many callers fan
            # out (chunked batches gather N concurrent infer calls) — that
            # is the device-memory backpressure AND the bound the staging
            # pool is sized against. Deadline semantics per in-flight step:
            # the fetch budget runs from this step's own enqueue, never
            # from when the host got around to waiting
            # (serving_core.deadline_remaining).
            async with self._depth_sem:
                async with self._inflight_sem:
                    t0 = time.perf_counter()
                    if t0 - t_sem > 0.0005:
                        record_stage("device_dispatch_wait", t0 - t_sem)
                    self._track_dispatch(t0)
                    try:
                        dev_out = await loop.run_in_executor(
                            None, self._enqueue_step, padded)
                    except BaseException:
                        self._track_complete(time.perf_counter())
                        raise
                    dispatched_at = time.monotonic()

                def fetch():
                    self.core.apply_chaos()
                    return self.core.corrupt_outputs(jax.device_get(dev_out))

                try:
                    if deadline is None:
                        out = await loop.run_in_executor(None, fetch)
                    else:
                        out = await self.core.run_deadlined(
                            fetch,
                            self.core.deadline_remaining(
                                deadline, dispatched_at),
                            on_zombie=partial(self._release_staging, staged))
                finally:
                    self._track_complete(time.perf_counter())
            dt = time.perf_counter() - t0
            self.m_infer.observe(dt)
            if not first:
                self._pp_observe(padded, dt)
            record_stage("device_step_first" if first else "device_step",
                         dt, attrs={"bucket_rows": bucket_rows})
            return out

        try:
            if self._prefetch:
                # eager infeed: batch n+1's host->device copies run here,
                # outside the in-flight semaphore, overlapping batch n's
                # compute (sharded per-chip copies under a mesh). The
                # prefetch semaphore (in_flight + 1 permits, held through
                # the step) caps how many padded batches can sit in device
                # memory ahead of the compute queue.
                async with self._prefetch_sem:
                    padded = await loop.run_in_executor(None, self._to_device, padded)
                    out = await step(padded)
            else:
                out = await step(padded)
        except StepDeadlineExceeded:
            staged = None  # the abandoned step still owns the buffers; the
            raise          # miss handler recycles them when it finally ends
        except Exception as e:
            if is_oom_error(e):
                if not self.packed and self._note_oom(bucket_rows):
                    # the finally below recycles the staging buffers (the
                    # step ended with an error, so nothing reads them)
                    return await self.infer(inputs)  # re-chunk on the capped grid
                if self.packed:
                    # can't re-slice a packed layout here; cap the grid so the
                    # REDELIVERED batch repacks against servable buckets
                    self._note_oom(bucket_rows)
            raise
        finally:
            # after device_get nothing can still read the host buffers —
            # even a CPU backend that aliased them zero-copy is done
            if staged is not None:
                self._release_staging(staged)
        self.m_rows.inc(n)
        self.health.mark_success()
        return {k: np.asarray(v)[:n] for k, v in out.items()}

    def warmup(self, seq_lens: Optional[list[int]] = None) -> int:
        """Precompile the bucket grid; returns number of executables built.

        Packed mode warms every reachable (row-bucket, example-bucket) pair:
        the row dim P lands in a smaller-or-equal bucket than the example dim
        E (each packed row holds >= 1 example), with E drawn from the
        extended example grid (``BucketPolicy.example_buckets``) — so the
        upper-triangular grid covers all shapes packed traffic can produce:
        full token-budget chunks (eb up to max_examples) and tail chunks
        alike. The persistent compile cache makes this a one-time cost per
        host.
        """
        count = 0
        has_seq = any("seq" in t for _, t in self.spec.values())
        seqs = seq_lens or (list(self.buckets.seq_buckets) if has_seq else [None])
        if self.packed:
            pairs = [(pb, eb) for eb in self.buckets.example_buckets()
                     for pb in self.buckets.batch_buckets if pb <= eb]
        else:
            pairs = [(bb, bb) for bb in self.buckets.batch_buckets]
        self._in_warmup = True
        try:
            for pb, eb in pairs:
                for sl in seqs:
                    fake = {}
                    for name, (dtype, trailing) in self.spec.items():
                        lead = eb if self.packed and "seq" not in trailing else pb
                        dims = tuple(sl if d == "seq" else d for d in trailing)
                        fake[name] = np.zeros((lead, *dims), dtype=dtype)
                    self.infer_sync(fake)
                    count += 1
            if self._pp_plan is not None:
                # probe each seq bucket's tick cost while the device is
                # quiet, so the first measured bubble has its reference
                for sl in seqs:
                    if sl:
                        self._pp_ensure_tick(sl)
        finally:
            self._in_warmup = False
        logger.info("[%s] warmed %d bucket executables", self.family.name, count)
        return count
