"""Shape bucketing: the bridge between ragged streams and XLA static shapes.

XLA compiles one executable per input shape. A streaming engine sees ragged
batch sizes and sequence lengths, so the runner pads every micro-batch up to a
small set of (batch, seq) buckets and keeps the compiled executable for each
bucket warm (SURVEY.md section 7 "hard parts" (a); the buffer layer owns
right-sizing, this module owns the bucket policy + padding).

Defaults are powers of two — each dimension at most doubles, so padding waste
is bounded by 50% and the executable count stays logarithmic.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from arkflow_tpu.errors import ConfigError


def pow2_buckets(lo: int, hi: int) -> list[int]:
    out = []
    b = max(1, lo)
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


@dataclass(frozen=True)
class BucketPolicy:
    batch_buckets: tuple[int, ...] = tuple(pow2_buckets(8, 256))
    seq_buckets: tuple[int, ...] = tuple(pow2_buckets(32, 512))

    @classmethod
    def from_config(cls, config: dict, *, max_batch: Optional[int] = None,
                    max_seq: Optional[int] = None) -> "BucketPolicy":
        bb = config.get("batch_buckets")
        sb = config.get("seq_buckets")
        if bb is None:
            bb = pow2_buckets(8, max_batch or 256)
        if sb is None:
            sb = pow2_buckets(32, max_seq or 512)
        bb = tuple(sorted(int(x) for x in bb))
        sb = tuple(sorted(int(x) for x in sb))
        if not bb or not sb or bb[0] <= 0 or sb[0] <= 0:
            raise ConfigError("bucket lists must be non-empty positive ints")
        return cls(bb, sb)

    @staticmethod
    def _pick(n: int, buckets: Sequence[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def batch_bucket(self, n: int) -> int:
        return self._pick(n, self.batch_buckets)

    def seq_bucket(self, n: int) -> int:
        return self._pick(n, self.seq_buckets)

    def max_batch(self) -> int:
        return self.batch_buckets[-1]


def pad_batch_dim(arr: np.ndarray, target: int) -> np.ndarray:
    """Pad axis 0 with zeros up to ``target`` rows."""
    n = arr.shape[0]
    if n == target:
        return arr
    if n > target:
        raise ValueError(f"batch {n} exceeds bucket {target}")
    pad = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def pad_seq_dim(arr: np.ndarray, target: int, axis: int = 1) -> np.ndarray:
    n = arr.shape[axis]
    if n == target:
        return arr
    if n > target:
        slicer = [slice(None)] * arr.ndim
        slicer[axis] = slice(0, target)
        return arr[tuple(slicer)]
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, target - n)
    return np.pad(arr, pad)
