"""Shape bucketing: the bridge between ragged streams and XLA static shapes.

XLA compiles one executable per input shape. A streaming engine sees ragged
batch sizes and sequence lengths, so the runner pads every micro-batch up to a
small set of (batch, seq) buckets and keeps the compiled executable for each
bucket warm (SURVEY.md section 7 "hard parts" (a); the buffer layer owns
right-sizing, this module owns the bucket policy + padding).

Defaults are powers of two — each dimension at most doubles, so padding waste
is bounded by 50% and the executable count stays logarithmic.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional, Sequence

import numpy as np

from arkflow_tpu.errors import ConfigError

if TYPE_CHECKING:
    from arkflow_tpu.batch import MessageBatch
    from arkflow_tpu.components.base import Ack


def pow2_buckets(lo: int, hi: int) -> list[int]:
    out = []
    b = max(1, lo)
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


@dataclass(frozen=True)
class BucketPolicy:
    batch_buckets: tuple[int, ...] = tuple(pow2_buckets(8, 256))
    seq_buckets: tuple[int, ...] = tuple(pow2_buckets(32, 512))
    #: packed serving only: how far past the row grid the EXAMPLE-dim bucket
    #: grid extends (a packed row holds several examples, so a full row
    #: bucket of short texts carries ~seq/len(example) times more examples
    #: than rows). 1 keeps the example grid identical to the row grid.
    example_scale: int = 1

    @classmethod
    def from_config(cls, config: dict, *, max_batch: Optional[int] = None,
                    max_seq: Optional[int] = None,
                    default_example_scale: int = 1) -> "BucketPolicy":
        bb = config.get("batch_buckets")
        sb = config.get("seq_buckets")
        if bb is None:
            bb = pow2_buckets(8, max_batch or 256)
        if sb is None:
            sb = pow2_buckets(32, max_seq or 512)
        bb = tuple(sorted(int(x) for x in bb))
        sb = tuple(sorted(int(x) for x in sb))
        if not bb or not sb or bb[0] <= 0 or sb[0] <= 0:
            raise ConfigError("bucket lists must be non-empty positive ints")
        es = config.get("example_scale", default_example_scale)
        if not isinstance(es, int) or isinstance(es, bool) or es < 1:
            raise ConfigError(
                f"example_scale must be an int >= 1, got {es!r}")
        return cls(bb, sb, es)

    @staticmethod
    def _pick(n: int, buckets: Sequence[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def batch_bucket(self, n: int) -> int:
        return self._pick(n, self.batch_buckets)

    def seq_bucket(self, n: int) -> int:
        return self._pick(n, self.seq_buckets)

    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    # -- packed serving: example-dim grid + token-budget grid ---------------

    def example_buckets(self) -> tuple[int, ...]:
        """Bucket grid for the packed path's EXAMPLE dim: the row grid,
        pow2-extended up to ``max_batch * example_scale`` (and at least the
        top seq bucket, so one worst-case row of minimum-length examples
        always has a servable example bucket). Derived from the row grid on
        purpose: ``capped``/``dp_scaled`` rescale it automatically."""
        out = list(self.batch_buckets)
        top = self.batch_buckets[-1]
        want = max(top * self.example_scale, self.seq_buckets[-1]) \
            if self.example_scale > 1 else top
        while top < want:
            top *= 2
            out.append(top)
        return tuple(out)

    def example_bucket(self, n: int) -> int:
        return self._pick(n, self.example_buckets())

    def max_examples(self) -> int:
        return self.example_buckets()[-1]

    def token_buckets(self, seq: int) -> tuple[int, ...]:
        """Token-budget grid for packed serving at row width ``seq``: each
        batch bucket's row capacity in tokens (rows x seq). Composes with
        ``dp_scaled`` (batch buckets already carry the x dp) and ``capped``
        (OOM-dropped row buckets vanish from the token grid too)."""
        if seq < 1:
            raise ConfigError(f"token_buckets seq must be >= 1, got {seq}")
        return tuple(b * seq for b in self.batch_buckets)

    def token_budget(self, seq: int) -> int:
        """Tokens that fill the LARGEST compiled (rows, seq) shape — the
        natural emission target for a token-budget coalescer feeding
        ``pack_tokens``."""
        return self.token_buckets(seq)[-1]

    def capped(self, below: int) -> Optional["BucketPolicy"]:
        """OOM degradation: the grid with only batch buckets strictly below
        ``below`` (the bucket the device just failed to hold). ``None`` when
        no smaller bucket exists — the caller can't degrade further and must
        surface the failure instead."""
        smaller = tuple(b for b in self.batch_buckets if b < below)
        if not smaller:
            return None
        return BucketPolicy(smaller, self.seq_buckets, self.example_scale)

    def dp_scaled(self, dp: int) -> "BucketPolicy":
        """The policy for dp-sharded dispatch: every batch bucket times
        ``dp``, so each global bucket splits into per-chip shards that land
        EXACTLY on this policy's original grid (a [8,16,32] policy at dp=4
        compiles global buckets [32,64,128] = per-chip [8,16,32]). Scaling by
        multiplication — rather than rounding up to a multiple — keeps
        per-chip shapes bucket-exact and makes divisibility by dp structural
        rather than checked per dispatch."""
        if dp < 1:
            raise ConfigError(f"dp must be >= 1, got {dp}")
        if dp == 1:
            return self
        return BucketPolicy(tuple(b * dp for b in self.batch_buckets),
                            self.seq_buckets, self.example_scale)


class BucketCapBus:
    """Process-wide fanout of device OOM bucket caps to live coalescers.

    The runner and the memory buffer's coalescer are independent components
    wired from different config sections; when the device proves it cannot
    hold a bucket (``RESOURCE_EXHAUSTED``), the runner caps its own grid AND
    announces the cap here so every registered coalescer stops merging
    emissions the device will just OOM on again. Process-global on purpose:
    one host serves one device topology, and a cap is a statement about the
    device, not about any single stream.

    Thread-tolerant: ``announce`` runs on runner executor threads while
    coalescers live on the event loop — ``cap()`` only shrinks a tuple and an
    int, both atomic reassignments, so the worst case is one more emission at
    the old target (which the runner then splits, not loses).
    """

    def __init__(self) -> None:
        import threading
        import weakref

        self._lock = threading.Lock()
        self._coalescers: "weakref.WeakSet[MicroBatchCoalescer]" = weakref.WeakSet()
        self._cap: Optional[int] = None
        #: shape listeners (memory buffers): objects with a
        #: ``retarget_shapes(batch_buckets, token_budget, deadline_s)``
        #: method — they own the coalesce deadline and the kwargs late
        #: tenant lanes are minted from, which no single coalescer can see
        self._listeners: "weakref.WeakSet" = weakref.WeakSet()

    @property
    def cap(self) -> Optional[int]:
        return self._cap

    def register(self, coalescer: "MicroBatchCoalescer") -> None:
        with self._lock:
            self._coalescers.add(coalescer)
            if self._cap is not None:
                coalescer.cap(self._cap)

    def register_listener(self, listener) -> None:
        """Register a buffer-level shape listener for future retargets.
        Unlike caps, committed retargets are NOT replayed onto late
        registrations: a cap is a device fact, a retarget is one stream's
        tuning preference — a component built later starts on its
        configured grid and follows from the tuner's next commit (the row
        grid a commit ``expect``-matches against never changes, so the next
        commit always reaches it)."""
        with self._lock:
            self._listeners.add(listener)

    def announce(self, cap: int) -> None:
        with self._lock:
            self._cap = cap if self._cap is None else min(self._cap, cap)
            for c in list(self._coalescers):
                c.cap(self._cap)

    def _clamped(self, buckets: tuple[int, ...],
                 token_budget: Optional[int]) -> tuple[tuple[int, ...], Optional[int]]:
        """An OOM cap always wins over a retarget: clamp the broadcast grid
        (and scale the budget like ``MicroBatchCoalescer.cap`` does) so a
        tuner commit can never re-grow buckets the device proved it cannot
        hold."""
        if self._cap is None or not buckets:
            return buckets, token_budget
        fitting = tuple(b for b in buckets if b <= self._cap)
        if not fitting:
            fitting = (max(1, int(self._cap)),)
        if token_budget is not None and fitting[-1] != buckets[-1]:
            token_budget = max(1, int(token_budget * fitting[-1] / buckets[-1]))
        return fitting, token_budget

    def clamp(self, batch_buckets: Sequence[int],
              token_budget: Optional[int] = None
              ) -> tuple[tuple[int, ...], Optional[int]]:
        """Apply the current OOM cap (if any) to a grid/budget pair —
        stream-bound retargets (which bypass the broadcast) clamp through
        here so a cap is honored no matter which path a flip takes."""
        with self._lock:
            return self._clamped(tuple(int(b) for b in batch_buckets),
                                 token_budget)

    def retarget(self, batch_buckets: Sequence[int], *,
                 token_budget: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 expect: Optional[Sequence[int]] = None) -> None:
        """Shape-tuner commit fanout: live coalescers whose CURRENT grid
        matches ``expect`` (None = all) adopt the new grid/budget, and
        buffer listeners additionally adopt the new coalesce deadline.
        Scoped by ``expect`` on purpose — the bus is process-global, and a
        retune of one stream's shapes must not disturb another stream's
        bucket-exactness. The OOM cap, when present, clamps the broadcast
        (a cap is a statement about the device; a retarget is merely a
        preference)."""
        bb = tuple(sorted(int(b) for b in batch_buckets))
        exp = tuple(sorted(int(b) for b in expect)) if expect is not None else None
        with self._lock:
            cb, ct = self._clamped(bb, token_budget)
            for c in list(self._coalescers):
                if exp is None or c.buckets == exp:
                    c.retarget(cb, ct)
            for listener in list(self._listeners):
                try:
                    listener.retarget_shapes(cb, ct, deadline_s, expect=exp)
                except Exception:
                    import logging

                    logging.getLogger("arkflow.tpu").exception(
                        "bucket retarget listener failed")

    def reset(self) -> None:
        """Test hook: forget the cap and any registrations (coalescers
        already shrunk/retargeted stay as they are)."""
        with self._lock:
            self._cap = None
            self._coalescers.clear()
            self._listeners.clear()


_GLOBAL_CAP_BUS = BucketCapBus()


def bucket_cap_bus() -> BucketCapBus:
    return _GLOBAL_CAP_BUS


class MicroBatchCoalescer:
    """Merges sub-bucket micro-batches into bucket-exact emissions.

    Streaming sources emit whatever batch size the broker delivered; padding
    each one to its compiled bucket alone wastes MXU cycles on zero rows
    (``arkflow_padding_waste_frac``). The coalescer holds written
    ``(batch, ack)`` pairs and carves emissions of EXACTLY the largest
    compiled batch bucket — splitting the batch that straddles the boundary
    and sharing its ack across the two emissions via ``split_ack`` — so
    steady-state device steps run at fill ratio 1.0. The caller (the memory
    buffer plugin) owns the deadline that bounds how long rows wait for a
    full bucket; ``pop_flush`` carves the remainder bucket-exact on
    deadline/close.

    Token-budget mode (``token_budget``): pending work is bucketed by TOTAL
    TOKEN COUNT instead of row count — per-row token estimates come from the
    payload column's Arrow offsets (``extract.payload_token_estimates``: one
    vectorized pass, no per-row Python), and emissions carve the row prefix
    whose token sum fills ``token_budget``. The budget is sized to fill a
    compiled ``(rows, seq)`` shape after ``pack_tokens`` packing
    (``BucketPolicy.token_budget(seq)``), so the packed row count lands
    bucket-exact where row-count carving would leave the packer starved or
    overflowing. Splits still happen on ROW boundaries (rows are atomic),
    with the same ``split_ack`` share semantics as row mode.

    At-least-once is preserved: every emission carries a composite ack over
    the source acks (or their split shares), so a quarantined merged batch
    acks exactly the source batches whose rows it contained, and a nacked
    one redelivers them.

    Poison isolation: the stream counts delivery attempts per MERGED batch
    fingerprint, so a poison source batch whose redeliveries kept regrouping
    with fresh traffic would mint a new fingerprint every round and nack-loop
    forever. The coalescer therefore watches its own emission acks — sources
    of a nacked emission are marked suspect, and a suspect batch re-arriving
    is emitted SOLO (stable fingerprint), so the stream's attempt budget
    converges and quarantine fires. A suspect that then succeeds is cleared.
    """

    #: bound on the suspect table; entries clear on ack, so this only
    #: matters with thousands of concurrently failing source batches
    MAX_SUSPECTS = 1024

    def __init__(self, batch_buckets: Sequence[int], *,
                 token_budget: Optional[int] = None,
                 token_field: Optional[str] = None,
                 token_bytes: Optional[float] = None,
                 max_row_tokens: Optional[int] = None):
        buckets = tuple(sorted(int(b) for b in batch_buckets))
        if not buckets or buckets[0] <= 0:
            raise ConfigError("coalesce batch_buckets must be non-empty positive ints")
        if token_budget is not None and token_budget < 1:
            raise ConfigError(
                f"coalesce token_budget must be a positive int, got {token_budget}")
        if token_bytes is not None and token_bytes <= 0:
            raise ConfigError(
                f"coalesce token_bytes must be positive, got {token_bytes}")
        if max_row_tokens is not None and max_row_tokens < 1:
            raise ConfigError(
                f"coalesce max_row_tokens must be >= 1, got {max_row_tokens}")
        self.buckets = buckets
        self.target = buckets[-1]
        #: token-budget mode: emissions carve this many estimated tokens
        #: instead of ``target`` rows (None = row mode)
        self.token_budget = int(token_budget) if token_budget is not None else None
        self._token_field = token_field
        self._token_bytes = token_bytes
        self._max_row_tokens = max_row_tokens
        #: held entries: (batch, ack, token-estimates, monotonic add time) —
        #: the add time of the oldest row consumed by a pop becomes
        #: ``last_pop_wait_s``, the coalescer-wait the trace layer records
        self._held: deque[tuple["MessageBatch", "Ack", Optional[np.ndarray], float]] = deque()
        #: suspect (previously-nacked) batches, emitted alone and first
        self._solo: deque[tuple["MessageBatch", "Ack", Optional[np.ndarray], float]] = deque()
        #: monotonic wait of the oldest row in the LAST popped emission
        self.last_pop_wait_s: float = 0.0
        #: fingerprint -> row count of each currently-suspect source batch
        self._suspects: dict[bytes, int] = {}
        #: cheap prefilter so healthy adds/acks skip hashing: row counts of
        #: current suspects (hash only on a row-count match)
        self._suspect_rows: set[int] = set()
        self._rows = 0
        self._tokens = 0

    @property
    def rows(self) -> int:
        return self._rows

    @property
    def tokens(self) -> int:
        """Estimated tokens held (token-budget mode; 0 in row mode)."""
        return self._tokens

    @property
    def pending(self) -> int:
        """Held entries — covers zero-row batches whose acks still await."""
        return len(self._held) + len(self._solo)

    def cap(self, max_bucket: int) -> None:
        """Shrink the target grid after a device OOM (see ``BucketCapBus``):
        drop buckets above ``max_bucket`` so future emissions stay within
        what the device can actually hold. If even the smallest bucket is
        above the cap, the cap itself becomes the only bucket. Already-held
        rows simply drain at the new, smaller target. Token-budget mode
        shrinks the token budget by the same ratio: the budget was sized to
        fill the old top (rows, seq) shape, and the device just proved it
        cannot hold that many rows."""
        fitting = tuple(b for b in self.buckets if b <= max_bucket)
        if not fitting:
            fitting = (max(1, int(max_bucket)),)
        if fitting == self.buckets:
            return
        if self.token_budget is not None:
            self.token_budget = max(
                1, int(self.token_budget * fitting[-1] / self.target))
        self.buckets = fitting
        self.target = fitting[-1]

    def retarget(self, batch_buckets: Sequence[int],
                 token_budget: Optional[int] = None) -> None:
        """Adopt a NEW target grid (shape-tuner flip; see ``BucketCapBus.
        retarget``). Unlike ``cap`` this may move buckets in either
        direction — the tuner only broadcasts after the runner's grid
        already flipped and every new shape is warm, so emissions carved at
        the new target land on compiled executables. Already-held rows
        simply drain at the new target. The token budget updates only when
        the coalescer is ALREADY in token mode (a mode flip would change
        emission semantics under the buffer's feet); ``None`` leaves the
        budget untouched."""
        buckets = tuple(sorted(int(b) for b in batch_buckets))
        if not buckets or buckets[0] <= 0:
            return
        self.buckets = buckets
        self.target = buckets[-1]
        if token_budget is not None and self.token_budget is not None:
            self.token_budget = max(1, int(token_budget))

    # -- token estimation (token-budget mode) -------------------------------

    def _row_tokens(self, batch: "MessageBatch") -> np.ndarray:
        """Per-row token estimates off the payload column's Arrow offsets
        (zero per-row Python; see ``extract.payload_token_estimates``).
        Batches without a usable payload column estimate conservatively —
        each row counts as ``max_row_tokens`` (or 1) — so malformed traffic
        still flows instead of wedging the budget accounting."""
        from arkflow_tpu.errors import ArkError
        from arkflow_tpu.tpu.extract import payload_token_estimates

        from arkflow_tpu.batch import DEFAULT_BINARY_VALUE_FIELD

        field = self._token_field or DEFAULT_BINARY_VALUE_FIELD
        try:
            col = batch.column(field)
            return payload_token_estimates(
                col, token_bytes=self._token_bytes,
                max_tokens=self._max_row_tokens)
        except ArkError:
            return np.full(batch.num_rows, self._max_row_tokens or 1,
                           dtype=np.int64)

    # -- suspect tracking (hashing only on failure paths, plus on adds/acks
    # -- that pass the row-count prefilter while failures are outstanding —
    # -- the all-healthy pipeline never serializes a batch) ----------------

    @staticmethod
    def _fingerprint(batch: "MessageBatch") -> bytes:
        """Shared with the stream's attempt budget (``batch_fingerprint``):
        solo-emission convergence requires the two to hash identically."""
        from arkflow_tpu.batch import batch_fingerprint

        return batch_fingerprint(batch)

    def _mark_suspect(self, batch: "MessageBatch") -> None:
        key = self._fingerprint(batch)
        if key not in self._suspects and len(self._suspects) >= self.MAX_SUSPECTS:
            self._suspects.pop(next(iter(self._suspects)))
        self._suspects[key] = batch.num_rows
        self._suspect_rows.add(batch.num_rows)

    def _clear_suspect(self, batch: "MessageBatch") -> None:
        if batch.num_rows not in self._suspect_rows:
            return  # prefilter: healthy acks never pay the hash either
        if self._suspects.pop(self._fingerprint(batch), None) is not None:
            self._suspect_rows = set(self._suspects.values())

    def _observed(self, batch: "MessageBatch", ack: "Ack") -> "Ack":
        """Wrap a source ack so emission outcomes feed the suspect table."""
        return _SuspectObserverAck(self, batch, ack)

    def add(self, batch: "MessageBatch", ack: "Ack") -> None:
        import time

        ack = self._observed(batch, ack)
        lens = self._row_tokens(batch) if self.token_budget is not None else None
        entry = (batch, ack, lens, time.monotonic())
        if (batch.num_rows in self._suspect_rows
                and self._fingerprint(batch) in self._suspects):
            self._solo.append(entry)
        else:
            self._held.append(entry)
        self._rows += batch.num_rows
        if lens is not None:
            self._tokens += int(lens.sum())

    def _note_wait(self, oldest_t_add: float) -> None:
        import time

        self.last_pop_wait_s = max(0.0, time.monotonic() - oldest_t_add)

    def _carve(self, rows: int) -> tuple["MessageBatch", "Ack"]:
        """Take exactly ``rows`` held rows as one merged emission, splitting
        the boundary batch (its source ack is shared across both emissions)."""
        from arkflow_tpu.batch import MessageBatch
        from arkflow_tpu.components.base import VecAck, split_ack

        parts: list["MessageBatch"] = []
        acks: list["Ack"] = []
        need = rows
        self._note_wait(self._held[0][3])
        while need > 0:
            batch, ack, _, t_add = self._held.popleft()
            if batch.num_rows <= need:
                parts.append(batch)
                acks.append(ack)
                need -= batch.num_rows
            else:
                head_ack, tail_ack = split_ack(ack, 2)
                parts.append(batch.slice(0, need))
                acks.append(head_ack)
                # the tail keeps its ORIGINAL add time: its rows have been
                # waiting since then, and the next pop's wait must say so
                self._held.appendleft((batch.slice(need), tail_ack, None, t_add))
                need = 0
        self._rows -= rows
        return MessageBatch.concat(parts), VecAck(acks)

    def _carve_tokens(self, budget: int) -> tuple["MessageBatch", "Ack"]:
        """Take the longest held row prefix whose estimated token sum fits
        ``budget``, splitting the boundary batch at a ROW edge (rows are
        atomic; the boundary source ack is shared via ``split_ack``). A
        single row whose estimate alone exceeds the budget emits solo —
        downstream packing/truncation owns over-long rows."""
        from arkflow_tpu.batch import MessageBatch
        from arkflow_tpu.components.base import VecAck, split_ack

        parts: list["MessageBatch"] = []
        acks: list["Ack"] = []
        took_rows = 0
        took_tokens = 0
        need = budget
        if self._held:
            self._note_wait(self._held[0][3])
        while need > 0 and self._held:
            batch, ack, lens, t_add = self._held[0]
            total = int(lens.sum())
            if total <= need:
                self._held.popleft()
                parts.append(batch)
                acks.append(ack)
                took_rows += batch.num_rows
                took_tokens += total
                need -= total
                continue
            # boundary batch: rows [0, k) fit the remaining budget
            cs = np.cumsum(lens)
            k = int(np.searchsorted(cs, need, side="right"))
            if k == 0:
                if parts:
                    break  # next row alone would overflow; emit under-budget
                k = 1  # a single over-budget row still has to flow
            if k >= batch.num_rows:
                # the whole batch fits after all (a single over-budget row):
                # take it intact — splitting would strand an empty tail and
                # its ack share in the queue
                self._held.popleft()
                parts.append(batch)
                acks.append(ack)
                took_rows += batch.num_rows
                took_tokens += total
                break
            self._held.popleft()
            head_ack, tail_ack = split_ack(ack, 2)
            parts.append(batch.slice(0, k))
            acks.append(head_ack)
            self._held.appendleft((batch.slice(k), tail_ack, lens[k:], t_add))
            took_rows += k
            took_tokens += int(cs[k - 1])
            break
        self._rows -= took_rows
        self._tokens -= took_tokens
        return MessageBatch.concat(parts), VecAck(acks)

    def _pop_solo(self) -> Optional[tuple["MessageBatch", "Ack"]]:
        if not self._solo:
            return None
        batch, ack, lens, t_add = self._solo.popleft()
        self._note_wait(t_add)
        self._rows -= batch.num_rows
        if lens is not None:
            self._tokens -= int(lens.sum())
        return batch, ack

    def pop_exact(self) -> Optional[tuple["MessageBatch", "Ack"]]:
        """Next emission: a suspect batch alone (stable fingerprint for the
        stream's attempt budget), else exactly ``target`` carved rows (row
        mode) / a ``token_budget``-filling row prefix (token mode)."""
        emission = self._pop_solo()
        if emission is not None:
            return emission
        if self.token_budget is not None:
            if self._tokens < self.token_budget:
                return None
            return self._carve_tokens(self.token_budget)
        if self._rows < self.target:
            return None
        return self._carve(self.target)

    def pop_flush(self) -> Optional[tuple["MessageBatch", "Ack"]]:
        """Deadline/close flush, one emission per call: carve the LARGEST
        bucket that the held rows fill exactly (so a 40-row flush against
        buckets [8,16,32] emits 32 then 8, zero padding), and only the
        sub-minimum remainder emits unpadded-to-bucket as one merged batch.
        Token mode: full-budget emissions first, then the whole remainder as
        one merged batch — the packer right-sizes its row count to a smaller
        bucket, so sub-budget flushes stay dense. Suspects drain through
        ``pop_exact`` first."""
        from arkflow_tpu.batch import MessageBatch
        from arkflow_tpu.components.base import VecAck

        emission = self.pop_exact()
        if emission is not None:
            return emission
        if not self._held:
            return None
        if self.token_budget is not None:
            self._note_wait(self._held[0][3])
            self._tokens = 0
            self._rows -= sum(b.num_rows for b, _, _, _ in self._held)
            parts = [b for b, _, _, _ in self._held]
            acks = VecAck([a for _, a, _, _ in self._held])
            self._held.clear()
            return MessageBatch.concat(parts), acks
        held_rows = self._rows
        fitting = [b for b in self.buckets if b <= held_rows]
        if fitting:
            return self._carve(fitting[-1])
        self._note_wait(self._held[0][3])
        parts = [b for b, _, _, _ in self._held]
        acks = VecAck([a for _, a, _, _ in self._held])
        self._held.clear()
        self._rows = 0
        return MessageBatch.concat(parts), acks


class _SuspectObserverAck:
    """Source-ack wrapper feeding emission outcomes back to the coalescer's
    suspect table: a nack marks the batch suspect (its redelivery emits
    solo), a final ack — delivered or quarantined — clears it."""

    __slots__ = ("_coalescer", "_batch", "_inner")

    def __init__(self, coalescer: MicroBatchCoalescer, batch: "MessageBatch",
                 inner: "Ack"):
        self._coalescer = coalescer
        self._batch = batch
        self._inner = inner

    @property
    def redeliverable(self) -> bool:
        return bool(getattr(self._inner, "redeliverable", False))

    async def ack(self) -> None:
        self._coalescer._clear_suspect(self._batch)
        await self._inner.ack()

    async def nack(self) -> None:
        # mark BEFORE the inner nack: the broker may requeue synchronously,
        # and the redelivered write must already see the suspicion
        self._coalescer._mark_suspect(self._batch)
        await self._inner.nack()


def pad_batch_dim(arr: np.ndarray, target: int) -> np.ndarray:
    """Pad axis 0 with zeros up to ``target`` rows."""
    n = arr.shape[0]
    if n == target:
        return arr
    if n > target:
        raise ValueError(f"batch {n} exceeds bucket {target}")
    pad = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def pad_seq_dim(arr: np.ndarray, target: int, axis: int = 1) -> np.ndarray:
    n = arr.shape[axis]
    if n == target:
        return arr
    if n > target:
        slicer = [slice(None)] * arr.ndim
        slicer[axis] = slice(0, target)
        return arr[tuple(slicer)]
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, target - n)
    return np.pad(arr, pad)
