from arkflow_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    global_registry,
)
from arkflow_tpu.obs.trace import (  # noqa: F401
    Span,
    TraceContext,
    Tracer,
    TracingConfig,
    activate,
    global_tracer,
    record_stage,
    stage_span,
)
