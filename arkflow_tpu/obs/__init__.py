from arkflow_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    global_registry,
)
