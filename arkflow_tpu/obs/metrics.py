"""First-class metrics: counters, gauges, histograms + Prometheus exposition.

The reference declares ``prometheus = "0.13"`` but never uses it — there is no
metrics endpoint (SURVEY.md section 5; verified zero references in
crates/**/*.rs). The BASELINE metric is rows/sec + p50/p99, so here
throughput/latency instrumentation is built into the runtime rather than
bolted on: stream stages update these metrics on the hot path and the engine
serves ``/metrics`` in Prometheus text format.

Implementation notes: asyncio runs stages on one thread, so plain Python
arithmetic is race-free; histograms keep fixed log-spaced buckets plus a
bounded reservoir for exact small-N quantiles.
"""

from __future__ import annotations

import math
import random
import time
from typing import Iterable, Optional


class Counter:
    __slots__ = ("name", "help", "labels", "value")

    def __init__(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    __slots__ = ("name", "help", "labels", "value")

    def __init__(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


#: default latency buckets: 0.1ms .. ~100s, log-spaced
_DEFAULT_BUCKETS = tuple(0.0001 * (2.0 ** i) for i in range(21))


class Histogram:
    __slots__ = ("name", "help", "labels", "buckets", "counts", "sum", "count", "_reservoir", "_rng")

    RESERVOIR = 2048

    def __init__(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None,
                 buckets: Iterable[float] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        self._reservoir: list[float] = []
        self._rng = random.Random(0xA2C)

    def observe(self, v: float) -> None:
        self.sum += v
        self.count += 1
        # linear scan is fine: ~21 buckets, and observe() is called per batch, not per row
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        r = self._reservoir
        if len(r) < self.RESERVOIR:
            r.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.RESERVOIR:
                r[j] = v

    def quantile(self, q: float) -> float:
        if not self._reservoir:
            return math.nan
        s = sorted(self._reservoir)
        idx = min(len(s) - 1, max(0, int(q * len(s))))
        return s[idx]

    def time(self):
        return _Timer(self)


class _Timer:
    __slots__ = ("h", "t0")

    def __init__(self, h: Histogram):
        self.h = h

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.h.observe(time.perf_counter() - self.t0)
        return False


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[tuple[str, tuple[tuple[str, str], ...]], object] = {}

    def _key(self, name: str, labels: Optional[dict[str, str]]):
        return (name, tuple(sorted((labels or {}).items())))

    def counter(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None) -> Counter:
        k = self._key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            m = Counter(name, help_, labels)
            self._metrics[k] = m
        return m  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None) -> Gauge:
        k = self._key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            m = Gauge(name, help_, labels)
            self._metrics[k] = m
        return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None,
                  buckets: Iterable[float] = _DEFAULT_BUCKETS) -> Histogram:
        k = self._key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            m = Histogram(name, help_, labels, buckets)
            self._metrics[k] = m
        return m  # type: ignore[return-value]

    def clear(self) -> None:
        self._metrics.clear()

    def collect(self) -> list[object]:
        return list(self._metrics.values())

    def sum_values(self, name: str) -> float:
        """Sum of a counter/gauge family across ALL label sets (e.g. every
        pool member's ``arkflow_tpu_step_deadline_misses``) — what chaos
        tests and the soak harness assert against."""
        return sum(m.value for m in self._metrics.values()
                   if isinstance(m, (Counter, Gauge)) and m.name == name)

    # -- Prometheus text exposition ---------------------------------------

    @staticmethod
    def _fmt_labels(labels: dict[str, str], extra: Optional[dict[str, str]] = None) -> str:
        all_labels = {**labels, **(extra or {})}
        if not all_labels:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(all_labels.items()))
        return "{" + inner + "}"

    def exposition(self) -> str:
        lines: list[str] = []
        seen_help: set[str] = set()
        for m in self._metrics.values():
            name = m.name  # type: ignore[attr-defined]
            if name not in seen_help:
                kind = "counter" if isinstance(m, Counter) else "gauge" if isinstance(m, Gauge) else "histogram"
                if m.help:  # type: ignore[attr-defined]
                    lines.append(f"# HELP {name} {m.help}")  # type: ignore[attr-defined]
                lines.append(f"# TYPE {name} {kind}")
                seen_help.add(name)
            if isinstance(m, (Counter, Gauge)):
                lines.append(f"{name}{self._fmt_labels(m.labels)} {m.value}")
            elif isinstance(m, Histogram):
                cum = 0
                for b, c in zip(m.buckets, m.counts):
                    cum += c
                    lines.append(f'{name}_bucket{self._fmt_labels(m.labels, {"le": repr(b)})} {cum}')
                cum += m.counts[-1]
                lines.append(f'{name}_bucket{self._fmt_labels(m.labels, {"le": "+Inf"})} {cum}')
                lines.append(f"{name}_sum{self._fmt_labels(m.labels)} {m.sum}")
                lines.append(f"{name}_count{self._fmt_labels(m.labels)} {m.count}")
        return "\n".join(lines) + "\n"


_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    return _GLOBAL
