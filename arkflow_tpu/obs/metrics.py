"""First-class metrics: counters, gauges, histograms + Prometheus exposition.

The reference declares ``prometheus = "0.13"`` but never uses it — there is no
metrics endpoint (SURVEY.md section 5; verified zero references in
crates/**/*.rs). The BASELINE metric is rows/sec + p50/p99, so here
throughput/latency instrumentation is built into the runtime rather than
bolted on: stream stages update these metrics on the hot path and the engine
serves ``/metrics`` in Prometheus text format.

Implementation notes: metrics are updated from SEVERAL threads — the stream
stages run on the event loop, but runner executor threads (``infer_sync``,
host prep), the step-deadline watchdog and pool members all touch counters
and histograms directly — so every mutation holds a small per-metric lock
(Python ``+=`` on a float is read-modify-write, NOT atomic under the GIL
across the bytecode boundary). Reads of a single float remain lock-free:
torn reads of one attribute are impossible, and exposition-time skew between
``sum`` and ``count`` of one histogram is acceptable for monitoring.
Histograms keep fixed log-spaced buckets plus a bounded reservoir for exact
small-N quantiles.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Iterable, Optional


class Counter:
    __slots__ = ("name", "help", "labels", "value", "_lock")

    def __init__(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("name", "help", "labels", "value", "_lock")

    def __init__(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self.value = float(v)  # single assignment: atomic enough

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n


#: default latency buckets: 0.1ms .. ~100s, log-spaced
_DEFAULT_BUCKETS = tuple(0.0001 * (2.0 ** i) for i in range(21))


class Histogram:
    __slots__ = ("name", "help", "labels", "buckets", "counts", "sum", "count",
                 "_reservoir", "_rng", "_lock")

    RESERVOIR = 2048

    def __init__(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None,
                 buckets: Iterable[float] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0
        self._reservoir: list[float] = []
        self._rng = random.Random(0xA2C)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.count += 1
            # linear scan is fine: ~21 buckets, and observe() is called per batch, not per row
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    break
            else:
                self.counts[-1] += 1
            r = self._reservoir
            if len(r) < self.RESERVOIR:
                r.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR:
                    r[j] = v

    def quantile(self, q: float) -> float:
        with self._lock:
            s = sorted(self._reservoir)
        if not s:
            return math.nan
        idx = min(len(s) - 1, max(0, int(q * len(s))))
        return s[idx]

    def time(self):
        return _Timer(self)


class _Timer:
    __slots__ = ("h", "t0")

    def __init__(self, h: Histogram):
        self.h = h

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.h.observe(time.perf_counter() - self.t0)
        return False


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[tuple[str, tuple[tuple[str, str], ...]], object] = {}
        #: guards registration (get-or-create) — metric families are minted
        #: from worker threads too (pool members, watchdogs); without it two
        #: threads can each create the series and split its updates
        self._reg_lock = threading.Lock()

    def _key(self, name: str, labels: Optional[dict[str, str]]):
        return (name, tuple(sorted((labels or {}).items())))

    def counter(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None) -> Counter:
        k = self._key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            with self._reg_lock:
                m = self._metrics.get(k)
                if m is None:
                    m = self._metrics[k] = Counter(name, help_, labels)
        return m  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None) -> Gauge:
        k = self._key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            with self._reg_lock:
                m = self._metrics.get(k)
                if m is None:
                    m = self._metrics[k] = Gauge(name, help_, labels)
        return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", labels: Optional[dict[str, str]] = None,
                  buckets: Iterable[float] = _DEFAULT_BUCKETS) -> Histogram:
        k = self._key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            with self._reg_lock:
                m = self._metrics.get(k)
                if m is None:
                    m = self._metrics[k] = Histogram(name, help_, labels, buckets)
        return m  # type: ignore[return-value]

    def clear(self) -> None:
        self._metrics.clear()

    def collect(self) -> list[object]:
        return list(self._metrics.values())

    def sum_values(self, name: str) -> float:
        """Sum of a counter/gauge family across ALL label sets (e.g. every
        pool member's ``arkflow_tpu_step_deadline_misses``) — what chaos
        tests and the soak harness assert against."""
        return sum(m.value for m in self._metrics.values()
                   if isinstance(m, (Counter, Gauge)) and m.name == name)

    # -- Prometheus text exposition ---------------------------------------

    @staticmethod
    def _escape_label(v: str) -> str:
        """Text-format label escaping (backslash, quote, newline) — tenant
        ids and error strings are attacker-influenced, so an unescaped
        quote would corrupt the whole scrape."""
        return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

    @classmethod
    def _fmt_labels(cls, labels: dict[str, str], extra: Optional[dict[str, str]] = None) -> str:
        all_labels = {**labels, **(extra or {})}
        if not all_labels:
            return ""
        inner = ",".join(f'{k}="{cls._escape_label(v)}"'
                         for k, v in sorted(all_labels.items()))
        return "{" + inner + "}"

    @staticmethod
    def _escape_help(text: str) -> str:
        return str(text).replace("\\", "\\\\").replace("\n", "\\n")

    @staticmethod
    def _fmt_le(b: float) -> str:
        # repr() keeps full float precision so cumulative buckets parse back
        # to the exact thresholds; integral thresholds render Prometheus
        # style ("1" not "1.0" is also accepted, keep repr for stability)
        return repr(b)

    def exposition(self) -> str:
        """Prometheus text format. Conformance notes: all samples of a
        metric family are CONTIGUOUS and preceded by exactly one # TYPE
        (families whose label sets were minted at different times must not
        interleave with other families); histogram buckets are cumulative
        with a terminal ``+Inf`` bucket equal to ``_count``; label values
        are escaped."""
        with self._reg_lock:
            metrics = list(self._metrics.values())
        by_name: dict[str, list] = {}
        for m in metrics:
            by_name.setdefault(m.name, []).append(m)  # type: ignore[attr-defined]
        lines: list[str] = []
        for name, family in by_name.items():
            first = family[0]
            kind = ("counter" if isinstance(first, Counter)
                    else "gauge" if isinstance(first, Gauge) else "histogram")
            if first.help:
                lines.append(f"# HELP {name} {self._escape_help(first.help)}")
            lines.append(f"# TYPE {name} {kind}")
            for m in family:
                if isinstance(m, (Counter, Gauge)):
                    lines.append(f"{name}{self._fmt_labels(m.labels)} {m.value}")
                elif isinstance(m, Histogram):
                    cum = 0
                    for b, c in zip(m.buckets, m.counts):
                        cum += c
                        lines.append(
                            f'{name}_bucket{self._fmt_labels(m.labels, {"le": self._fmt_le(b)})} {cum}')
                    cum += m.counts[-1]
                    lines.append(f'{name}_bucket{self._fmt_labels(m.labels, {"le": "+Inf"})} {cum}')
                    lines.append(f"{name}_sum{self._fmt_labels(m.labels)} {m.sum}")
                    lines.append(f"{name}_count{self._fmt_labels(m.labels)} {m.count}")
        return "\n".join(lines) + "\n"


_GLOBAL = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    return _GLOBAL
