"""End-to-end per-batch tracing: stage spans across every serving tier.

Aggregate histograms answer "how slow is the p99" but not "where did THIS
slow request spend its time". This module is the diagnostic plane for that
question: a lightweight, always-on span layer (zero deps, monotonic clocks,
bounded memory) whose trace context rides the batch as the
``__meta_ext_trace`` metadata column — the same mechanism that makes
tenant/deadline/priority survive redelivery, ``split_ack`` shares, coalescer
merges and quarantine — and crosses the cluster flight plane so one trace
stitches ingest-tier and worker-tier spans into a single tree.

Pieces:

- ``TraceContext``: (trace_id, parent span_id, sampled) — the wire/column
  form is a compact JSON string. Stamped once at input by the stream;
  redeliveries keep their id, so every delivery attempt lands in the same
  trace.
- ``Tracer``: records completed ``Span``s into a per-trace open table and
  feeds every span duration to the ``arkflow_stage_seconds{stage=...}``
  histograms (always, sampled or not — the aggregate view costs nothing
  extra). ``finish`` commits a trace to the bounded done-ring when it was
  head-sampled OR its status is pathological (shed / deadline overrun /
  error) — forced sampling, so the traces worth debugging are always
  captured regardless of the sample rate.
- The done-ring serves the engine's ``/trace`` endpoint: the slowest-N
  recent traces plus a per-stage latency breakdown (p50/p99 and each
  stage's share of end-to-end time).
- Cross-tier stitching: the ingest dispatcher sends the context in the
  ``infer`` request frame; the worker records its spans into its OWN
  ``Tracer`` (one per process — in-process test fleets stay separated) and
  exports them back in a trace-tagged flight frame; ``adopt_spans`` grafts
  them under the ingest-side hop span. Durations are monotonic-local per
  process, so they are meaningful even when tier clocks disagree; only the
  wall-clock ``start_ms`` fields are subject to skew.

Nested instrumentation (runner device steps, processor infeed prep) uses a
``contextvars`` scope: the stream activates the batch's trace around
``pipeline.process`` and any instrumented code below records via
``record_stage``/``stage_span`` without threading a context object through
every API. The contextvar carries the *tracer* too, so worker-hosted
processors record into the worker's tracer, not the global one.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from arkflow_tpu.errors import ConfigError
from arkflow_tpu.obs.metrics import global_registry

#: statuses that force-commit a trace regardless of the head-sampling
#: decision: these are exactly the requests an operator needs to see
#: ``fleet`` = an autoscaling-controller decision (runtime/fleet.py): rare,
#: operator-relevant, and meaningless to head-sample — always committed
FORCE_STATUSES = ("shed", "deadline", "error", "fleet")


def _new_id(nbytes: int = 8) -> str:
    import os

    return os.urandom(nbytes).hex()


@dataclass(frozen=True)
class TraceContext:
    """The context that rides the batch: trace identity + current parent
    span + the head-sampling decision (made once, at the root tier)."""

    trace_id: str
    span_id: str = ""  # parent for spans recorded under this context
    sampled: bool = True

    def to_dict(self) -> dict:
        return {"t": self.trace_id, "p": self.span_id,
                "s": 1 if self.sampled else 0}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_json(cls, raw: Any) -> Optional["TraceContext"]:
        """Tolerant parse: a malformed column value must never fail the hot
        path — the batch simply continues untraced."""
        if not raw:
            return None
        try:
            d = json.loads(raw) if isinstance(raw, (str, bytes)) else raw
            tid = d.get("t")
            if not tid or not isinstance(tid, str):
                return None
            return cls(trace_id=tid, span_id=str(d.get("p") or ""),
                       sampled=bool(d.get("s", 1)))
        except (ValueError, AttributeError, TypeError):
            return None

    def with_parent(self, span_id: str) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.sampled)


@dataclass
class Span:
    stage: str
    dur_s: float
    span_id: str
    parent_id: str = ""
    start_ms: float = 0.0  # wall clock, display/ordering only
    tier: str = ""
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        out = {"stage": self.stage, "dur_ms": round(self.dur_s * 1000.0, 3),
               "span_id": self.span_id, "parent_id": self.parent_id,
               "start_ms": round(self.start_ms, 1), "tier": self.tier}
        if self.attrs:
            out["attrs"] = self.attrs
        return out

    @classmethod
    def from_dict(cls, d: Mapping) -> Optional["Span"]:
        try:
            return cls(stage=str(d["stage"]),
                       dur_s=float(d.get("dur_ms", 0.0)) / 1000.0,
                       span_id=str(d.get("span_id") or _new_id()),
                       parent_id=str(d.get("parent_id") or ""),
                       start_ms=float(d.get("start_ms", 0.0)),
                       tier=str(d.get("tier") or ""),
                       attrs=dict(d.get("attrs") or {}))
        except (KeyError, TypeError, ValueError):
            return None


@dataclass
class TracingConfig:
    """The ``tracing:`` config block (engine top level; cluster workers
    accept the same block in their worker config)."""

    enabled: bool = True
    #: head-sampling probability for NON-pathological traces; sheds,
    #: deadline overruns and errors always commit (forced sampling)
    sample_rate: float = 1.0
    #: bounded ring of committed (finished) traces served by /trace
    max_traces: int = 256
    #: bound on concurrently-open (unfinished) traces
    max_open: int = 4096
    #: spans kept per trace; extras are dropped and counted
    max_spans_per_trace: int = 64
    #: default trace count for the /trace endpoint
    slow_n: int = 16

    @classmethod
    def from_mapping(cls, m: Any) -> "TracingConfig":
        import os

        # ARKFLOW_TRACE=0 stays effective when the config doesn't say
        # otherwise: an absent `enabled:` key defers to the env kill switch
        # (the engine re-applies this config over the global tracer, so a
        # hardcoded True default would silently defeat the switch)
        env_enabled = os.environ.get("ARKFLOW_TRACE", "1") != "0"
        if m is None:
            return cls(enabled=env_enabled)
        if not isinstance(m, Mapping):
            raise ConfigError(f"'tracing' must be a mapping, got {m!r}")
        c = cls()
        enabled = m.get("enabled", env_enabled)
        if not isinstance(enabled, bool):
            raise ConfigError(f"tracing.enabled must be a bool, got {enabled!r}")
        c.enabled = enabled
        rate = m.get("sample_rate", 1.0)
        if isinstance(rate, bool) or not isinstance(rate, (int, float)) \
                or not 0.0 <= float(rate) <= 1.0:
            raise ConfigError(
                f"tracing.sample_rate must be a number in [0, 1], got {rate!r}")
        c.sample_rate = float(rate)
        for key, default, minimum in (("max_traces", 256, 1),
                                      ("max_open", 4096, 1),
                                      ("max_spans_per_trace", 64, 1),
                                      ("slow_n", 16, 1)):
            v = m.get(key, default)
            if isinstance(v, bool) or not isinstance(v, int) or v < minimum:
                raise ConfigError(
                    f"tracing.{key} must be an int >= {minimum}, got {v!r}")
            setattr(c, key, v)
        return c


class _OpenTrace:
    __slots__ = ("spans", "dropped", "started_wall")

    def __init__(self) -> None:
        self.spans: list[Span] = []
        self.dropped = 0
        self.started_wall = time.time()


class Tracer:
    """Span recorder + bounded trace store for ONE process tier.

    Thread-safe: spans arrive from the event loop, runner executor threads
    and (in tests) plain threads; every mutation of the open table / done
    ring holds the lock. Per-span cost is one lock, one list append and one
    histogram observe — per BATCH, not per row."""

    def __init__(self, tier: str = "ingest",
                 config: Optional[TracingConfig] = None):
        self.tier = tier
        self.cfg = config or TracingConfig()
        self._lock = threading.Lock()
        self._open: "OrderedDict[str, _OpenTrace]" = OrderedDict()
        self._done: deque[dict] = deque(maxlen=self.cfg.max_traces)
        self._rng = random.Random()
        self._commit_seq = 0
        self.spans_recorded = 0
        self.spans_dropped = 0
        self.traces_started = 0
        self.traces_forced = 0
        self.open_evicted = 0

    # -- configuration -----------------------------------------------------

    def configure(self, cfg: TracingConfig, tier: Optional[str] = None) -> None:
        """Apply a parsed ``tracing:`` block (engine/worker startup). The
        done-ring is rebuilt at the new bound, keeping the newest traces."""
        with self._lock:
            self.cfg = cfg
            if tier is not None:
                self.tier = tier
            self._done = deque(self._done, maxlen=cfg.max_traces)

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # -- trace lifecycle ---------------------------------------------------

    def begin(self, parent: Optional[TraceContext] = None) -> Optional[TraceContext]:
        """Root a new trace (head-sampling decided here) or adopt an
        existing context (redelivery / downstream tier: the root's decision
        sticks). Returns None when tracing is disabled."""
        if not self.cfg.enabled:
            return None
        if parent is not None:
            return parent
        sampled = (self.cfg.sample_rate >= 1.0
                   or self._rng.random() < self.cfg.sample_rate)
        with self._lock:
            self.traces_started += 1
        return TraceContext(trace_id=_new_id(), sampled=sampled)

    def record(self, ctx: Optional[TraceContext], stage: str, dur_s: float,
               *, parent_id: Optional[str] = None, attrs: Optional[dict] = None,
               start_wall: Optional[float] = None,
               span_id: Optional[str] = None) -> str:
        """Record one completed span; returns its span id (so callers can
        parent later spans under it). ``span_id`` lets a caller pre-allocate
        the id (cross-tier hops name their parent BEFORE the child tier
        runs). No-op (empty id) when untraced."""
        if ctx is None or not self.cfg.enabled:
            return ""
        dur = max(0.0, float(dur_s))
        # callers record AFTER the measured interval: the default start is
        # now minus the duration, so /trace timelines order correctly
        span = Span(stage=stage, dur_s=dur,
                    span_id=span_id or _new_id(),
                    parent_id=(parent_id if parent_id
                               is not None else ctx.span_id),
                    start_ms=(start_wall if start_wall is not None
                              else time.time() - dur) * 1000.0,
                    tier=self.tier, attrs=dict(attrs or {}))
        self._observe_stage(stage, span.dur_s)
        self._append(ctx.trace_id, span)
        return span.span_id

    @staticmethod
    def _observe_stage(stage: str, dur_s: float) -> None:
        global_registry().histogram(
            "arkflow_stage_seconds",
            "per-batch stage latency from the trace layer",
            {"stage": stage}).observe(dur_s)

    def _append(self, trace_id: str, span: Span) -> None:
        with self._lock:
            ot = self._open.get(trace_id)
            if ot is None:
                while len(self._open) >= self.cfg.max_open:
                    self._open.popitem(last=False)
                    self.open_evicted += 1
                ot = self._open[trace_id] = _OpenTrace()
            if len(ot.spans) >= self.cfg.max_spans_per_trace:
                ot.dropped += 1
                self.spans_dropped += 1
                return
            ot.spans.append(span)
            self.spans_recorded += 1

    def adopt_spans(self, ctx: Optional[TraceContext],
                    spans: list[Mapping]) -> None:
        """Graft spans exported by another tier (the worker's trace frame)
        into this trace. Their durations already fed the WORKER's stage
        histograms; here they only join the tree, so aggregate metrics
        never double-count a stage across tiers."""
        if ctx is None or not self.cfg.enabled:
            return
        for d in spans:
            span = Span.from_dict(d)
            if span is not None:
                self._append(ctx.trace_id, span)

    def export_open(self, ctx: Optional[TraceContext]) -> list[dict]:
        """Pop and return this trace's open spans as JSON-able dicts — the
        worker-side end of cross-tier stitching (the trace is owned and
        finished by the caller's tier)."""
        if ctx is None:
            return []
        with self._lock:
            ot = self._open.pop(ctx.trace_id, None)
        return [s.to_dict() for s in ot.spans] if ot else []

    def finish(self, ctx: Optional[TraceContext], status: str = "ok", *,
               e2e_s: Optional[float] = None,
               attrs: Optional[dict] = None) -> bool:
        """Close a trace: commit it to the done-ring when head-sampled or
        when the status forces sampling (shed/deadline/error). Returns
        whether the trace was committed."""
        if ctx is None or not self.cfg.enabled:
            return False
        with self._lock:
            ot = self._open.pop(ctx.trace_id, None)
            forced = status in FORCE_STATUSES
            if not (ctx.sampled or forced):
                return False
            spans = ot.spans if ot else []
            self._commit_seq += 1
            # e2e fallback sums ROOT spans only: nested children (device
            # step inside process, flight legs inside the hop) overlap
            # their parents and would double-count the trace's latency
            root_ms = sum(s.dur_s for s in spans if not s.parent_id) * 1000.0
            rec = {
                "trace_id": ctx.trace_id,
                "status": status,
                "forced": forced and not ctx.sampled,
                "seq": self._commit_seq,
                "e2e_ms": (round(e2e_s * 1000.0, 3) if e2e_s is not None
                           else round(root_ms, 3)),
                "spans": [s.to_dict() for s in spans],
                "dropped_spans": ot.dropped if ot else 0,
            }
            if attrs:
                rec["attrs"] = dict(attrs)
            if forced and not ctx.sampled:
                self.traces_forced += 1
            self._done.append(rec)
            return True

    # -- introspection (the /trace payload) --------------------------------

    def commit_seq(self) -> int:
        """Watermark for delta views (bench phases read the breakdown of
        only the traces committed after their start)."""
        with self._lock:
            return self._commit_seq

    def slowest(self, n: Optional[int] = None,
                min_seq: int = 0) -> list[dict]:
        with self._lock:
            recs = [r for r in self._done if r["seq"] > min_seq]
        recs.sort(key=lambda r: r["e2e_ms"], reverse=True)
        return recs[: (n if n is not None else self.cfg.slow_n)]

    def stage_breakdown(self, min_seq: int = 0) -> dict:
        """Per-stage p50/p99 + share of end-to-end time over the committed
        traces (newer than ``min_seq``).

        ``share_of_e2e`` counts only a stage's TOP-LEVEL spans (no parent)
        against the summed trace e2e, so the shares of disjoint top-level
        stages sum to <= 1.0 — a nested span (``device_step`` inside
        ``process``, flight legs inside a hop) overlaps its parent and used
        to inflate the sum past 1.0 in BENCH_RESULT.json. Stages whose
        spans are ALL nested report ``nested: true`` plus ``nested_under``
        (their most common parent stage) and a 0.0 top-level share; their
        p50/p99/total still cover every span, so the within-parent cost
        stays visible."""
        with self._lock:
            recs = [r for r in self._done if r["seq"] > min_seq]
        # span_id -> stage, per trace, so nested stages can name the parent
        # stage they report under (ids are process-unique: one shared map)
        span_stage: dict[str, str] = {}
        for r in recs:
            for s in r["spans"]:
                sid = s.get("span_id")
                if sid:
                    span_stage[sid] = s["stage"]
        stages: dict[str, list[float]] = {}
        top: dict[str, float] = {}  # stage -> summed top-level duration
        parents: dict[str, dict[str, int]] = {}  # stage -> parent stage counts
        total_e2e_ms = 0.0
        for r in recs:
            total_e2e_ms += r["e2e_ms"]
            for s in r["spans"]:
                stage = s["stage"]
                stages.setdefault(stage, []).append(s["dur_ms"])
                pid = s.get("parent_id") or ""
                if not pid:
                    top[stage] = top.get(stage, 0.0) + s["dur_ms"]
                else:
                    pstage = span_stage.get(pid)
                    if pstage is not None:
                        counts = parents.setdefault(stage, {})
                        counts[pstage] = counts.get(pstage, 0) + 1
        out: dict[str, dict] = {}
        for stage, durs in sorted(stages.items()):
            durs.sort()
            entry = {
                "count": len(durs),
                "p50_ms": round(durs[len(durs) // 2], 3),
                "p99_ms": round(durs[min(len(durs) - 1,
                                         int(0.99 * len(durs)))], 3),
                "total_ms": round(sum(durs), 3),
                "share_of_e2e": (round(top.get(stage, 0.0) / total_e2e_ms, 4)
                                 if total_e2e_ms > 0 else 0.0),
            }
            if stage not in top:  # every span nested: mark it as such
                entry["nested"] = True
                pcounts = parents.get(stage)
                if pcounts:
                    entry["nested_under"] = max(pcounts, key=pcounts.get)
            out[stage] = entry
        return {"traces": len(recs), "stages": out}

    def summary(self) -> dict:
        """One-line liveness summary for /health: is tracing on, how much
        is retained, and how often forced sampling fired."""
        with self._lock:
            return {
                "enabled": self.cfg.enabled,
                "sample_rate": self.cfg.sample_rate,
                "tier": self.tier,
                "traces_retained": len(self._done),
                "traces_open": len(self._open),
                "spans_recorded": self.spans_recorded,
                "forced_samples": self.traces_forced,
            }

    def clear(self) -> None:
        """Test/bench hook: drop all trace state (config survives)."""
        with self._lock:
            self._open.clear()
            self._done.clear()
            self.spans_recorded = self.spans_dropped = 0
            self.traces_started = self.traces_forced = self.open_evicted = 0
            self._commit_seq = 0


# ---------------------------------------------------------------------------
# process-global tracer + contextvar scope for nested instrumentation
# ---------------------------------------------------------------------------

def _default_config() -> TracingConfig:
    """ARKFLOW_TRACE=0 is the operator kill switch (A/B overhead runs, or
    paranoia); the engine's `tracing:` config block overrides it."""
    import os

    return TracingConfig(enabled=os.environ.get("ARKFLOW_TRACE", "1") != "0")


_GLOBAL = Tracer(config=_default_config())


def global_tracer() -> Tracer:
    return _GLOBAL


class _Scope:
    __slots__ = ("tracer", "ctx")

    def __init__(self, tracer: Tracer, ctx: TraceContext):
        self.tracer = tracer
        self.ctx = ctx


_ACTIVE: ContextVar[Optional[_Scope]] = ContextVar("arkflow_trace_scope",
                                                   default=None)


@contextmanager
def activate(tracer: Tracer, ctx: Optional[TraceContext],
             parent_id: Optional[str] = None):
    """Make (tracer, ctx) the ambient trace scope for nested
    ``record_stage``/``stage_span`` calls — the stream wraps
    ``pipeline.process`` with this so runners/processors need no context
    plumbing. Contextvars flow into child tasks (``asyncio.gather``), so
    packed fan-out windows inherit the scope; plain executor threads do
    not, which keeps off-loop helpers no-ops by construction."""
    if ctx is None or not tracer.enabled:
        yield
        return
    scoped = ctx if parent_id is None else ctx.with_parent(parent_id)
    token = _ACTIVE.set(_Scope(tracer, scoped))
    try:
        yield
    finally:
        _ACTIVE.reset(token)


def current_scope() -> Optional[_Scope]:
    return _ACTIVE.get()


def record_stage(stage: str, dur_s: float, *,
                 attrs: Optional[dict] = None) -> str:
    """Record a span under the ambient scope (no-op when untraced)."""
    scope = _ACTIVE.get()
    if scope is None:
        return ""
    return scope.tracer.record(scope.ctx, stage, dur_s, attrs=attrs)


@contextmanager
def stage_span(stage: str, attrs: Optional[dict] = None):
    """Time a block as a span under the ambient scope; children recorded
    inside the block parent under it. Exceptions mark the span
    ``error=true`` and propagate."""
    scope = _ACTIVE.get()
    if scope is None:
        yield
        return
    span_id = _new_id()
    token = _ACTIVE.set(_Scope(scope.tracer, scope.ctx.with_parent(span_id)))
    t0 = time.perf_counter()
    wall = time.time()
    err = False
    try:
        yield
    except BaseException:
        err = True
        raise
    finally:
        _ACTIVE.reset(token)
        a = dict(attrs or {})
        if err:
            a["error"] = True
        scope.tracer.record(scope.ctx, stage, time.perf_counter() - t0,
                            parent_id=scope.ctx.span_id, attrs=a,
                            start_wall=wall, span_id=span_id)
