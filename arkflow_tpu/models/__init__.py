"""Model families for streaming inference processors.

The reference executes no models — its Python processor is the extension hook
where user ML code runs (ref: crates/arkflow-plugin/src/processor/python.rs).
Per BASELINE.json, this build makes model execution first-class: each family
here is a pure-JAX functional model (params pytree + jittable apply) designed
for the MXU — bfloat16 matmuls, static shapes, ``lax.scan`` for recurrence —
and registered under a name the ``tpu_inference`` processor resolves from
config.

Families (mapped to BASELINE.json bench configs):
- ``bert_classifier``  BERT-base sequence classification (Kafka->BERT->Kafka)
- ``lstm_ae``          LSTM autoencoder anomaly score   (MQTT->LSTM-AE->stdout)
- ``vit_embedder``     ViT-B/16 image embedding          (HTTP->ViT->Redis)
- ``decoder_lm``       Llama-style decoder LM            (CDC->LLM-summary->NATS)
"""

from arkflow_tpu.models.registry import get_model, list_models, register_model  # noqa: F401

import arkflow_tpu.models.bert  # noqa: F401
import arkflow_tpu.models.lstm_ae  # noqa: F401
import arkflow_tpu.models.vit  # noqa: F401
import arkflow_tpu.models.decoder  # noqa: F401
