"""Paged KV-cache decode for the decoder LM (vLLM-style, TPU-native).

The contiguous cache in ``decoder.py`` preallocates ``[B, max_len]`` per
sequence; mixed-length workloads waste most of it. Here KV lives in a pool
of fixed-size pages — ``[layers, num_pages, page, kv_heads, dh]`` — and each
serving slot owns an int32 page table. Pages are allocated/freed by the
host-side scheduler (``arkflow_tpu.tpu.serving``) BETWEEN steps; device code
only ever reads/writes through static-shaped gathers and scatters, so every
step jits once and replays (no dynamic shapes, XLA-friendly).

Page 0 is a reserved scratch page: inactive slots and masked prompt padding
write there, which keeps the scatter free of conditionals.

The reference has no serving layer at all (its python processor is
user-code); this implements the engine the `tpu_generate` processor's
continuous-batching mode runs on. Design follows the public PagedAttention
idea (Kwon et al., SOSP'23) re-expressed for XLA: page-table gather +
masked attention instead of custom CUDA paging.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from arkflow_tpu.models import common as cm
from arkflow_tpu.models.decoder import DecoderConfig, _mlp, _rope


def init_page_pool(cfg: DecoderConfig, num_pages: int, page_size: int):
    """KV page pools: [layers, num_pages, page, kv_heads, dh] bf16."""
    dh = cfg.dim // cfg.heads
    shape = (cfg.layers, num_pages, page_size, cfg.kv_heads, dh)
    return jnp.zeros(shape, jnp.bfloat16), jnp.zeros(shape, jnp.bfloat16)


def _constrain(x, sharding):
    """Pin a per-layer pool slice to its tensor-parallel sharding (KV heads
    over ``tp``). Under GSPMD the layer scan would otherwise be free to
    all-gather the pools at every step — hundreds of MB of HBM churn; the
    constraint keeps scatter/gather partitioned. ``None`` (single-device
    serving) is a no-op so the unsharded path traces identically."""
    if sharding is None:
        return x
    return jax.lax.with_sharding_constraint(x, sharding)


def _attend_paged(q, kp, vp, page_table, off, cfg: DecoderConfig,
                  kv_sharding, interpret: bool):
    """Page-table-indirect flash attention over one layer's pool slices
    (ops/ragged_attention.paged_flash_attention): query i of row b sits at
    absolute position ``off[b] + i`` and attends keys 0..off+i, read
    straight from the pools — the [B, ctx, heads, dh] gather+repeat the
    dense reference materializes per layer per step never exists.

    Under tensor parallelism the kernel runs inside ``shard_map`` over the
    ``kv_sharding`` mesh's tp axis: attention is independent per KV head,
    q's head dim splits into the same contiguous head groups the pools
    shard by (tp | kv_heads is validated at server build), so each shard
    attends its local heads with zero collectives — the pools are never
    all-gathered."""
    from arkflow_tpu.ops.ragged_attention import paged_flash_attention

    if kv_sharding is None:
        return paged_flash_attention(q, kp, vp, page_table, off,
                                     interpret=interpret)
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = kv_sharding.mesh
    head_spec = P(None, None, "tp", None)  # q/out: [B, C, H, dh], H over tp

    def local(q_, kp_, vp_, table_, off_):
        return paged_flash_attention(q_, kp_, vp_, table_, off_,
                                     interpret=interpret)

    return shard_map(
        local, mesh=mesh,
        in_specs=(head_spec, kv_sharding.spec, kv_sharding.spec, P(), P()),
        out_specs=head_spec,
        check_rep=False,
    )(q, kp, vp, page_table, off)


def paged_prefill(params: dict, cfg: DecoderConfig, input_ids, lengths,
                  page_table, k_pages, v_pages, return_logits: bool = False,
                  kv_sharding=None):
    """Prefill prompts and scatter their K/V into pages.

    input_ids: [B, T] right-padded; lengths: [B]; page_table: [B, P].
    Returns (next_ids [B], k_pages, v_pages) — pools updated for all
    positions < lengths (padding scatters to scratch page 0).

    ``kv_sharding``: optional per-layer-pool ``NamedSharding`` (KV heads over
    ``tp``) for tensor-parallel serving; see ``_constrain``.
    """
    b, t = input_ids.shape
    page = k_pages.shape[2]
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    causal = jnp.tril(jnp.ones((t, t), bool))[None, None]
    key_valid = (jnp.arange(t)[None, :] < lengths[:, None])[:, None, None, :]
    mask = jnp.logical_and(causal, key_valid)
    x = cm.embedding(params["embed"], input_ids)

    # scatter coordinates for every (row, position): valid positions route
    # through the page table, padding goes to scratch page 0
    pos_valid = positions < lengths[:, None]                     # [B, T]
    logical_page = positions // page                             # [B, T]
    page_idx = jnp.where(
        pos_valid,
        jnp.take_along_axis(page_table, logical_page, axis=1),
        0,
    )                                                            # [B, T]
    offset = jnp.where(pos_valid, positions % page, 0)           # [B, T]

    def layer(carry, lp_and_pools):
        x, = carry
        lp, kp, vp = lp_and_pools
        y = cm.rms_norm(lp["attn_norm"], x, cfg.norm_eps)
        q = cm.dense(lp["wq"], y).reshape(b, t, cfg.heads, dh)
        k = cm.dense(lp["wk"], y).reshape(b, t, cfg.kv_heads, dh)
        v = cm.dense(lp["wv"], y).reshape(b, t, cfg.kv_heads, dh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        kp = _constrain(kp.at[page_idx, offset].set(k.astype(jnp.bfloat16)),
                        kv_sharding)
        vp = _constrain(vp.at[page_idx, offset].set(v.astype(jnp.bfloat16)),
                        kv_sharding)
        kk = jnp.repeat(k, group, axis=2)
        vv = jnp.repeat(v, group, axis=2)
        attn = cm.attention(q, kk, vv, mask).reshape(b, t, cfg.heads * dh)
        x = x + cm.dense(lp["wo"], attn)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        x = x + _mlp(lp, y, cfg, token_mask=pos_valid)
        return (x,), (kp, vp)

    (x,), (new_k, new_v) = jax.lax.scan(
        layer, (x,), (params["layers"], k_pages, v_pages))
    x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
    logits = cm.dense(params["lm_head"], x).astype(jnp.float32)
    last = jnp.clip(lengths - 1, 0, t - 1)
    last_logits = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0, :]
    if return_logits:
        return last_logits, new_k, new_v
    return jnp.argmax(last_logits, axis=-1).astype(jnp.int32), new_k, new_v


def paged_prefill_chunk(params: dict, cfg: DecoderConfig, input_ids, chunk_off,
                        chunk_len, page_table, k_pages, v_pages,
                        return_all: bool = False, kv_sharding=None,
                        attention_kernel: str = "gather",
                        kernel_interpret: bool = False):
    """Prefill ONE CHUNK of a prompt at absolute offset ``chunk_off``.

    Chunked prefill keeps continuous serving responsive: a long prompt no
    longer occupies the device for one monolithic prefill while every
    decode lane stalls — the scheduler interleaves fixed-size chunks with
    decode steps (same motivation as Sarathi/vLLM chunked prefill,
    re-expressed for XLA static shapes: one executable per chunk size).

    input_ids: [B, C] right-padded chunk; chunk_off: [B] absolute start
    position; chunk_len: [B] true tokens in this chunk; page_table: [B, P]
    must already map every page the chunk writes (plus all earlier ones).
    Earlier chunks' K/V are read back through the page-table gather, so
    attention is exact over positions 0..off+i for query i.

    Returns (last_logits [B, vocab] — at the chunk's final true position,
    meaningful only for the prompt's last chunk — , k_pages, v_pages).
    With ``return_all`` (speculative verification): logits for EVERY chunk
    position, [B, C, vocab].

    Doubles as the speculative-decode verifier: scoring k drafted tokens is
    one call with C=k. Rejected drafts leave stale K/V at their positions,
    which is benign — no mask ever admits a key position beyond the
    querying token's own position, and the position->page mapping is
    deterministic, so the true token overwrites the same cell when it arrives.

    ``attention_kernel``: ``"gather"`` (reference — materialize
    ``kp[page_table]`` and run masked dense attention) or ``"paged"`` (the
    Pallas kernel reads the page table in place; ``kernel_interpret`` runs
    it interpreted for CPU tests). Both produce the same attention to float
    tolerance; the serving layer gates the swap on argmax parity.
    """
    b, t = input_ids.shape
    p_slots = page_table.shape[1]
    page = k_pages.shape[2]
    ctx = p_slots * page
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads

    positions = chunk_off[:, None] + jnp.arange(t)[None, :]       # [B, C]
    pos_valid = jnp.arange(t)[None, :] < chunk_len[:, None]       # [B, C]
    logical_page = positions // page
    page_idx = jnp.where(
        pos_valid,
        jnp.take_along_axis(page_table, jnp.minimum(logical_page, p_slots - 1), axis=1),
        0,
    )
    offset = jnp.where(pos_valid, positions % page, 0)
    key_pos = jnp.arange(ctx)[None, None, None, :]                # [1,1,1,ctx]
    # query i attends keys 0..off+i. Padded queries keep this causal mask
    # rather than an all-False row: a fully-masked softmax is NaN, and a
    # NaN activation would leak through the MoE dispatch einsum (0 * NaN)
    # into real tokens' expert inputs. Their finite garbage output is
    # excluded from routing by token_mask and never read out.
    mask = key_pos <= positions[:, None, :, None]                 # [B,1,C,ctx]
    x = cm.embedding(params["embed"], input_ids)

    def layer(carry, lp_and_pools):
        x, = carry
        lp, kp, vp = lp_and_pools
        y = cm.rms_norm(lp["attn_norm"], x, cfg.norm_eps)
        q = cm.dense(lp["wq"], y).reshape(b, t, cfg.heads, dh)
        k = cm.dense(lp["wk"], y).reshape(b, t, cfg.kv_heads, dh)
        v = cm.dense(lp["wv"], y).reshape(b, t, cfg.kv_heads, dh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        kp = _constrain(kp.at[page_idx, offset].set(k.astype(jnp.bfloat16)),
                        kv_sharding)
        vp = _constrain(vp.at[page_idx, offset].set(v.astype(jnp.bfloat16)),
                        kv_sharding)
        if attention_kernel == "paged":
            attn = _attend_paged(q, kp, vp, page_table, chunk_off, cfg,
                                 kv_sharding, kernel_interpret)
            attn = attn.reshape(b, t, cfg.heads * dh)
        else:
            # earlier chunks' keys come back through the page gather (this
            # chunk's own keys were just scattered, so they are included too)
            kk = kp[page_table].reshape(b, ctx, cfg.kv_heads, dh).astype(x.dtype)
            vv = vp[page_table].reshape(b, ctx, cfg.kv_heads, dh).astype(x.dtype)
            kk = jnp.repeat(kk, group, axis=2)
            vv = jnp.repeat(vv, group, axis=2)
            attn = cm.attention(q, kk, vv, mask).reshape(b, t, cfg.heads * dh)
        x = x + cm.dense(lp["wo"], attn)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        x = x + _mlp(lp, y, cfg, token_mask=pos_valid)
        return (x,), (kp, vp)

    (x,), (new_k, new_v) = jax.lax.scan(
        layer, (x,), (params["layers"], k_pages, v_pages))
    x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
    logits = cm.dense(params["lm_head"], x).astype(jnp.float32)
    if return_all:
        return logits, new_k, new_v
    last = jnp.clip(chunk_len - 1, 0, t - 1)
    last_logits = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0, :]
    return last_logits, new_k, new_v


def paged_decode_step(params: dict, cfg: DecoderConfig, token_ids, lengths,
                      active, page_table, k_pages, v_pages,
                      return_logits: bool = False, kv_sharding=None,
                      attention_kernel: str = "gather",
                      kernel_interpret: bool = False):
    """One decode step over all serving slots.

    token_ids: [S] current token per slot; lengths: [S] tokens already in
    cache (the new token writes at position lengths[s]); active: [S] bool;
    page_table: [S, P]. Returns (next_ids [S], k_pages, v_pages).

    ``attention_kernel="gather"`` (reference) gathers each slot's pages —
    a [S, P*page] dense context copy per layer — and masks positions
    >= lengths+1, so scratch-page garbage never contributes.
    ``"paged"`` reads the page table in place through the Pallas kernel
    (same mask, expressed as the causal bound q_pos = lengths): the dense
    context is never materialized and fully-invalid pages are skipped.
    """
    s = token_ids.shape[0]
    p_slots = page_table.shape[1]
    page = k_pages.shape[2]
    ctx = p_slots * page
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads

    positions = lengths[:, None]                                  # [S, 1]
    x = cm.embedding(params["embed"], token_ids[:, None])         # [S, 1, D]

    write_logical = lengths // page
    write_page = jnp.where(
        active,
        jnp.take_along_axis(page_table, write_logical[:, None], axis=1)[:, 0],
        0,
    )                                                             # [S]
    write_off = jnp.where(active, lengths % page, 0)              # [S]
    # keys valid after the write: positions 0..lengths (inclusive)
    key_pos = jnp.arange(ctx)[None, :]                            # [1, ctx]
    valid = (key_pos <= lengths[:, None])[:, None, None, :]       # [S,1,1,ctx]

    def layer(carry, lp_and_pools):
        x, = carry
        lp, kp, vp = lp_and_pools
        y = cm.rms_norm(lp["attn_norm"], x, cfg.norm_eps)
        q = cm.dense(lp["wq"], y).reshape(s, 1, cfg.heads, dh)
        k = cm.dense(lp["wk"], y).reshape(s, 1, cfg.kv_heads, dh)
        v = cm.dense(lp["wv"], y).reshape(s, 1, cfg.kv_heads, dh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        kp = _constrain(
            kp.at[write_page, write_off].set(k[:, 0].astype(jnp.bfloat16)),
            kv_sharding)
        vp = _constrain(
            vp.at[write_page, write_off].set(v[:, 0].astype(jnp.bfloat16)),
            kv_sharding)
        if attention_kernel == "paged":
            # the single query sits at absolute position lengths[s]; the
            # kernel's causal bound (key <= lengths) is exactly `valid`
            attn = _attend_paged(q, kp, vp, page_table, lengths, cfg,
                                 kv_sharding, kernel_interpret)
            attn = attn.reshape(s, 1, cfg.heads * dh)
        else:
            # gather each slot's context from the pool: [S, P, page, kh, dh]
            kk = kp[page_table].reshape(s, ctx, cfg.kv_heads, dh).astype(x.dtype)
            vv = vp[page_table].reshape(s, ctx, cfg.kv_heads, dh).astype(x.dtype)
            kk = jnp.repeat(kk, group, axis=2)
            vv = jnp.repeat(vv, group, axis=2)
            attn = cm.attention(q, kk, vv, valid).reshape(s, 1, cfg.heads * dh)
        x = x + cm.dense(lp["wo"], attn)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        # inactive lanes must not consume expert capacity (MoE)
        x = x + _mlp(lp, y, cfg, token_mask=active[:, None])
        return (x,), (kp, vp)

    (x,), (new_k, new_v) = jax.lax.scan(
        layer, (x,), (params["layers"], k_pages, v_pages))
    x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
    logits = cm.dense(params["lm_head"], x).astype(jnp.float32)
    if return_logits:
        return logits[:, -1, :], new_k, new_v
    return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), new_k, new_v
