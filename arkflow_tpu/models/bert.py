"""BERT-base sequence classifier — the flagship streaming-inference model.

Target workload: Kafka text -> BERT-base classify -> Kafka (BASELINE.json
config 2, >=100k rows/sec/chip at p99 < 50ms on v5e). Architecture follows the
standard BERT-base shape (12 layers, hidden 768, 12 heads, FFN 3072,
vocab 30522) as a pure-JAX functional model: bfloat16 matmuls on the MXU,
float32 LN, softmax in float32 by default (``softmax_dtype: bfloat16``
halves scores bandwidth — the serving/bench opt-in), static shapes
bucketed by the runner.

Weights can be imported from a HuggingFace ``bert-base-uncased`` checkpoint
when one is available locally (``from_hf_state_dict``); benches run fine on
random init since throughput is weight-independent.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from arkflow_tpu.models import common as cm
from arkflow_tpu.models.registry import ModelFamily, register_model


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn: int = 3072
    max_positions: int = 512
    type_vocab: int = 2
    num_labels: int = 2
    ln_eps: float = 1e-12
    #: attention via the ragged Pallas kernel. REQUIRES right-padding:
    #: attention_mask must be a contiguous prefix of ones (row sums become
    #: per-row lengths; ModelRunner enforces this outside jit). Fully-padded
    #: K tiles are skipped on the MXU; pad positions output zeros instead of
    #: attending (identical [CLS] logits — pad keys are masked either way).
    #: None = auto: ModelRunner resolves to True on TPU backends (where the
    #: kernel wins on partially-filled buckets), False elsewhere; direct
    #: ``apply`` callers get the XLA path unless they opt in explicitly.
    use_flash_attention: "bool | None" = None
    #: trace-time floor: buckets with seq below this use XLA attention even
    #: when flash is on. At short seq the kernel's tiles degenerate (tile =
    #: seq < MXU 128x128) and the grid overhead dominates — measured on a
    #: v5e at seq 32 the Pallas path cost 47% of end-to-end throughput.
    #: None = unset: no floor for direct/explicit users; ModelRunner's
    #: auto-resolution fills in the measured crossover (128) only then, so
    #: an operator-tuned value is never clobbered.
    flash_min_seq: "int | None" = None
    flash_interpret: bool = False  # CPU-interpret mode (tests)
    #: packed execution only: route the block-diagonal attention through the
    #: segment flash kernel (ops/segment_attention.py) instead of an XLA
    #: pair mask. Resolved by ModelRunner from ARKFLOW_PACKED_FLASH=1 (TPU
    #: backends, kill-switchable via ARKFLOW_FLASH=0) — direct callers opt
    #: in explicitly; stays off until the kernel has chip A/B numbers.
    packed_flash: bool = False
    #: softmax accumulation dtype for XLA attention. float32 is the safe
    #: default; "bfloat16" halves the scores-tensor bandwidth, worth ~11%
    #: of the whole serving step at b1024/seq32 on a v5e (60.8 -> 54.2ms
    #: measured) with argmax-identical labels on the tested checkpoints.
    #: An explicit reduced-precision opt-in like serving_dtype.
    softmax_dtype: str = "float32"

    def __post_init__(self):
        if self.softmax_dtype not in ("float32", "bfloat16"):
            from arkflow_tpu.errors import ConfigError

            raise ConfigError(
                f"softmax_dtype {self.softmax_dtype!r} invalid "
                "(float32/bfloat16)")


def init(rng, cfg: BertConfig) -> dict:
    keys = iter(jax.random.split(rng, 16 + 8 * cfg.layers))
    params = {
        "embed": {
            "word": cm.embedding_init(next(keys), cfg.vocab_size, cfg.hidden),
            "position": cm.embedding_init(next(keys), cfg.max_positions, cfg.hidden),
            "token_type": cm.embedding_init(next(keys), cfg.type_vocab, cfg.hidden),
            "ln": cm.layer_norm_init(cfg.hidden),
        },
        "layers": [],
        "pooler": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
        "classifier": cm.dense_init(next(keys), cfg.hidden, cfg.num_labels),
    }
    for _ in range(cfg.layers):
        params["layers"].append(
            {
                "q": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "k": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "v": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "attn_out": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "attn_ln": cm.layer_norm_init(cfg.hidden),
                "ffn_in": cm.dense_init(next(keys), cfg.hidden, cfg.ffn),
                "ffn_out": cm.dense_init(next(keys), cfg.ffn, cfg.hidden),
                "ffn_ln": cm.layer_norm_init(cfg.hidden),
            }
        )
    # stack per-layer params into leading-axis pytrees for lax.scan over layers
    params["layers"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params["layers"])
    return params


def encode(params: dict, cfg: BertConfig, input_ids, attention_mask,
           *, positions=None, pair_mask=None, segments=None):
    """[B, S] ids/mask -> [B, S, hidden] bf16 encodings.

    ``positions``/``pair_mask``/``segments`` are the packed-execution hooks
    (tpu/packing.py): per-token position ids, a full [B,1,Sq,Sk]
    block-diagonal mask, or (instead of the mask) per-token segment ids
    driving the segment flash kernel — the mask disables the ragged flash
    kernel (it reads prefix lengths, which cannot express segment
    structure); ``segments`` routes to ``ops/segment_attention.py``, which
    derives the mask in-kernel without O(S^2) HBM traffic.
    """
    b, s = input_ids.shape
    if positions is None:
        positions = jnp.arange(s)[None, :]
    x = (
        cm.embedding(params["embed"]["word"], input_ids)
        + cm.embedding(params["embed"]["position"], positions)
        + cm.embedding(params["embed"]["token_type"], jnp.zeros_like(input_ids))
    )
    x = cm.layer_norm(params["embed"]["ln"], x, cfg.ln_eps)
    if pair_mask is not None:
        mask = pair_mask
    else:
        mask = attention_mask[:, None, None, :].astype(bool)  # [B,1,1,Sk]
    lengths = attention_mask.astype(jnp.int32).sum(axis=1)  # contiguous-prefix masks
    flash_ok = pair_mask is None and segments is None

    def _pow2_tile() -> int:
        # largest pow2 tile (<=128) dividing the bucket length, so any
        # configured seq bucket works
        tile = 1
        while tile * 2 <= min(s, 128) and s % (tile * 2) == 0:
            tile *= 2
        return tile

    def _attend(q, k, v):
        # s is static at trace time: each bucket decides flash-vs-XLA
        # independently, so one stream can serve seq-32 on XLA and seq-512
        # on the ragged kernel from the same config
        if segments is not None:
            from arkflow_tpu.ops.segment_attention import segment_flash_attention

            tile = _pow2_tile()
            qh = jnp.einsum("bshd->bhsd", q)
            kh = jnp.einsum("bshd->bhsd", k)
            vh = jnp.einsum("bshd->bhsd", v)
            out = segment_flash_attention(
                qh, kh, vh, segments, tile_q=tile, tile_k=tile,
                interpret=cfg.flash_interpret,
            )
            return jnp.einsum("bhsd->bshd", out)
        if flash_ok and cfg.use_flash_attention and s >= (cfg.flash_min_seq or 0):
            from arkflow_tpu.ops.ragged_attention import ragged_flash_attention

            tile = _pow2_tile()
            qh = jnp.einsum("bshd->bhsd", q)
            kh = jnp.einsum("bshd->bhsd", k)
            vh = jnp.einsum("bshd->bhsd", v)
            out = ragged_flash_attention(
                qh, kh, vh, lengths, tile_q=tile, tile_k=tile,
                interpret=cfg.flash_interpret,
            )
            return jnp.einsum("bhsd->bshd", out)
        return cm.attention(q, k, v, mask,
                            softmax_dtype=jnp.dtype(cfg.softmax_dtype))

    def layer(x, lp):
        h = cfg.heads
        dh = cfg.hidden // h
        q = cm.dense(lp["q"], x).reshape(b, s, h, dh)
        k = cm.dense(lp["k"], x).reshape(b, s, h, dh)
        v = cm.dense(lp["v"], x).reshape(b, s, h, dh)
        attn = _attend(q, k, v).reshape(b, s, cfg.hidden)
        x = cm.layer_norm(lp["attn_ln"], x + cm.dense(lp["attn_out"], attn), cfg.ln_eps)
        ff = cm.dense(lp["ffn_out"], cm.gelu(cm.dense(lp["ffn_in"], x)))
        x = cm.layer_norm(lp["ffn_ln"], x + ff, cfg.ln_eps)
        return x, None

    # scan over stacked layers: one traced layer body regardless of depth
    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x


def apply(params: dict, cfg: BertConfig, *, input_ids, attention_mask) -> dict:
    x = encode(params, cfg, input_ids, attention_mask)
    pooled = jnp.tanh(cm.dense(params["pooler"], x[:, 0, :]))
    logits = cm.dense(params["classifier"], pooled).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    return {
        "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
        "score": jnp.max(probs, axis=-1),
        "logits": logits,
    }


def apply_packed(params: dict, cfg: BertConfig, *, input_ids, segment_ids,
                 position_ids, example_row, example_pos) -> dict:
    """Packed-execution forward (tpu/packing.py layout): [P, S] packed rows
    holding E examples. Attention is block-diagonal on ``segment_ids``
    (tokens never attend across examples; 0 marks dead positions), position
    embeddings follow ``position_ids``, and each example's [CLS] encoding is
    gathered from (example_row, example_pos) — outputs are [E] in original
    example order. Fully-dead padded rows are sliced away by the caller
    (their un-gathered encodings are path-dependent: uniform attention on
    the XLA pair-mask path, exact zeros on the segment-kernel path).
    """
    seg = segment_ids
    live = (seg > 0).astype(jnp.int32)
    if cfg.packed_flash and input_ids.shape[1] >= (cfg.flash_min_seq or 0):
        # opt-in segment flash kernel (ops/segment_attention.py): in-kernel
        # block-diagonal masking, no O(S^2) mask in HBM. cfg-resolved (see
        # packed_flash) so the kill switch and backend checks happen at
        # runner altitude, never as an env read inside the jit.
        x = encode(params, cfg, input_ids, live,
                   positions=position_ids, segments=seg)
    else:
        pair = (seg[:, None, :] == seg[:, :, None]) & (seg > 0)[:, None, :]
        pair_mask = pair[:, None, :, :]  # [P, 1, Sq, Sk], broadcast over heads
        x = encode(params, cfg, input_ids, live,
                   positions=position_ids, pair_mask=pair_mask)
    cls = x[example_row, example_pos, :]  # [E, hidden]
    pooled = jnp.tanh(cm.dense(params["pooler"], cls))
    logits = cm.dense(params["classifier"], pooled).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    return {
        "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
        "score": jnp.max(probs, axis=-1),
        "logits": logits,
    }


def pp_stage_fns(cfg: BertConfig):
    """Stage bodies for pipelined-parallel serving (parallel/pipeline.py
    ``make_pp_infer_step``): embeddings -> per-layer encoder block -> pooler/
    classifier head. The layer math mirrors ``encode``'s XLA-attention scan
    body exactly (pp serving always resolves flash OFF under a mesh, like
    every sharded path), so pp outputs are bitwise-identical to the
    single-device XLA path per row."""

    def pre(params: dict, inputs: dict):
        input_ids = inputs["input_ids"]
        attention_mask = inputs["attention_mask"]
        b, s = input_ids.shape
        positions = jnp.arange(s)[None, :]
        x = (
            cm.embedding(params["embed"]["word"], input_ids)
            + cm.embedding(params["embed"]["position"], positions)
            + cm.embedding(params["embed"]["token_type"], jnp.zeros_like(input_ids))
        )
        x = cm.layer_norm(params["embed"]["ln"], x, cfg.ln_eps)
        # [B,1,1,Sk] like encode(); rides aux so each microbatch slices its
        # own rows' masks
        mask = attention_mask[:, None, None, :].astype(bool)
        return x, {"mask": mask}

    def layer(lp: dict, x, aux: dict):
        b, s = x.shape[0], x.shape[1]
        h = cfg.heads
        dh = cfg.hidden // h
        q = cm.dense(lp["q"], x).reshape(b, s, h, dh)
        k = cm.dense(lp["k"], x).reshape(b, s, h, dh)
        v = cm.dense(lp["v"], x).reshape(b, s, h, dh)
        attn = cm.attention(q, k, v, aux["mask"],
                            softmax_dtype=jnp.dtype(cfg.softmax_dtype))
        attn = attn.reshape(b, s, cfg.hidden)
        x = cm.layer_norm(lp["attn_ln"], x + cm.dense(lp["attn_out"], attn), cfg.ln_eps)
        ff = cm.dense(lp["ffn_out"], cm.gelu(cm.dense(lp["ffn_in"], x)))
        return cm.layer_norm(lp["ffn_ln"], x + ff, cfg.ln_eps)

    def post(params: dict, x, aux: dict):
        pooled = jnp.tanh(cm.dense(params["pooler"], x[:, 0, :]))
        logits = cm.dense(params["classifier"], pooled).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        return {
            "label": jnp.argmax(logits, axis=-1).astype(jnp.int32),
            "score": jnp.max(probs, axis=-1),
            "logits": logits,
        }

    return pre, layer, post


def input_spec(cfg: BertConfig) -> dict:
    return {"input_ids": ("int32", ("seq",)), "attention_mask": ("int32", ("seq",))}


def packed_input_spec(cfg: BertConfig) -> dict:
    """Input spec for packed execution. Leading-dim roles: ``packed`` arrays
    share the packed-row dim P; ``example`` arrays share the example dim E."""
    return {
        "input_ids": ("int32", ("seq",)),
        "segment_ids": ("int32", ("seq",)),
        "position_ids": ("int32", ("seq",)),
        "example_row": ("int32", ()),
        "example_pos": ("int32", ()),
    }


def param_specs(cfg: BertConfig, axes: dict) -> dict:
    """PartitionSpecs for tensor-parallel serving: heads/FFN sharded on ``tp``.

    ``axes`` maps logical axis roles to mesh axis names, e.g. {"tp": "tp"}.
    """
    tp = axes.get("tp")
    d = lambda spec_w: {"w": spec_w, "b": P(spec_w[-1])}  # bias follows output dim
    layer = {
        "q": d(P(None, tp)),
        "k": d(P(None, tp)),
        "v": d(P(None, tp)),
        "attn_out": d(P(tp, None)),
        "attn_ln": {"scale": P(None), "bias": P(None)},
        "ffn_in": d(P(None, tp)),
        "ffn_out": d(P(tp, None)),
        "ffn_ln": {"scale": P(None), "bias": P(None)},
    }
    # layer params are stacked with a leading scan axis -> prepend None
    layer = jax.tree_util.tree_map(lambda s: P(None, *s), layer,
                                   is_leaf=lambda x: isinstance(x, P))
    return {
        "embed": {
            "word": {"table": P(tp, None)},
            "position": {"table": P(None, None)},
            "token_type": {"table": P(None, None)},
            "ln": {"scale": P(None), "bias": P(None)},
        },
        "layers": layer,
        "pooler": d(P(None, tp)),
        "classifier": d(P(None, None)),
    }


def from_hf_state_dict(state: dict, cfg: BertConfig) -> dict:
    """Convert a HuggingFace ``BertForSequenceClassification`` state_dict
    (torch tensors — any dtype including bfloat16 — or numpy) into this
    model's param pytree."""

    def t(name, transpose=False):
        return cm.hf_tensor(state, name, transpose)

    def lin(prefix):
        return {"w": t(f"{prefix}.weight", transpose=True), "b": t(f"{prefix}.bias")}

    def ln(prefix):
        return {"scale": t(f"{prefix}.weight"), "bias": t(f"{prefix}.bias")}

    e = "bert.embeddings"
    layers = []
    for i in range(cfg.layers):
        p = f"bert.encoder.layer.{i}"
        layers.append(
            {
                "q": lin(f"{p}.attention.self.query"),
                "k": lin(f"{p}.attention.self.key"),
                "v": lin(f"{p}.attention.self.value"),
                "attn_out": lin(f"{p}.attention.output.dense"),
                "attn_ln": ln(f"{p}.attention.output.LayerNorm"),
                "ffn_in": lin(f"{p}.intermediate.dense"),
                "ffn_out": lin(f"{p}.output.dense"),
                "ffn_ln": ln(f"{p}.output.LayerNorm"),
            }
        )
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    return {
        "embed": {
            "word": {"table": t(f"{e}.word_embeddings.weight")},
            "position": {"table": t(f"{e}.position_embeddings.weight")},
            "token_type": {"table": t(f"{e}.token_type_embeddings.weight")},
            "ln": ln(f"{e}.LayerNorm"),
        },
        "layers": stacked,
        "pooler": lin("bert.pooler.dense"),
        "classifier": lin("classifier"),
    }


register_model(
    ModelFamily(
        name="bert_classifier",
        make_config=BertConfig,
        init=init,
        apply=apply,
        input_spec=input_spec,
        param_specs=param_specs,
        extras={
            "from_hf_state_dict": from_hf_state_dict,
            "apply_packed": apply_packed,
            "packed_input_spec": packed_input_spec,
            "pp_stage_fns": pp_stage_fns,
        },
    )
)
