"""Model registry: name -> ModelFamily (init/apply/signature metadata)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from arkflow_tpu.errors import ConfigError


@dataclass
class ModelFamily:
    """A streaming-servable model family.

    - ``make_config(**overrides)``: build the family's config dataclass.
    - ``init(rng, cfg)``: params pytree.
    - ``apply(params, cfg, **inputs)``: jittable forward; returns dict of outputs.
    - ``input_spec(cfg)``: dict input_name -> ("int32"|"float32", trailing shape)
      describing per-example features (leading batch dim implied); the runner
      uses it for bucketing/padding.
    - ``param_specs(cfg, axes)``: optional PartitionSpec pytree for multi-chip.
    """

    name: str
    make_config: Callable[..., Any]
    init: Callable[..., Any]
    apply: Callable[..., dict]
    input_spec: Callable[[Any], dict]
    param_specs: Optional[Callable[[Any, dict], Any]] = None
    extras: dict = field(default_factory=dict)


_REGISTRY: dict[str, ModelFamily] = {}


def register_model(family: ModelFamily) -> ModelFamily:
    if family.name in _REGISTRY:
        raise ConfigError(f"model family {family.name!r} already registered")
    _REGISTRY[family.name] = family
    return family


def get_model(name: str) -> ModelFamily:
    fam = _REGISTRY.get(name)
    if fam is None:
        raise ConfigError(f"unknown model family {name!r} (available: {sorted(_REGISTRY)})")
    return fam


def list_models() -> list[str]:
    return sorted(_REGISTRY)
