"""ViT-B/16 image embedder (HTTP image ingest -> embedding -> vector sink).

BASELINE.json config 4. Patchify is a reshape + single [P*P*C, D] matmul
(equivalent to the conv patch-embed but expressed as a dense layer the MXU
tiles perfectly); 12 pre-LN transformer layers, CLS-token embedding out.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from arkflow_tpu.models import common as cm
from arkflow_tpu.models.registry import ModelFamily, register_model


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch: int = 16
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn: int = 3072
    channels: int = 3

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch) ** 2


def init(rng, cfg: ViTConfig) -> dict:
    keys = iter(jax.random.split(rng, 8 + 8 * cfg.layers))
    patch_dim = cfg.patch * cfg.patch * cfg.channels
    params = {
        "patch_embed": cm.dense_init(next(keys), patch_dim, cfg.hidden),
        "cls": jax.random.normal(next(keys), (1, 1, cfg.hidden), jnp.float32) * 0.02,
        "pos": jax.random.normal(next(keys), (1, cfg.num_patches + 1, cfg.hidden), jnp.float32) * 0.02,
        "ln_out": cm.layer_norm_init(cfg.hidden),
        "layers": [],
    }
    for _ in range(cfg.layers):
        params["layers"].append(
            {
                "ln1": cm.layer_norm_init(cfg.hidden),
                "q": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "k": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "v": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "attn_out": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "ln2": cm.layer_norm_init(cfg.hidden),
                "ffn_in": cm.dense_init(next(keys), cfg.hidden, cfg.ffn),
                "ffn_out": cm.dense_init(next(keys), cfg.ffn, cfg.hidden),
            }
        )
    params["layers"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params["layers"])
    return params


def _patchify(images: jnp.ndarray, cfg: ViTConfig) -> jnp.ndarray:
    """[B, H, W, C] -> [B, N, P*P*C] by pure reshape/transpose (no conv)."""
    b, h, w, c = images.shape
    p = cfg.patch
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def apply(params: dict, cfg: ViTConfig, *, images) -> dict:
    """images: [B, H, W, C] float32 in [0,1] -> {"embedding": [B, hidden]}."""
    b = images.shape[0]
    x = cm.dense(params["patch_embed"], _patchify(images.astype(jnp.bfloat16), cfg))
    cls = jnp.broadcast_to(params["cls"].astype(x.dtype), (b, 1, cfg.hidden))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(x.dtype)
    s = x.shape[1]

    def layer(x, lp):
        h, dh = cfg.heads, cfg.hidden // cfg.heads
        y = cm.layer_norm(lp["ln1"], x)
        q = cm.dense(lp["q"], y).reshape(b, s, h, dh)
        k = cm.dense(lp["k"], y).reshape(b, s, h, dh)
        v = cm.dense(lp["v"], y).reshape(b, s, h, dh)
        x = x + cm.dense(lp["attn_out"], cm.attention(q, k, v).reshape(b, s, cfg.hidden))
        y = cm.layer_norm(lp["ln2"], x)
        x = x + cm.dense(lp["ffn_out"], cm.gelu(cm.dense(lp["ffn_in"], y)))
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    emb = cm.layer_norm(params["ln_out"], x)[:, 0, :].astype(jnp.float32)
    return {"embedding": emb}


def input_spec(cfg: ViTConfig) -> dict:
    return {"images": ("float32", (cfg.image_size, cfg.image_size, cfg.channels))}


def from_hf_state_dict(state: dict, cfg: ViTConfig) -> dict:
    """Convert a HuggingFace ViT state_dict into this model's params.

    Accepts both ``ViTForImageClassification`` dicts (``vit.``-prefixed keys)
    and bare ``ViTModel`` dicts (``embeddings.``/``encoder.`` keys).
    The conv patch-projection maps onto our dense patchify: with our flatten
    order (row, col, channel), ``dense_w[(i*P + j)*C + c, d] = conv_w[d, c, i, j]``
    i.e. ``conv_w.transpose(2, 3, 1, 0).reshape(P*P*C, D)``.
    """
    prefixed = any(k.startswith("vit.") for k in state)

    def t(name, transpose=False):
        return cm.hf_tensor(state, name if prefixed else name[len("vit."):], transpose)

    conv_w = t("vit.embeddings.patch_embeddings.projection.weight")
    patch_w = jnp.transpose(conv_w, (2, 3, 1, 0)).reshape(-1, cfg.hidden)
    layers = []
    for i in range(cfg.layers):
        p = f"vit.encoder.layer.{i}"
        layers.append(
            {
                "ln1": {"scale": t(f"{p}.layernorm_before.weight"),
                        "bias": t(f"{p}.layernorm_before.bias")},
                "q": {"w": t(f"{p}.attention.attention.query.weight", True),
                      "b": t(f"{p}.attention.attention.query.bias")},
                "k": {"w": t(f"{p}.attention.attention.key.weight", True),
                      "b": t(f"{p}.attention.attention.key.bias")},
                "v": {"w": t(f"{p}.attention.attention.value.weight", True),
                      "b": t(f"{p}.attention.attention.value.bias")},
                "attn_out": {"w": t(f"{p}.attention.output.dense.weight", True),
                             "b": t(f"{p}.attention.output.dense.bias")},
                "ln2": {"scale": t(f"{p}.layernorm_after.weight"),
                        "bias": t(f"{p}.layernorm_after.bias")},
                "ffn_in": {"w": t(f"{p}.intermediate.dense.weight", True),
                           "b": t(f"{p}.intermediate.dense.bias")},
                "ffn_out": {"w": t(f"{p}.output.dense.weight", True),
                            "b": t(f"{p}.output.dense.bias")},
            }
        )
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    return {
        "patch_embed": {
            "w": jnp.asarray(patch_w),
            "b": t("vit.embeddings.patch_embeddings.projection.bias"),
        },
        "cls": t("vit.embeddings.cls_token"),
        "pos": t("vit.embeddings.position_embeddings"),
        "ln_out": {"scale": t("vit.layernorm.weight"), "bias": t("vit.layernorm.bias")},
        "layers": stacked,
    }


register_model(
    ModelFamily(
        name="vit_embedder",
        make_config=ViTConfig,
        init=init,
        apply=apply,
        input_spec=input_spec,
        extras={"from_hf_state_dict": from_hf_state_dict},
    )
)
