"""ViT-B/16 image embedder (HTTP image ingest -> embedding -> vector sink).

BASELINE.json config 4. Patchify is a reshape + single [P*P*C, D] matmul
(equivalent to the conv patch-embed but expressed as a dense layer the MXU
tiles perfectly); 12 pre-LN transformer layers, CLS-token embedding out.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from arkflow_tpu.models import common as cm
from arkflow_tpu.models.registry import ModelFamily, register_model


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch: int = 16
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn: int = 3072
    channels: int = 3

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch) ** 2


def init(rng, cfg: ViTConfig) -> dict:
    keys = iter(jax.random.split(rng, 8 + 8 * cfg.layers))
    patch_dim = cfg.patch * cfg.patch * cfg.channels
    params = {
        "patch_embed": cm.dense_init(next(keys), patch_dim, cfg.hidden),
        "cls": jax.random.normal(next(keys), (1, 1, cfg.hidden), jnp.float32) * 0.02,
        "pos": jax.random.normal(next(keys), (1, cfg.num_patches + 1, cfg.hidden), jnp.float32) * 0.02,
        "ln_out": cm.layer_norm_init(cfg.hidden),
        "layers": [],
    }
    for _ in range(cfg.layers):
        params["layers"].append(
            {
                "ln1": cm.layer_norm_init(cfg.hidden),
                "q": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "k": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "v": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "attn_out": cm.dense_init(next(keys), cfg.hidden, cfg.hidden),
                "ln2": cm.layer_norm_init(cfg.hidden),
                "ffn_in": cm.dense_init(next(keys), cfg.hidden, cfg.ffn),
                "ffn_out": cm.dense_init(next(keys), cfg.ffn, cfg.hidden),
            }
        )
    params["layers"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params["layers"])
    return params


def _patchify(images: jnp.ndarray, cfg: ViTConfig) -> jnp.ndarray:
    """[B, H, W, C] -> [B, N, P*P*C] by pure reshape/transpose (no conv)."""
    b, h, w, c = images.shape
    p = cfg.patch
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def apply(params: dict, cfg: ViTConfig, *, images) -> dict:
    """images: [B, H, W, C] float32 in [0,1] -> {"embedding": [B, hidden]}."""
    b = images.shape[0]
    x = cm.dense(params["patch_embed"], _patchify(images.astype(jnp.bfloat16), cfg))
    cls = jnp.broadcast_to(params["cls"].astype(x.dtype), (b, 1, cfg.hidden))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(x.dtype)
    s = x.shape[1]

    def layer(x, lp):
        h, dh = cfg.heads, cfg.hidden // cfg.heads
        y = cm.layer_norm(lp["ln1"], x)
        q = cm.dense(lp["q"], y).reshape(b, s, h, dh)
        k = cm.dense(lp["k"], y).reshape(b, s, h, dh)
        v = cm.dense(lp["v"], y).reshape(b, s, h, dh)
        x = x + cm.dense(lp["attn_out"], cm.attention(q, k, v).reshape(b, s, cfg.hidden))
        y = cm.layer_norm(lp["ln2"], x)
        x = x + cm.dense(lp["ffn_out"], cm.gelu(cm.dense(lp["ffn_in"], y)))
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    emb = cm.layer_norm(params["ln_out"], x)[:, 0, :].astype(jnp.float32)
    return {"embedding": emb}


def input_spec(cfg: ViTConfig) -> dict:
    return {"images": ("float32", (cfg.image_size, cfg.image_size, cfg.channels))}


register_model(
    ModelFamily(
        name="vit_embedder",
        make_config=ViTConfig,
        init=init,
        apply=apply,
        input_spec=input_spec,
    )
)
