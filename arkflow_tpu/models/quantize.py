"""W8A8 dynamic-int8 serving quantization.

Why: the BASELINE north star (>=100k rows/sec/chip of BERT-base) is above the
bf16 roofline of a v5e chip (~197 TFLOP/s; seq-32 BERT-base needs ~5.4 GFLOP
per row). The MXU's int8 path doubles that ceiling (~394 TOPS), so serving
throughput scales past what any bf16 schedule can reach. The reference engine
has no analog (its "model" slot is user Python, ref
crates/arkflow-plugin/src/processor/python.rs); this is TPU-native headroom.

Scheme (standard dynamic W8A8):
- Weights: symmetric per-output-channel int8 at load time
  (``scale = absmax(in_dim)/127``), stored as ``{"w_q": int8, "w_scale": f32}``
  beside the original bias. Works on scan-stacked layer params too: the
  leading stack axis rides along in both ``w_q`` and ``w_scale``.
- Activations: symmetric per-row dynamic int8 inside the jitted step
  (absmax over the feature dim — a cheap fused reduction).
- Matmul: int8 x int8 -> int32 on the MXU, dequantized by
  ``row_scale * col_scale`` and biased in the compute dtype.

``common.dense`` dispatches on the presence of ``w_q``, so every model family
whose dense layers go through it serves int8 without touching model code.
Embeddings, layer norms, and attention score/value einsums stay bf16/f32
(lookup- or activation-only; negligible FLOPs at serving shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: dense-param dicts are {"w": [in, out] (or [..., in, out] stacked), "b"?}
_WEIGHT_KEY = "w"


def quantize_dense(p: dict) -> dict:
    """One dense-param dict -> its W8A8 serving form (bias kept, bf16)."""
    w = p[_WEIGHT_KEY]
    scale = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0  # [..., 1, out]
    scale = jnp.maximum(scale, 1e-8)
    w_q = jnp.round(w / scale).astype(jnp.int8)
    out = {"w_q": w_q, "w_scale": scale.astype(jnp.float32)}
    if "b" in p:
        out["b"] = p["b"].astype(jnp.bfloat16)
    return out


def quantize_for_serving(params) -> tuple["dict", int]:
    """Walk a param pytree: int8-quantize every dense dict, cast the
    remaining float leaves (embeddings, norms, non-dense tensors) to bf16.
    Returns (new_params, quantized_dense_count)."""
    count = 0

    def walk(node):
        nonlocal count
        if isinstance(node, dict):
            w = node.get(_WEIGHT_KEY)
            if w is not None and hasattr(w, "dtype") and jnp.issubdtype(
                    w.dtype, jnp.floating) and w.ndim >= 2:
                count += 1
                return quantize_dense(node)
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        if hasattr(node, "dtype") and jnp.issubdtype(node.dtype, jnp.floating):
            return node.astype(jnp.bfloat16)
        return node

    return walk(params), count


def quantize_param_specs(specs):
    """Mirror ``quantize_for_serving`` over a PartitionSpec pytree so int8
    param trees shard under the same mesh layouts as their float originals.

    Every dense-spec dict ``{"w": P(..., in_ax, out_ax), "b"?: ...}`` becomes
    ``{"w_q": <w spec>, "w_scale": <w spec with the in-dim axis replicated>,
    "b"?: ...}``: ``w_q`` keeps the weight's layout exactly (same shape), and
    ``w_scale`` has a size-1 in-dim (`[..., 1, out]`), which cannot be split
    over a >1 mesh axis, so that entry is forced to None while the out-dim
    sharding rides along. Non-dense specs (embeddings, norms, MoE expert
    stacks) pass through untouched — quantization leaves those params alone.

    Contract (matches quantize_for_serving's predicate): a dict with a ``w``
    key is a dense layer. Families keep non-dense weights under other names
    (``table``, ``scale``, ``w_gate``...), so key presence is sufficient.
    """
    from jax.sharding import PartitionSpec as P

    def walk(node):
        if isinstance(node, dict):
            if _WEIGHT_KEY in node:
                w = node[_WEIGHT_KEY]
                entries = tuple(w) if isinstance(w, P) else ()
                if len(entries) >= 2:
                    scale = P(*entries[:-2], None, entries[-1])
                else:  # replicated / underspecified weight spec
                    scale = P()
                out = {"w_q": w, "w_scale": scale}
                if "b" in node:
                    out["b"] = node["b"]
                return out
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, P):
            # PartitionSpec subclasses tuple: the container branch below
            # would rebuild it as P(<generator>,) — a malformed spec that
            # only detonates at NamedSharding validation under a mesh
            return node
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(specs)


def dense_w8a8(p: dict, x: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """int8 dynamic-activation dense: quantize rows of ``x``, int8 matmul
    (int32 accumulate on the MXU), dequantize, bias."""
    xf = x.astype(jnp.float32)
    row_scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True), 1e-6) / 127.0
    x_q = jnp.round(xf / row_scale).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, p["w_q"],
        (((x_q.ndim - 1,), (p["w_q"].ndim - 2,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    # w_scale is [..., 1, out]; drop its kept in-dim axis to broadcast [out]
    w_scale = jnp.squeeze(p["w_scale"], axis=-2)
    y = (acc.astype(jnp.float32) * row_scale * w_scale).astype(dtype)
    if "b" in p:
        y = y + p["b"].astype(dtype)
    return y
