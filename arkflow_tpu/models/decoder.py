"""Decoder-only LM (Llama-style): GQA + RoPE + RMSNorm + SwiGLU.

BASELINE.json config 5 (Kafka CDC -> batched summarization -> NATS) and the
framework's multi-chip flagship: parameters carry tensor-parallel
PartitionSpecs, activations carry (dp, sp) sharding constraints, and the full
training step (loss + adamw update) jits over an arbitrary
``Mesh(dp, tp, sp)`` — GSPMD inserts the ICI collectives. Long-context
attention can also run as an explicit ring over the ``sp`` axis
(arkflow_tpu.parallel.ring_attention) when sequence length exceeds one chip's
HBM.

Defaults are a small test shape; ``llama3_8b()`` gives the production shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from arkflow_tpu.models import common as cm
from arkflow_tpu.models.registry import ModelFamily, register_model


@dataclass(frozen=True)
class DecoderConfig:
    vocab_size: int = 2048
    dim: int = 256
    layers: int = 4
    heads: int = 8
    kv_heads: int = 4
    ffn: int = 688
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    #: route attention through the explicit sp-ring (long context): requires a
    #: mesh with an "sp" axis passed to forward/train_step
    use_ring_attention: bool = False
    #: >1 turns the MLP into a switch-style top-1 MoE; experts shard over the
    #: "ep" mesh axis via capacity-based dispatch/combine einsums (GSPMD turns
    #: the expert dim into true expert parallelism). Tokens beyond an expert's
    #: capacity are dropped (standard Switch behavior).
    num_experts: int = 0
    #: expert capacity = ceil(tokens / num_experts * capacity_factor)
    capacity_factor: float = 1.25
    #: Switch load-balance aux loss weight (alpha); without it top-1 routing
    #: collapses onto one expert and capacity overflow zeroes most tokens
    router_aux_weight: float = 0.01
    #: router z-loss weight (penalizes large router logits for stability)
    router_z_weight: float = 1e-3
    #: rematerialize each layer in the backward pass (jax.checkpoint): trades
    #: FLOPs for HBM so long-context training fits (activations are O(layers)
    #: otherwise)
    remat: bool = False


def llama3_8b() -> DecoderConfig:
    return DecoderConfig(
        vocab_size=128256, dim=4096, layers=32, heads=32, kv_heads=8,
        ffn=14336, max_seq=8192,
    )


def init(rng, cfg: DecoderConfig) -> dict:
    dh = cfg.dim // cfg.heads
    keys = iter(jax.random.split(rng, 4 + 7 * cfg.layers))
    params = {
        "embed": cm.embedding_init(next(keys), cfg.vocab_size, cfg.dim),
        "norm_out": cm.rms_norm_init(cfg.dim),
        "lm_head": cm.dense_init(next(keys), cfg.dim, cfg.vocab_size, bias=False),
        "layers": [],
    }
    for _ in range(cfg.layers):
        layer = {
            "attn_norm": cm.rms_norm_init(cfg.dim),
            "wq": cm.dense_init(next(keys), cfg.dim, cfg.heads * dh, bias=False),
            "wk": cm.dense_init(next(keys), cfg.dim, cfg.kv_heads * dh, bias=False),
            "wv": cm.dense_init(next(keys), cfg.dim, cfg.kv_heads * dh, bias=False),
            "wo": cm.dense_init(next(keys), cfg.heads * dh, cfg.dim, bias=False),
            "mlp_norm": cm.rms_norm_init(cfg.dim),
        }
        if cfg.num_experts > 1:
            e = cfg.num_experts
            sub = jax.random.split(next(keys), 4)
            scale = 1.0 / (cfg.dim ** 0.5)
            layer["router"] = cm.dense_init(sub[0], cfg.dim, e, bias=False)
            layer["experts"] = {
                "w_gate": jax.random.uniform(sub[1], (e, cfg.dim, cfg.ffn), jnp.float32, -scale, scale),
                "w_up": jax.random.uniform(sub[2], (e, cfg.dim, cfg.ffn), jnp.float32, -scale, scale),
                "w_down": jax.random.uniform(sub[3], (e, cfg.ffn, cfg.dim), jnp.float32, -scale, scale),
            }
        else:
            layer["w_gate"] = cm.dense_init(next(keys), cfg.dim, cfg.ffn, bias=False)
            layer["w_up"] = cm.dense_init(next(keys), cfg.dim, cfg.ffn, bias=False)
            layer["w_down"] = cm.dense_init(next(keys), cfg.ffn, cfg.dim, bias=False)
        params["layers"].append(layer)
    params["layers"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params["layers"])
    return params


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embedding. x: [B, S, H, Dh]; positions: [B, S]."""
    dh = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, dh, 2, dtype=jnp.float32) / dh))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, Dh/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _moe_mlp(lp: dict, y: jnp.ndarray, cfg: DecoderConfig,
             token_mask=None) -> jnp.ndarray:
    """Switch-style top-1 MoE SwiGLU with capacity-based dispatch/combine.

    Each token routes to its top expert; tokens queue into per-expert capacity
    slots (cumsum position) and overflow drops to zero output. Compute is
    dispatch -> per-expert SwiGLU on [E, C, D] -> combine, so FLOPs scale with
    ``tokens * capacity_factor`` regardless of expert count, and GSPMD shards
    the E dim over the "ep" mesh axis (param specs) — the dispatch/combine
    einsums become the all-to-all.

    ``token_mask`` ([B, S] bool/int) excludes tokens (right padding, inactive
    serving lanes) from routing entirely: they consume NO expert capacity and
    produce zero MLP output — otherwise one row's padding could evict another
    row's real tokens from a full expert queue.
    """
    import math

    ex = lp["experts"]
    dtype = y.dtype
    b, s, d = y.shape
    e = ex["w_gate"].shape[0]
    tokens = b * s
    capacity = max(1, math.ceil(tokens / e * cfg.capacity_factor))

    yf = y.reshape(tokens, d)
    router_logits = cm.dense(lp["router"], yf, dtype=jnp.float32)  # [T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)  # [T]
    weight = jnp.take_along_axis(probs, top[:, None], axis=-1)[:, 0]  # [T]
    expert_onehot = jax.nn.one_hot(top, e, dtype=jnp.float32)  # [T, E]
    if token_mask is not None:
        expert_onehot = expert_onehot * token_mask.reshape(tokens, 1).astype(jnp.float32)
    # position of each token in its expert's queue: the routed column holds
    # position+1, others 0; sum over E then subtract 1
    pos_plus1 = (jnp.cumsum(expert_onehot, axis=0) * expert_onehot).sum(axis=-1)
    pos_idx = pos_plus1.astype(jnp.int32) - 1  # [T]
    keep = (pos_idx >= 0) & (pos_idx < capacity)
    slot_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32) * keep[:, None]
    dispatch = jnp.einsum("te,tc->tec", expert_onehot, slot_onehot)  # [T, E, C]

    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dtype), yf.astype(dtype))
    gate = jnp.einsum("ecd,edf->ecf", expert_in, ex["w_gate"].astype(dtype))
    up = jnp.einsum("ecd,edf->ecf", expert_in, ex["w_up"].astype(dtype))
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(dtype) * up
    expert_out = jnp.einsum("ecf,efd->ecd", act, ex["w_down"].astype(dtype))

    combine = dispatch * weight[:, None, None]  # routing prob folded in
    out = jnp.einsum("tec,ecd->td", combine.astype(jnp.float32),
                     expert_out.astype(jnp.float32))

    # Switch aux stats: f_e = fraction of tokens routed to expert e, P_e =
    # mean router prob; lb = E * sum(f*P) is minimized by uniform routing.
    # z = mean(logsumexp(logits)^2) keeps router logits small.
    frac = expert_onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    lb = e * jnp.sum(frac * mean_prob)
    z = jnp.mean(jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2)
    return out.reshape(b, s, d).astype(dtype), (lb, z)


def _attention_block(lp: dict, x: jnp.ndarray, cfg: DecoderConfig, positions,
                     causal=None, ring_attn=None) -> jnp.ndarray:
    """Shared pre-norm GQA attention block (rope, kv-head repeat, residual).

    ``ring_attn`` substitutes the sp-ring kernel for plain masked attention.
    Used by forward() and the pipeline-parallel stage apply — one source of
    truth for the layer math."""
    b, s = positions.shape
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads
    y = cm.rms_norm(lp["attn_norm"], x, cfg.norm_eps)
    q = cm.dense(lp["wq"], y).reshape(b, s, cfg.heads, dh)
    k = cm.dense(lp["wk"], y).reshape(b, s, cfg.kv_heads, dh)
    v = cm.dense(lp["wv"], y).reshape(b, s, cfg.kv_heads, dh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    if ring_attn is not None:
        attn = ring_attn(q, k, v)
    else:
        attn = cm.attention(q, k, v, causal)
    return x + cm.dense(lp["wo"], attn.reshape(b, s, cfg.heads * dh))


def _mlp(lp: dict, y: jnp.ndarray, cfg: DecoderConfig, token_mask=None) -> jnp.ndarray:
    """Dense SwiGLU or Switch MoE, depending on cfg (aux stats dropped) —
    the shared MLP for the incremental-decode paths, where the aux loss is
    irrelevant."""
    if cfg.num_experts > 1:
        out, _aux = _moe_mlp(lp, y, cfg, token_mask=token_mask)
        return out
    gate = jax.nn.silu(cm.dense(lp["w_gate"], y).astype(jnp.float32)).astype(y.dtype)
    return cm.dense(lp["w_down"], gate * cm.dense(lp["w_up"], y))


def _shard_act(x, axes):
    """Constrain [B, S, ...] activations to (dp, sp) when a mesh is active."""
    if not axes:
        return x
    spec = P(axes.get("dp"), axes.get("sp"), *([None] * (x.ndim - 2)))
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x  # no mesh in scope (single-chip eager/test path)


def forward(params: dict, cfg: DecoderConfig, input_ids, *, axes=None, mesh=None,
            return_aux: bool = False):
    """[B, S] ids -> [B, S, vocab] float32 logits (causal).

    With ``cfg.use_ring_attention`` and a mesh carrying an ``sp`` axis, the
    attention core runs as an explicit K/V ring over sequence shards
    (arkflow_tpu.parallel.ring_attention) instead of GSPMD's default
    all-gather — O(S/n) attention memory per chip for long context.
    """
    axes = axes or {}
    b, s = input_ids.shape
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads
    x = cm.embedding(params["embed"], input_ids)
    x = _shard_act(x, axes)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    causal = jnp.tril(jnp.ones((s, s), bool))[None, None, :, :]

    ring_attn = None
    if cfg.use_ring_attention and mesh is not None and axes.get("sp"):
        from arkflow_tpu.parallel.ring_attention import make_ring_attention_spec

        ring_attn = make_ring_attention_spec(
            mesh, sp_axis=axes["sp"], batch_axis=axes.get("dp"),
            head_axis=axes.get("tp"), causal=True,
        )

    def layer(x, lp):
        x = _attention_block(lp, x, cfg, positions, causal, ring_attn)
        x = _shard_act(x, axes)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        if cfg.num_experts > 1:
            moe_out, aux = _moe_mlp(lp, y, cfg)
            x = x + moe_out
        else:
            gate = jax.nn.silu(cm.dense(lp["w_gate"], y).astype(jnp.float32)).astype(y.dtype)
            x = x + cm.dense(lp["w_down"], gate * cm.dense(lp["w_up"], y))
            aux = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        return _shard_act(x, axes), aux

    # prevent_cse=False: scan already isolates iterations, and the default
    # optimization barriers would block XLA fusion in the backward pass
    scan_body = jax.checkpoint(layer, prevent_cse=False) if cfg.remat else layer
    x, (lb_per_layer, z_per_layer) = jax.lax.scan(scan_body, x, params["layers"])
    x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
    logits = cm.dense(params["lm_head"], x).astype(jnp.float32)
    if return_aux:
        return logits, {"load_balance": lb_per_layer.mean(), "router_z": z_per_layer.mean()}
    return logits


def apply(params: dict, cfg: DecoderConfig, *, input_ids, axes=None, mesh=None) -> dict:
    logits = forward(params, cfg, input_ids, axes=axes, mesh=mesh)
    return {"logits": logits, "next_token": jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)}


def loss_fn(params: dict, cfg: DecoderConfig, input_ids, targets, mask, *, axes=None, mesh=None):
    """Causal LM cross-entropy, mean over unmasked target tokens.

    MoE configs additionally carry the Switch load-balance aux loss and
    router z-loss (weighted by ``router_aux_weight`` / ``router_z_weight``)
    — without them top-1 routing collapses onto a single expert.
    """
    logits, aux = forward(params, cfg, input_ids, axes=axes, mesh=mesh, return_aux=True)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    maskf = mask.astype(jnp.float32)
    loss = -(ll * maskf).sum() / jnp.maximum(maskf.sum(), 1.0)
    if cfg.num_experts > 1:
        loss = (loss
                + cfg.router_aux_weight * aux["load_balance"]
                + cfg.router_z_weight * aux["router_z"])
    return loss


def make_train_step(cfg: DecoderConfig, optimizer, *, axes=None, mesh=None):
    """Returns ``train_step(params, opt_state, batch) -> (params, opt_state, loss)``.

    Jit this over a Mesh with sharded params/batch for the full
    dp x tp x sp distributed step.
    """

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, cfg, batch["input_ids"], batch["targets"], batch["mask"],
            axes=axes, mesh=mesh,
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def param_specs(cfg: DecoderConfig, axes: dict) -> dict:
    """Sharding layout: attention heads and FFN over ``tp``; expert dim over
    ``ep`` (MoE); embed/lm_head on the vocab dim; norms replicated."""
    tp = axes.get("tp")
    ep = axes.get("ep")
    layer = {
        "attn_norm": {"scale": P(None)},
        "wq": {"w": P(None, tp)},
        "wk": {"w": P(None, tp)},
        "wv": {"w": P(None, tp)},
        "wo": {"w": P(tp, None)},
        "mlp_norm": {"scale": P(None)},
    }
    if cfg.num_experts > 1:
        layer["router"] = {"w": P(None, None)}
        layer["experts"] = {
            "w_gate": P(ep, None, tp),
            "w_up": P(ep, None, tp),
            "w_down": P(ep, tp, None),
        }
    else:
        layer["w_gate"] = {"w": P(None, tp)}
        layer["w_up"] = {"w": P(None, tp)}
        layer["w_down"] = {"w": P(tp, None)}
    layer = jax.tree_util.tree_map(
        lambda sp: P(None, *sp), layer, is_leaf=lambda x: isinstance(x, P)
    )
    return {
        "embed": {"table": P(tp, None)},
        "norm_out": {"scale": P(None)},
        "lm_head": {"w": P(None, tp)},
        "layers": layer,
    }


def from_hf_state_dict(state: dict, cfg: DecoderConfig) -> dict:
    """Convert a HuggingFace ``LlamaForCausalLM`` state_dict (torch tensors —
    any dtype including bfloat16 — or numpy arrays) into this model's param
    pytree. Linear weights transpose from torch's [out, in] to [in, out]."""
    if cfg.num_experts > 1:
        raise ValueError("from_hf_state_dict maps dense Llama checkpoints; MoE configs unsupported")

    def t(name, transpose=False):
        return cm.hf_tensor(state, name, transpose)

    layers = []
    for i in range(cfg.layers):
        p = f"model.layers.{i}"
        layers.append(
            {
                "attn_norm": {"scale": t(f"{p}.input_layernorm.weight")},
                "wq": {"w": t(f"{p}.self_attn.q_proj.weight", transpose=True)},
                "wk": {"w": t(f"{p}.self_attn.k_proj.weight", transpose=True)},
                "wv": {"w": t(f"{p}.self_attn.v_proj.weight", transpose=True)},
                "wo": {"w": t(f"{p}.self_attn.o_proj.weight", transpose=True)},
                "mlp_norm": {"scale": t(f"{p}.post_attention_layernorm.weight")},
                "w_gate": {"w": t(f"{p}.mlp.gate_proj.weight", transpose=True)},
                "w_up": {"w": t(f"{p}.mlp.up_proj.weight", transpose=True)},
                "w_down": {"w": t(f"{p}.mlp.down_proj.weight", transpose=True)},
            }
        )
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    lm_head = ("lm_head.weight" if "lm_head.weight" in state
               else "model.embed_tokens.weight")  # tied embeddings
    return {
        "embed": {"table": t("model.embed_tokens.weight")},
        "norm_out": {"scale": t("model.norm.weight")},
        "lm_head": {"w": t(lm_head, transpose=True)},
        "layers": stacked,
    }


# -- incremental decoding (batched summarization path) ---------------------

def select_token(logits, key=None, temperature: float = 0.0, top_k: int = 0):
    """Greedy (temperature<=0) or temperature/top-k categorical sampling.

    ``logits``: [B, V] float32; ``key`` required when sampling."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / temperature
    if top_k > 0:
        # lax.top_k, not a full vocab sort: this runs once per decoded token
        k = min(int(top_k), scaled.shape[-1])  # permissive top_k degrades
        kth = jax.lax.top_k(scaled, k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def init_kv_cache(cfg: DecoderConfig, batch: int, max_len: int) -> dict:
    """Cache layout for ragged batched generation:

    - ``length``: scalar write cursor (same slot for every row).
    - ``lengths``: per-row true context length (RoPE positions; right-padding
      slots between ``lengths[i]`` and ``prompt_len`` are masked out of
      attention forever).
    - ``prompt_len``: width of the prefilled prompt block (0 = pure stepwise).
    """
    dh = cfg.dim // cfg.heads
    shape = (cfg.layers, batch, max_len, cfg.kv_heads, dh)
    return {
        "k": jnp.zeros(shape, jnp.bfloat16),
        "v": jnp.zeros(shape, jnp.bfloat16),
        "length": jnp.zeros((), jnp.int32),
        "lengths": jnp.zeros((batch,), jnp.int32),
        "prompt_len": jnp.zeros((), jnp.int32),
    }


def prefill(params: dict, cfg: DecoderConfig, input_ids, cache: dict,
            lengths=None, return_logits: bool = False) -> tuple[jnp.ndarray, dict]:
    """Fill a FRESH KV cache with right-padded prompts in one forward pass.

    input_ids: [B, T]; ``lengths``: [B] true prompt lengths (default: T for
    every row). Attention masks out each row's padding slots, and the greedy
    next token is read from position ``lengths[i] - 1`` — padded prompts
    condition only on real tokens. The cache write cursor lands at T;
    continuing from a non-empty cache is not supported (cursor must be 0).
    """
    b, t = input_ids.shape
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    causal = jnp.tril(jnp.ones((t, t), bool))[None, None]
    key_valid = (jnp.arange(t)[None, :] < lengths[:, None])[:, None, None, :]  # [B,1,1,T]
    mask = jnp.logical_and(causal, key_valid)
    token_mask = jnp.arange(t)[None, :] < lengths[:, None]  # [B, T] real tokens
    x = cm.embedding(params["embed"], input_ids)

    def layer(carry, lp):
        x, li = carry
        y = cm.rms_norm(lp["attn_norm"], x, cfg.norm_eps)
        q = cm.dense(lp["wq"], y).reshape(b, t, cfg.heads, dh)
        k = cm.dense(lp["wk"], y).reshape(b, t, cfg.kv_heads, dh)
        v = cm.dense(lp["wv"], y).reshape(b, t, cfg.kv_heads, dh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        k_cache = jax.lax.dynamic_update_slice(
            cache["k"][li], k.astype(jnp.bfloat16), (0, 0, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            cache["v"][li], v.astype(jnp.bfloat16), (0, 0, 0, 0)
        )
        kk = jnp.repeat(k, group, axis=2)
        vv = jnp.repeat(v, group, axis=2)
        attn = cm.attention(q, kk, vv, mask).reshape(b, t, cfg.heads * dh)
        x = x + cm.dense(lp["wo"], attn)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        x = x + _mlp(lp, y, cfg, token_mask=token_mask)
        return (x, li + 1), (k_cache, v_cache)

    (x, _), (ks, vs) = jax.lax.scan(layer, (x, 0), params["layers"])
    x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
    logits = cm.dense(params["lm_head"], x).astype(jnp.float32)  # [B, T, V]
    # read each row's logits at its true last token, not at padding
    last = jnp.clip(lengths - 1, 0, t - 1)
    last_logits = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0, :]
    new_cache = {
        "k": ks, "v": vs,
        "length": jnp.asarray(t, jnp.int32),
        "lengths": lengths,
        "prompt_len": jnp.asarray(t, jnp.int32),
    }
    if return_logits:
        return last_logits, new_cache
    return jnp.argmax(last_logits, axis=-1).astype(jnp.int32), new_cache


def decode_step(params: dict, cfg: DecoderConfig, token_ids, cache: dict,
                return_logits: bool = False) -> tuple[jnp.ndarray, dict]:
    """One token per sequence: [B, 1] ids + cache -> ([B] next ids, cache).

    Jittable with a static cache size; the python generation loop lives in
    the summarization processor.
    """
    b = token_ids.shape[0]
    dh = cfg.dim // cfg.heads
    group = cfg.heads // cfg.kv_heads
    pos = cache["length"]  # scalar write cursor (shared slot)
    lengths = cache["lengths"]  # [B] true per-row context lengths (RoPE)
    prompt_len = cache["prompt_len"]
    max_len = cache["k"].shape[2]
    positions = lengths[:, None]
    x = cm.embedding(params["embed"], token_ids)

    # valid keys per row: real prompt tokens + the generated block (padding
    # slots between lengths[i] and prompt_len stay masked forever)
    ks_idx = jnp.arange(max_len)[None, :]
    valid = jnp.logical_or(
        ks_idx < lengths[:, None],
        jnp.logical_and(ks_idx >= prompt_len, ks_idx <= pos),
    )[:, None, None, :]

    def layer(carry, inputs):
        x, li = carry[0], carry[1]
        lp = inputs
        y = cm.rms_norm(lp["attn_norm"], x, cfg.norm_eps)
        q = cm.dense(lp["wq"], y).reshape(b, 1, cfg.heads, dh)
        k = cm.dense(lp["wk"], y).reshape(b, 1, cfg.kv_heads, dh)
        v = cm.dense(lp["wv"], y).reshape(b, 1, cfg.kv_heads, dh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        k_cache = jax.lax.dynamic_update_slice(
            cache["k"][li], k.astype(jnp.bfloat16), (0, pos, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            cache["v"][li], v.astype(jnp.bfloat16), (0, pos, 0, 0)
        )
        kk = jnp.repeat(k_cache, group, axis=2)
        vv = jnp.repeat(v_cache, group, axis=2)
        attn = cm.attention(q, kk, vv, valid).reshape(b, 1, cfg.heads * dh)
        x = x + cm.dense(lp["wo"], attn)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        x = x + _mlp(lp, y, cfg)
        return (x, li + 1), (k_cache, v_cache)

    (x, _), (ks, vs) = jax.lax.scan(layer, (x, 0), params["layers"])
    x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
    logits = cm.dense(params["lm_head"], x).astype(jnp.float32)
    new_cache = {
        "k": ks, "v": vs,
        "length": pos + 1,
        "lengths": lengths + 1,
        "prompt_len": prompt_len,
    }
    if return_logits:
        return logits[:, -1, :], new_cache
    return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), new_cache


def generate(params: dict, cfg: DecoderConfig, input_ids, lengths,
             max_new_tokens: int, eos_id: int = 2,
             n_real=None, temperature: float = 0.0, top_k: int = 0,
             rng_key=None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Whole-sequence generation under one jit: prefill + a
    ``lax.while_loop`` decode with EOS early-exit. One device dispatch per
    batch instead of one per token — the difference between usable and
    unusable latency over a remote-TPU link.

    ``temperature<=0`` is greedy; otherwise temperature/top-k categorical
    sampling driven by ``rng_key`` (one split per step, deterministic for a
    fixed key). Returns (tokens [B, max_new_tokens] int32 zero-padded after
    EOS, counts [B] of real tokens per row).
    """
    b, t = input_ids.shape
    sampling = temperature > 0.0
    key = rng_key if rng_key is not None else jax.random.PRNGKey(0)
    cache = init_kv_cache(cfg, b, t + max_new_tokens)
    first, cache = prefill(params, cfg, input_ids, cache, lengths=lengths,
                           return_logits=True)
    key, sub = jax.random.split(key)
    nxt = select_token(first, sub, temperature if sampling else 0.0, top_k)
    out0 = jnp.zeros((b, max_new_tokens), jnp.int32)
    # batch-padding rows start done, so they don't gate the EOS early-exit
    done0 = (jnp.arange(b) >= n_real) if n_real is not None else jnp.zeros((b,), bool)
    counts0 = jnp.zeros((b,), jnp.int32)

    def cond(state):
        step, _nxt, _key, done, _counts, _cache, _out = state
        return jnp.logical_and(step < max_new_tokens, ~jnp.all(done))

    def body(state):
        step, nxt, key, done, counts, cache, out = state
        # decode at the TOP for steps >= 1 (step 0 uses the prefill token), so
        # the loop never pays a trailing forward pass after the final emission
        key, sub = jax.random.split(key)

        def decode(args):
            nxt, cache = args
            logits, cache = decode_step(params, cfg, nxt[:, None], cache,
                                        return_logits=True)
            return select_token(logits, sub, temperature if sampling else 0.0,
                                top_k), cache

        nxt, cache = jax.lax.cond(step > 0, decode, lambda args: args, (nxt, cache))
        is_eos = nxt == eos_id
        keep = jnp.logical_and(~done, ~is_eos)
        emit = jnp.where(keep, nxt, 0)
        out = jax.lax.dynamic_update_slice(out, emit[:, None], (0, step))
        counts = counts + keep.astype(jnp.int32)
        done = jnp.logical_or(done, is_eos)
        return step + 1, nxt, key, done, counts, cache, out

    _, _, _, _, counts, _, out = jax.lax.while_loop(
        cond, body, (0, nxt, key, done0, counts0, cache, out0)
    )
    return out, counts


def pp_stage_fns(cfg: DecoderConfig):
    """Stage bodies for pipelined-parallel serving (parallel/pipeline.py
    ``make_pp_infer_step``): embed -> dense decoder block -> norm/lm_head.
    Mirrors ``forward``'s scan body (no mesh axes: pp streams whole
    activations stage-to-stage, never sharding them), so pp outputs match
    the single-device ``apply`` bitwise per row. MoE routes through ep and
    long context through sp/ring — not composed with pp, same as training."""
    from arkflow_tpu.errors import ConfigError

    if cfg.num_experts > 1:
        raise ConfigError("pipeline parallelism + MoE (ep) is not composed yet")
    if cfg.use_ring_attention:
        raise ConfigError("pipeline parallelism + ring attention is not composed yet")

    def pre(params: dict, inputs: dict):
        return cm.embedding(params["embed"], inputs["input_ids"]), {}

    def layer(lp: dict, x, aux: dict):
        b, s = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        causal = jnp.tril(jnp.ones((s, s), bool))[None, None, :, :]
        x = _attention_block(lp, x, cfg, positions, causal)
        y = cm.rms_norm(lp["mlp_norm"], x, cfg.norm_eps)
        gate = jax.nn.silu(cm.dense(lp["w_gate"], y).astype(jnp.float32)).astype(y.dtype)
        return x + cm.dense(lp["w_down"], gate * cm.dense(lp["w_up"], y))

    def post(params: dict, x, aux: dict):
        x = cm.rms_norm(params["norm_out"], x, cfg.norm_eps)
        logits = cm.dense(params["lm_head"], x).astype(jnp.float32)
        return {"logits": logits,
                "next_token": jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)}

    return pre, layer, post


def input_spec(cfg: DecoderConfig) -> dict:
    return {"input_ids": ("int32", ("seq",))}


register_model(
    ModelFamily(
        name="decoder_lm",
        make_config=DecoderConfig,
        init=init,
        apply=apply,
        input_spec=input_spec,
        param_specs=param_specs,
        extras={
            "forward": forward,
            "loss_fn": loss_fn,
            "make_train_step": make_train_step,
            "llama3_8b": llama3_8b,
            "from_hf_state_dict": from_hf_state_dict,
            "init_kv_cache": init_kv_cache,
            "prefill": prefill,
            "decode_step": decode_step,
            "generate": generate,
            "pp_stage_fns": pp_stage_fns,
        },
    )
)
