"""LSTM autoencoder anomaly scorer (MQTT telemetry -> anomaly score).

BASELINE.json config 3. Encoder LSTM compresses a [B, T, F] sensor window to a
latent; decoder LSTM reconstructs; anomaly score = per-window reconstruction
MSE. Recurrence is ``lax.scan`` (compiler-friendly, no Python loops); the
gates' matmuls are fused into single [F+H, 4H] projections for the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from arkflow_tpu.models import common as cm
from arkflow_tpu.models.registry import ModelFamily, register_model


@dataclass(frozen=True)
class LstmAeConfig:
    features: int = 8
    hidden: int = 64
    latent: int = 16
    window: int = 32  # time steps per example


def _lstm_init(key, in_dim: int, hidden: int) -> dict:
    return cm.dense_init(key, in_dim + hidden, 4 * hidden)


def _lstm_scan(p: dict, xs: jnp.ndarray, hidden: int):
    """xs: [T, B, F] -> (final (h, c), outputs [T, B, H]). Gates in one matmul."""
    b = xs.shape[1]
    h0 = jnp.zeros((b, hidden), jnp.float32)
    c0 = jnp.zeros((b, hidden), jnp.float32)

    def step(carry, x):
        h, c = carry
        z = cm.dense(p, jnp.concatenate([x, h], axis=-1), dtype=jnp.float32)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    (h, c), ys = jax.lax.scan(step, (h0, c0), xs)
    return (h, c), ys


def init(rng, cfg: LstmAeConfig) -> dict:
    k1, k2, k3, k4, k5 = jax.random.split(rng, 5)
    return {
        "encoder": _lstm_init(k1, cfg.features, cfg.hidden),
        "to_latent": cm.dense_init(k2, cfg.hidden, cfg.latent),
        "from_latent": cm.dense_init(k3, cfg.latent, cfg.hidden),
        "decoder": _lstm_init(k4, cfg.hidden, cfg.hidden),
        "head": cm.dense_init(k5, cfg.hidden, cfg.features),
    }


def apply(params: dict, cfg: LstmAeConfig, *, values) -> dict:
    """values: [B, T, F] float32 sensor windows -> anomaly score per window."""
    x = jnp.transpose(values.astype(jnp.float32), (1, 0, 2))  # [T, B, F]
    (h, _), _ = _lstm_scan(params["encoder"], x, cfg.hidden)
    latent = jnp.tanh(cm.dense(params["to_latent"], h, dtype=jnp.float32))
    seed = cm.dense(params["from_latent"], latent, dtype=jnp.float32)
    # decoder receives the latent seed at every step (standard AE unrolling)
    dec_in = jnp.broadcast_to(seed[None], (cfg.window, *seed.shape))
    _, ys = _lstm_scan(params["decoder"], dec_in, cfg.hidden)
    recon = cm.dense(params["head"], ys, dtype=jnp.float32)  # [T, B, F]
    recon = jnp.transpose(recon, (1, 0, 2))
    err = jnp.mean(jnp.square(recon - values.astype(jnp.float32)), axis=(1, 2))
    return {"score": err, "reconstruction": recon}


def loss_fn(params: dict, cfg: LstmAeConfig, values) -> jnp.ndarray:
    """Mean reconstruction MSE — anomaly detectors train on normal traffic."""
    return apply(params, cfg, values=values)["score"].mean()


def make_train_step(cfg: LstmAeConfig, optimizer):
    """``train_step(params, opt_state, batch{"values"}) -> (params, opt_state, loss)``."""

    def train_step(params, opt_state, batch):
        import optax

        loss, grads = jax.value_and_grad(loss_fn)(params, cfg, batch["values"])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def input_spec(cfg: LstmAeConfig) -> dict:
    return {"values": ("float32", (cfg.window, cfg.features))}


register_model(
    ModelFamily(
        name="lstm_ae",
        make_config=LstmAeConfig,
        init=init,
        apply=apply,
        input_spec=input_spec,
        extras={"loss_fn": loss_fn, "make_train_step": make_train_step},
    )
)
