"""Shared pure-JAX building blocks: params-as-pytrees, jittable applies.

Design rules (TPU-first):
- Params live in nested dicts; applies are pure functions -> trivially
  jittable, shardable with ``NamedSharding`` pytrees, no framework state.
- Compute dtype is bfloat16 (MXU-native); normalisation statistics and softmax
  run in float32 for stability; params are kept in float32 master copies and
  cast at use (standard mixed-precision recipe).
- No Python control flow on data; recurrences use ``lax.scan``.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

Params = Any  # nested dict pytree


def dense_init(key, in_dim: int, out_dim: int, *, bias: bool = True) -> Params:
    w_key, _ = jax.random.split(key)
    scale = 1.0 / math.sqrt(in_dim)
    p = {"w": jax.random.uniform(w_key, (in_dim, out_dim), jnp.float32, -scale, scale)}
    if bias:
        p["b"] = jnp.zeros((out_dim,), jnp.float32)
    return p


def dense(p: Params, x: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    if "w_q" in p:  # W8A8 serving form (models/quantize.py): int8 on the MXU
        from arkflow_tpu.models.quantize import dense_w8a8

        return dense_w8a8(p, x, dtype)
    y = x.astype(dtype) @ p["w"].astype(dtype)
    if "b" in p:
        y = y + p["b"].astype(dtype)
    return y


def layer_norm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32), "bias": jnp.zeros((dim,), jnp.float32)}


def layer_norm(p: Params, x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def rms_norm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32)}


def rms_norm(p: Params, x: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    return (y * p["scale"]).astype(x.dtype)


def embedding_init(key, vocab: int, dim: int, scale: float = 0.02) -> Params:
    return {"table": jax.random.normal(key, (vocab, dim), jnp.float32) * scale}


def embedding(p: Params, ids: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    return p["table"].astype(dtype)[ids]


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.gelu(x, approximate=True)


def attention(q, k, v, mask=None, *, softmax_dtype=jnp.float32):
    """Batched multi-head attention core: [B, S, H, Dh] tensors.

    Softmax in float32; matmuls in the input dtype (bfloat16) for the MXU.
    ``mask``: broadcastable to [B, H, Sq, Sk], True = attend.
    """
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(softmax_dtype) / math.sqrt(dh)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(softmax_dtype).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def hf_tensor(state: dict, name: str, transpose: bool = False) -> jnp.ndarray:
    """One HF state_dict entry (torch tensor — any dtype incl. bfloat16 — or
    numpy array) -> float32 jnp array, optionally transposed ([out,in] ->
    [in,out] for torch linear weights)."""
    import numpy as np

    v = state[name]
    if hasattr(v, "detach"):  # torch tensor; .float() first (numpy lacks bf16)
        v = v.detach().cpu().float().numpy()
    arr = np.asarray(v, dtype=np.float32)
    return jnp.asarray(arr.T if transpose else arr)
