"""Engine configuration: single file, format by extension.

YAML / JSON / TOML parse into typed config objects (ref:
crates/arkflow-core/src/config.rs:87-107). Component configs stay as raw
``{"type": ..., **payload}`` mappings — the builder registry consumes them
(the serde-flatten equivalent, ref input/mod.rs:98-106).

Defaults mirror the reference: health server on ``0.0.0.0:8080``
(config.rs:26-172), pipeline ``thread_num`` = cpu count (pipeline/mod.rs:106).
"""

from __future__ import annotations

import json
import os

try:
    import tomllib
except ImportError:  # python < 3.11: the vendored fallback has the same API
    import tomli as tomllib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping, Optional

import yaml

from arkflow_tpu.errors import ConfigError


@dataclass
class PipelineConfig:
    thread_num: int = 0  # 0 -> cpu count
    processors: list[dict] = field(default_factory=list)
    #: >0 runs the chain in that many worker PROCESSES (GIL escape for
    #: Python-bound transforms; see runtime/procpool.py). 0 = in-process.
    process_pool: int = 0
    #: >0 shards the ENTIRE ingest hot path (decode -> coalesce -> admission
    #: -> dispatch) across that many OS processes behind one endpoint: the
    #: stage queue between input and workers becomes an Arrow-IPC flight hop
    #: partitioned by batch_fingerprint/tenant hash (runtime/hostshard.py).
    #: 0 = the single-process stream. Mutually exclusive with process_pool,
    #: which shards only the processor chain, not the queue/coalescer.
    ingest_shards: int = 0
    #: how many times a batch may be delivered (processed + written) before
    #: it is quarantined to error_output instead of redelivered. 1 keeps the
    #: quarantine-on-first-failure behavior; >1 lets transient processing
    #: failures heal through broker/nack redelivery.
    max_delivery_attempts: int = 1
    #: stage-queue depth between input/buffer and the workers; 0 keeps the
    #: historical ``thread_num * 4`` (ref stream/mod.rs:90-93)
    queue_size: int = 0
    #: per-batch latency budget in millis, measured from ingest time unless
    #: an absolute ``__meta_ext_deadline_ms`` column overrides it; setting
    #: it turns on deadline-aware admission (see runtime/overload.py)
    deadline_ms: Optional[float] = None
    #: default admission-priority band for batches without a
    #: ``__meta_ext_priority`` column
    priority: int = 0
    #: parsed ``pipeline.overload`` controller knobs (OverloadConfig), or
    #: None when overload control is fully disabled
    overload: Optional[object] = None

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "PipelineConfig":
        from arkflow_tpu.runtime.overload import OverloadConfig

        if not isinstance(m, Mapping):
            raise ConfigError("pipeline config must be a mapping")
        threads = m.get("thread_num", 0)
        if not isinstance(threads, int) or threads < 0:
            raise ConfigError(f"pipeline.thread_num must be a non-negative int, got {threads!r}")
        pool = m.get("process_pool", 0)
        if not isinstance(pool, int) or pool < 0:
            raise ConfigError(
                f"pipeline.process_pool must be a non-negative int, got {pool!r}")
        shards = m.get("ingest_shards", 0)
        if isinstance(shards, bool) or not isinstance(shards, int) or shards < 0:
            raise ConfigError(
                f"pipeline.ingest_shards must be a non-negative int, got {shards!r}")
        if shards > 0 and pool > 0:
            raise ConfigError(
                "pipeline.ingest_shards and pipeline.process_pool are mutually "
                "exclusive: ingest sharding already runs the whole hot path "
                "(coalesce + admission + chain) in shard processes")
        procs = m.get("processors", [])
        if not isinstance(procs, list):
            raise ConfigError("pipeline.processors must be a list")
        attempts = m.get("max_delivery_attempts", 1)
        if not isinstance(attempts, int) or attempts < 1:
            raise ConfigError(
                f"pipeline.max_delivery_attempts must be an int >= 1, got {attempts!r}")
        qsize = m.get("queue_size", 0)
        if not isinstance(qsize, int) or isinstance(qsize, bool) or qsize < 0:
            raise ConfigError(
                f"pipeline.queue_size must be a non-negative int, got {qsize!r}")
        deadline = m.get("deadline_ms")
        if deadline is not None:
            if isinstance(deadline, bool) or not isinstance(deadline, (int, float)) \
                    or deadline <= 0:
                raise ConfigError(
                    f"pipeline.deadline_ms must be a positive number, got {deadline!r}")
            deadline = float(deadline)
        priority = m.get("priority", 0)
        if not isinstance(priority, int) or isinstance(priority, bool):
            raise ConfigError(f"pipeline.priority must be an int, got {priority!r}")
        overload = OverloadConfig.from_config(
            m.get("overload"), deadline_ms=deadline, priority=priority)
        return cls(thread_num=threads, processors=[dict(p) for p in procs],
                   process_pool=pool, ingest_shards=shards,
                   max_delivery_attempts=attempts,
                   queue_size=qsize, deadline_ms=deadline, priority=priority,
                   overload=overload)

    def effective_threads(self) -> int:
        return self.thread_num if self.thread_num > 0 else (os.cpu_count() or 1)

    def effective_queue_size(self) -> int:
        """Stage-queue depth: configured ``queue_size`` or the historical
        ``thread_num * 4`` default."""
        return self.queue_size if self.queue_size > 0 else self.effective_threads() * 4


@dataclass
class TemporaryConfig:
    name: str
    config: dict

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "TemporaryConfig":
        m = dict(m)
        name = m.pop("name", None)
        if not name:
            raise ConfigError("temporary config requires a 'name'")
        return cls(name=name, config=m)


@dataclass
class StreamConfig:
    input: dict
    pipeline: PipelineConfig
    output: dict
    error_output: Optional[dict] = None
    buffer: Optional[dict] = None
    temporary: list[TemporaryConfig] = field(default_factory=list)
    name: Optional[str] = None
    #: crash policy: {max_retries: N, backoff: "5s", reset_after: "5m"}
    #: rebuilds and restarts a crashed stream (the reference only logs,
    #: ref engine/mod.rs:268-273); a run longer than reset_after restores
    #: the full retry budget; None keeps log-and-stop behavior
    restart: Optional[dict] = None
    #: delivery-path retry for output.write (from ``output.retry``; the key
    #: also stays visible to connector builders that use it for connect-time
    #: retries, e.g. pulsar). None -> RetryConfig defaults.
    output_retry: Optional[object] = None
    #: circuit breaker over output.write (from ``output.circuit_breaker``);
    #: None -> disabled
    output_circuit_breaker: Optional[object] = None
    error_output_retry: Optional[object] = None
    error_output_circuit_breaker: Optional[object] = None
    #: capped-exponential reconnect schedule after input Disconnection (from
    #: ``input.reconnect``); None -> stream defaults (100ms doubling to 5s)
    input_reconnect: Optional[object] = None

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "StreamConfig":
        from arkflow_tpu.utils.circuit_breaker import CircuitBreakerConfig
        from arkflow_tpu.utils.retry import RetryConfig

        if not isinstance(m, Mapping):
            raise ConfigError("stream config must be a mapping")
        for req in ("input", "output"):
            if req not in m:
                raise ConfigError(f"stream config missing required section {req!r}")
        pipeline = PipelineConfig.from_mapping(m.get("pipeline", {}))
        _validate_token_coalesce(m.get("buffer"), pipeline.processors)
        _validate_response_cache(pipeline.processors)
        _validate_generate_mesh(pipeline.processors)
        _validate_inference_mesh(pipeline.processors)
        _validate_dispatch_knobs(pipeline.processors)
        _validate_swap(pipeline.processors)
        _validate_tuner(pipeline.processors)
        _validate_integrity(pipeline.processors)
        _validate_remote_tpu(pipeline.processors)
        temps = [TemporaryConfig.from_mapping(t) for t in m.get("temporary", [])]
        input_cfg = dict(m["input"])
        reconnect = input_cfg.pop("reconnect", None)
        output_cfg = dict(m["output"])
        out_breaker = CircuitBreakerConfig.from_config(output_cfg.pop("circuit_breaker", None))
        out_retry = RetryConfig.from_config(output_cfg["retry"]) if output_cfg.get("retry") else None
        err_cfg = dict(m["error_output"]) if m.get("error_output") else None
        err_breaker = err_retry = None
        if err_cfg is not None:
            err_breaker = CircuitBreakerConfig.from_config(err_cfg.pop("circuit_breaker", None))
            err_retry = RetryConfig.from_config(err_cfg["retry"]) if err_cfg.get("retry") else None
        return cls(
            input=input_cfg,
            pipeline=pipeline,
            output=output_cfg,
            error_output=err_cfg,
            buffer=dict(m["buffer"]) if m.get("buffer") else None,
            temporary=temps,
            name=m.get("name"),
            restart=_restart_config(m.get("restart")),
            output_retry=out_retry,
            output_circuit_breaker=out_breaker,
            error_output_retry=err_retry,
            error_output_circuit_breaker=err_breaker,
            input_reconnect=RetryConfig.from_config(reconnect) if reconnect else None,
        )


def _validate_token_coalesce(buffer_cfg: Any, processors: list[dict]) -> None:
    """Cross-component sanity for the packed fast path: a buffer carving
    token-budget emissions only makes sense feeding a packing-enabled
    ``tpu_inference`` processor (token-sized emissions fill a compiled
    (rows, seq) shape only AFTER pack_tokens; an unpacked runner would pad
    their oversized row counts straight back). Caught at parse time with a
    clear message — the component builders can't see across sections."""
    packing_vals = []
    for p in processors:
        # chaos streams wrap the real processor: look through `fault.inner`
        # so the cross-check still sees the tpu_inference config
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping) or p.get("type") != "tpu_inference":
            continue
        packing = p.get("packing", False)
        if not isinstance(packing, bool):
            raise ConfigError(
                f"tpu_inference.packing must be a bool, got {packing!r}")
        packing_vals.append(packing)
    if not isinstance(buffer_cfg, Mapping):
        return
    coalesce = buffer_cfg.get("coalesce")
    if not isinstance(coalesce, Mapping):
        return
    token_budget = coalesce.get("token_budget")
    if token_budget is None:
        return
    if isinstance(token_budget, bool) or not isinstance(token_budget, int) \
            or token_budget < 1:
        raise ConfigError(
            f"buffer.coalesce.token_budget must be a positive int, "
            f"got {token_budget!r}")
    if packing_vals and not any(packing_vals):
        raise ConfigError(
            "buffer.coalesce.token_budget requires 'packing: true' on the "
            "stream's tpu_inference processor (token-budget emissions only "
            "fill the compiled (rows, seq) shape after pack_tokens packing; "
            "set packing: true or drop token_budget)")


def _validate_response_cache(processors: list[dict]) -> None:
    """Parse-time validation of ``tpu_inference.response_cache`` knobs, so a
    bad cache config fails at ``--validate`` instead of at stream build —
    looking through ``fault.inner`` chaos wrappers like the coalesce check.
    The actual construction happens in the processor builder
    (runtime/respcache.py ``build_response_cache``); this shares its parse
    rules without instantiating a cache (or its metric series) per pass."""
    from arkflow_tpu.runtime.respcache import parse_response_cache_config

    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping) or p.get("type") != "tpu_inference":
            continue
        if p.get("response_cache") is not None:
            parse_response_cache_config(p["response_cache"])


def _validate_swap(processors: list[dict]) -> None:
    """Parse-time validation of the ``swap:`` hot-swap block on
    ``tpu_inference``/``tpu_generate`` (tpu/swap.py owns the parse rules; it
    imports no jax), looking through ``fault.inner`` chaos wrappers like the
    other cross-checks — a bad canary/drain knob fails at ``--validate``
    instead of at the first POST /admin/swap."""
    from arkflow_tpu.tpu.swap import parse_swap_config

    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping):
            continue
        ptype = p.get("type")
        if ptype in ("tpu_inference", "tpu_generate") and p.get("swap") is not None:
            parse_swap_config(p["swap"], who=str(ptype))


def _validate_integrity(processors: list[dict]) -> None:
    """Parse-time validation of the ``integrity:`` silent-data-corruption
    block on ``tpu_inference``/``tpu_generate`` (tpu/integrity.py owns the
    parse rules; it imports no jax), looking through ``fault.inner`` chaos
    wrappers like the other cross-checks — a bad probe cadence fails at
    ``--validate`` instead of at stream build."""
    from arkflow_tpu.tpu.integrity import parse_integrity_config

    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping):
            continue
        kind = p.get("type")
        if kind in ("tpu_inference", "tpu_generate") \
                and p.get("integrity") is not None:
            parse_integrity_config(p["integrity"], who=kind)
            if kind == "tpu_generate" \
                    and p.get("serving", "batch") != "continuous":
                raise ConfigError(
                    "tpu_generate: integrity requires serving: continuous "
                    "(batch mode holds no resident serving member to probe)")


def _validate_tuner(processors: list[dict]) -> None:
    """Parse-time validation of the ``tuner:`` traffic-adaptive-shapes block
    on ``tpu_inference`` (tpu/tuner.py owns the parse rules; it imports no
    jax), looking through ``fault.inner`` chaos wrappers like the other
    cross-checks — a bad interval/margin knob fails at ``--validate``
    instead of at stream build."""
    from arkflow_tpu.tpu.tuner import parse_tuner_config

    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping) or p.get("type") != "tpu_inference":
            continue
        if p.get("tuner") is None:
            continue
        cfg = parse_tuner_config(p["tuner"], who="tpu_inference")
        mesh = p.get("mesh")
        pp = mesh.get("pp", 1) if isinstance(mesh, Mapping) else 1
        if cfg is not None and cfg.enabled and isinstance(pp, int) and pp > 1:
            raise ConfigError(
                "tpu_inference: 'tuner' does not compose with mesh pp "
                "(pipelined stages serve one schedule at a time; a warm "
                "compile would interleave collectives with the live GPipe "
                "ring)")


def _validate_remote_tpu(processors: list[dict]) -> None:
    """Parse-time validation of the ``remote_tpu`` cluster-dispatch stage
    (runtime/cluster.py owns the parse rules; it imports no jax), looking
    through ``fault.inner`` chaos wrappers like the other cross-checks — a
    bad worker URL, routing knob, ``decode_candidates``, or one-sided
    ``fleet.roles`` split (prefill capacity with no decode capacity, or
    vice versa) fails at ``--validate`` instead of at stream connect."""
    from arkflow_tpu.runtime.cluster import parse_remote_tpu_config

    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if isinstance(p, Mapping) and p.get("type") == "remote_tpu":
            parse_remote_tpu_config(p)


#: decoder_lm's DecoderConfig default — mirrored here (not imported) so mesh
#: validation at parse time never drags jax into `--validate`
_DECODER_LM_DEFAULT_KV_HEADS = 4

#: model-family layer-count defaults, mirrored (not imported — jax) so the
#: pp stage-count check runs at parse time; an unknown model defers the
#: check to stream build, where the runner counts the real layer stack
_MODEL_DEFAULT_LAYERS = {"bert_classifier": 12, "decoder_lm": 4}


def _validate_inference_mesh(processors: list[dict]) -> None:
    """Parse-time checks for multi-chip ``tpu_inference`` serving, looking
    through ``fault.inner`` chaos wrappers like the other cross-checks:

    - mesh axis values must be positive ints;
    - ``pp`` (pipelined model segmentation) composes with ``dp`` only —
      tp/sp alongside pp, ``device_pool`` on the same processor, and
      ``packing`` all fail here with a clear message instead of a build
      error after jax loads;
    - ``pp`` must not exceed the model's layer count (each stage needs at
      least one layer), checked against ``model_config.layers`` or the
      family default when the config leaves it unset;
    - the pp knobs (``pp_microbatch_rows`` / ``pp_layer_costs`` /
      ``pp_profile``) are type-checked so ``--validate`` catches them.
    """
    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping) or p.get("type") != "tpu_inference":
            continue
        mesh = p.get("mesh")
        if mesh is None:
            continue
        if not isinstance(mesh, Mapping):
            raise ConfigError(
                f"tpu_inference.mesh must be a mapping, got {mesh!r}")
        axes: dict[str, int] = {}
        for k in ("dp", "tp", "sp", "pp"):
            v = mesh.get(k, 1)
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ConfigError(
                    f"tpu_inference.mesh.{k} must be a positive int, got {v!r}")
            axes[k] = v
        if axes["pp"] <= 1:
            continue
        for axis in ("tp", "sp"):
            if axes[axis] > 1:
                raise ConfigError(
                    f"tpu_inference: mesh pp composes with dp only — mesh "
                    f"{axis} > 1 alongside pp is unsupported (stages stream "
                    "whole activations; shard tensors on a separate tp "
                    "processor instead)")
        if p.get("device_pool"):
            raise ConfigError(
                "tpu_inference: 'device_pool' and mesh pp are mutually "
                "exclusive (a pool member is a single-device runner; pick "
                "pipelined stages OR replicated serving)")
        if p.get("packing", False) is True:
            raise ConfigError(
                "tpu_inference: packing + mesh pp is not supported — the pp "
                "schedule streams fixed-shape microbatches, packed layouts "
                "are data-dependent (serve pp unpacked, or keep packing on "
                "dp/pool)")
        mc = p.get("model_config")
        layers = (mc.get("layers") if isinstance(mc, Mapping) else None)
        if layers is None:
            layers = _MODEL_DEFAULT_LAYERS.get(str(p.get("model", "")))
        if (isinstance(layers, int) and not isinstance(layers, bool)
                and axes["pp"] > layers):
            raise ConfigError(
                f"tpu_inference: mesh pp={axes['pp']} exceeds the model's "
                f"{layers} layers (every pipeline stage needs at least one "
                "layer)")
        mb = p.get("pp_microbatch_rows")
        if mb is not None and (isinstance(mb, bool) or not isinstance(mb, int)
                               or mb < 1):
            raise ConfigError(
                f"tpu_inference.pp_microbatch_rows must be a positive int, "
                f"got {mb!r}")
        costs = p.get("pp_layer_costs")
        if costs is not None and (
                not isinstance(costs, list) or not costs
                or not all(isinstance(c, (int, float)) and not isinstance(c, bool)
                           and c >= 0 for c in costs)):
            raise ConfigError(
                "tpu_inference.pp_layer_costs must be a non-empty list of "
                f"non-negative numbers, got {costs!r}")
        prof = p.get("pp_profile")
        if prof is not None and not isinstance(prof, str):
            raise ConfigError(
                f"tpu_inference.pp_profile must be a path string, got {prof!r}")


def _validate_generate_mesh(processors: list[dict]) -> None:
    """Parse-time checks for multi-chip ``tpu_generate`` serving, looking
    through ``fault.inner`` chaos wrappers like the other cross-checks:

    - mesh axis values must be positive ints;
    - ``serving: continuous`` shards TENSOR-PARALLEL only — the lockstep
      slot grid does not batch-split, so ``dp``/``sp`` > 1 fail here with a
      clear message instead of a shape error at stream build;
    - ``tp`` must divide the model's KV head count (the page pools shard
      over KV heads on the tp axis).
    """
    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping) or p.get("type") != "tpu_generate":
            continue
        mesh = p.get("mesh")
        if mesh is None:
            continue
        if not isinstance(mesh, Mapping):
            raise ConfigError(
                f"tpu_generate.mesh must be a mapping, got {mesh!r}")
        axes: dict[str, int] = {}
        for k in ("dp", "tp", "sp"):
            v = mesh.get(k, 1)
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ConfigError(
                    f"tpu_generate.mesh.{k} must be a positive int, got {v!r}")
            axes[k] = v
        if str(p.get("serving", "batch")) != "continuous":
            continue
        for axis in ("dp", "sp"):
            if axes[axis] > 1:
                raise ConfigError(
                    f"tpu_generate: serving: continuous + mesh {axis} > 1 is "
                    "unsupported — the lockstep slot grid does not "
                    "batch-split; shard tp (mesh: {tp: N}) or use serving: "
                    "batch / tpu_inference for dp")
        tp = axes["tp"]
        if tp > 1:
            mc = p.get("model_config")
            kv_heads = (mc.get("kv_heads") if isinstance(mc, Mapping) else None)
            if kv_heads is None and p.get("model", "decoder_lm") == "decoder_lm":
                kv_heads = _DECODER_LM_DEFAULT_KV_HEADS
            if (isinstance(kv_heads, int) and not isinstance(kv_heads, bool)
                    and kv_heads % tp != 0):
                raise ConfigError(
                    f"tpu_generate: mesh tp={tp} must divide the model's "
                    f"kv_heads={kv_heads} (KV pages shard over heads on the "
                    "tp axis)")


def _validate_dispatch_knobs(processors: list[dict]) -> None:
    """Parse-time checks for the hot-path perf knobs (PR 13), looking
    through ``fault.inner`` chaos wrappers like the other cross-checks:

    - ``tpu_inference.dispatch_depth`` / ``tpu_generate.dispatch_depth``
      must be positive ints; the generate path caps at 2 (lockstep decode
      can only lag host bookkeeping by one step) and composes with neither
      speculative decoding nor sampling (both at ``--validate``, not as a
      shape/state error at stream build);
    - ``tpu_generate.decode_kernel`` must name a known kernel.
    """
    for p in processors:
        while (isinstance(p, Mapping) and p.get("type") == "fault"
               and isinstance(p.get("inner"), Mapping)):
            p = p["inner"]
        if not isinstance(p, Mapping):
            continue
        ptype = p.get("type")
        if ptype not in ("tpu_inference", "tpu_generate"):
            continue
        depth = p.get("dispatch_depth")
        if depth is not None:
            if isinstance(depth, bool) or not isinstance(depth, int) or depth < 1:
                raise ConfigError(
                    f"{ptype}.dispatch_depth must be a positive int, "
                    f"got {depth!r}")
        if ptype != "tpu_generate":
            continue
        kernel = p.get("decode_kernel")
        if kernel is not None and kernel not in ("auto", "gather", "paged"):
            raise ConfigError(
                f"tpu_generate.decode_kernel must be auto|gather|paged, "
                f"got {kernel!r}")
        if depth is not None and depth > 2:
            raise ConfigError(
                "tpu_generate.dispatch_depth caps at 2: lockstep decode "
                "can only lag host bookkeeping by one in-flight step")
        if depth is not None and depth > 1:
            if int(p.get("speculative_tokens", 0) or 0) > 0:
                raise ConfigError(
                    "tpu_generate: dispatch_depth > 1 and speculative_tokens "
                    "are mutually exclusive (both restructure the decode loop)")
            if float(p.get("temperature", 0.0) or 0.0) != 0.0:
                raise ConfigError(
                    "tpu_generate: dispatch_depth > 1 requires greedy "
                    "decoding (temperature 0) — a lane that finished at step "
                    "N still rides step N+1 and would consume sampling RNG")


def _restart_config(m: Any) -> Optional[dict]:
    if m is None or m is False:
        return None  # `restart: {}` means "defaults", not "disabled"
    if not isinstance(m, Mapping):
        raise ConfigError("stream 'restart' must be a mapping")
    from arkflow_tpu.utils.duration import parse_duration

    try:
        out = {
            "max_retries": int(m.get("max_retries", 3)),
            "backoff_s": parse_duration(str(m.get("backoff", "5s"))),
            # a run at least this long resets the retry budget (supervisor
            # convention: occasional crashes over days shouldn't accumulate)
            "reset_after_s": parse_duration(str(m.get("reset_after", "5m"))),
        }
    except (TypeError, ValueError) as e:
        raise ConfigError(f"stream 'restart' values invalid: {e}") from e
    if out["max_retries"] < 0 or out["backoff_s"] < 0 or out["reset_after_s"] < 0:
        raise ConfigError("stream restart values must be non-negative")
    return out


@dataclass
class HealthCheckConfig:
    enabled: bool = True
    host: str = "0.0.0.0"
    port: int = 8080
    path: str = "/health"
    #: opt-in: directory for POST /debug/profile JAX traces (endpoint is
    #: absent when unset — it adds device overhead and writes to disk)
    profiling_dir: Optional[str] = None

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "HealthCheckConfig":
        c = cls()
        c.enabled = bool(m.get("enabled", True))
        c.host = str(m.get("host", c.host))
        c.port = int(m.get("port", c.port))
        c.path = str(m.get("path", c.path))
        c.profiling_dir = m.get("profiling_dir")
        return c


@dataclass
class LoggingConfig:
    level: str = "info"
    file_path: Optional[str] = None
    format: str = "plain"  # plain | json

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "LoggingConfig":
        c = cls()
        c.level = str(m.get("level", c.level)).lower()
        c.file_path = m.get("file_path") or m.get("file")
        c.format = str(m.get("format", c.format)).lower()
        if c.format not in ("plain", "json"):
            raise ConfigError(f"logging.format must be plain|json, got {c.format!r}")
        return c


@dataclass
class EngineConfig:
    streams: list[StreamConfig]
    health_check: HealthCheckConfig = field(default_factory=HealthCheckConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    #: per-batch tracing knobs (obs/trace.py TracingConfig): head-sampling
    #: rate + retention bounds for the /trace endpoint; always-on by
    #: default — the engine applies it to the process-global tracer
    tracing: Optional[object] = None

    @classmethod
    def from_mapping(cls, m: Mapping[str, Any]) -> "EngineConfig":
        from arkflow_tpu.obs.trace import TracingConfig

        if not isinstance(m, Mapping):
            raise ConfigError("engine config must be a mapping")
        raw_streams = m.get("streams")
        if not raw_streams or not isinstance(raw_streams, list):
            raise ConfigError("engine config requires a non-empty 'streams' list")
        streams = [StreamConfig.from_mapping(s) for s in raw_streams]
        health = HealthCheckConfig.from_mapping(m.get("health_check", {}) or {})
        logging_ = LoggingConfig.from_mapping(m.get("logging", {}) or {})
        tracing = TracingConfig.from_mapping(m.get("tracing"))
        return cls(streams=streams, health_check=health, logging=logging_,
                   tracing=tracing)

    def validate_components(self) -> list[str]:
        """Check every component's ``type`` tag resolves against the
        registries (goes beyond the reference's parse-only ``--validate``).
        Returns human-readable problems; empty = OK."""
        from arkflow_tpu.components.registry import ensure_plugins_loaded, registered_types

        ensure_plugins_loaded()
        problems: list[str] = []
        for i, s in enumerate(self.streams):
            for family, c in (
                ("input", s.input),
                ("output", s.output),
                *((("output", s.error_output),) if s.error_output else ()),
                *((("buffer", s.buffer),) if s.buffer else ()),
                *((("processor", p) for p in s.pipeline.processors)),
                *((("temporary", t.config) for t in s.temporary)),
            ):
                t = c.get("type")
                if t not in registered_types(family):
                    problems.append(f"stream[{i}]: unknown {family} type {t!r}")
        return problems

    @classmethod
    def from_file(cls, path: str | Path) -> "EngineConfig":
        p = Path(path)
        if not p.exists():
            raise ConfigError(f"config file not found: {p}")
        suffix = p.suffix.lower()
        text = p.read_text()
        try:
            if suffix in (".yaml", ".yml"):
                data = yaml.safe_load(text)
            elif suffix == ".json":
                data = json.loads(text)
            elif suffix == ".toml":
                data = tomllib.loads(text)
            else:
                raise ConfigError(f"unsupported config extension {suffix!r} (use .yaml/.json/.toml)")
        except (yaml.YAMLError, json.JSONDecodeError, tomllib.TOMLDecodeError) as e:
            raise ConfigError(f"failed to parse {p}: {e}") from e
        return cls.from_mapping(data or {})
