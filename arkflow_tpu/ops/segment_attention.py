"""Segment-masked flash attention for token-packed execution.

Packed rows (tpu/packing.py) hold several examples whose tokens must only
attend within their own segment. The XLA path materializes a [B, 1, S, S]
block-diagonal mask — O(S^2) HBM traffic per row that dwarfs the scores at
long sequence. This kernel keeps the online-softmax flash structure of
``ops/ragged_attention.py`` (chip-proven) and derives the mask on the fly
from two VMEM reads of the per-token ``segment_ids`` ([B, S] int32, 0 =
dead position), so nothing quadratic ever touches HBM.

Packed rows are ~fully dense (that is the point of packing), so there is no
tile-skipping: every K tile computes, masked by segment equality. Dead
positions (segment 0) emit zeros.

Opt-in for serving via ``ARKFLOW_PACKED_FLASH=1`` until it has been A/B'd
on real hardware — the XLA pair-mask path stays the default for packed
execution (models/bert.py::apply_packed).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _segment_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, o_ref, *, tile_k: int):
    from arkflow_tpu.ops.ragged_attention import flash_softmax_loop

    q = q_ref[0, 0].astype(jnp.float32)  # [TQ, D]
    s = k_ref.shape[2]
    seg_q = segq_ref[0]  # [TQ] int32

    def valid_at(t):
        seg_k = segk_ref[0, pl.ds(t * tile_k, tile_k)]  # [TK]
        # block-diagonal mask from the segment ids: same segment AND live
        return jnp.logical_and(
            seg_q[:, None] == seg_k[None, :], seg_q[:, None] > 0)

    o, m, l = flash_softmax_loop(q, k_ref, v_ref, s // tile_k, tile_k, valid_at)
    # dead queries (segment 0) emit zeros; their fully-masked softmax is
    # uniform, so the accumulator alone cannot zero them
    q_live = (seg_q > 0)[:, None]
    o_ref[0, 0] = jnp.where(
        q_live, o / jnp.maximum(l[:, None], 1e-30), 0.0
    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("tile_q", "tile_k", "interpret"))
def segment_flash_attention(q, k, v, segment_ids, *, tile_q: int = 128,
                            tile_k: int = 128, interpret: bool = False):
    """q/k/v: [B, H, S, D]; segment_ids: [B, S] int32 (0 = dead position).

    Tokens attend exactly within their segment (block-diagonal); dead
    positions output zeros. Non-causal (packed classification rows).
    """
    b, h, s, d = q.shape
    tile_q = min(tile_q, s)
    tile_k = min(tile_k, s)
    if s % tile_q or s % tile_k:
        raise ValueError(f"seq len {s} must divide tiles ({tile_q}, {tile_k})")
    from jax.experimental.pallas import tpu as pltpu

    grid = (b, h, s // tile_q)
    kernel = functools.partial(_segment_kernel, tile_k=tile_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, tile_q), lambda bi, hi, qi: (bi, qi)),
            pl.BlockSpec((1, s), lambda bi, hi, qi: (bi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
    )
    seg = jnp.asarray(segment_ids, jnp.int32)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, seg, seg)
