"""Pallas TPU kernels for the ops XLA fusion doesn't already cover."""

from arkflow_tpu.ops.flash_attention import flash_attention  # noqa: F401
from arkflow_tpu.ops.ragged_attention import ragged_flash_attention  # noqa: F401
