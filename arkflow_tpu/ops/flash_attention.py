"""Flash attention as a Pallas TPU kernel.

Streaming-softmax attention tiled for VMEM: the grid walks (batch, head,
q-tile); each program holds one Q tile in VMEM, loops over K/V tiles with an
online max/denominator accumulator in float32, and writes the normalised tile
once — attention memory is O(TILE_Q * S) scores per program instead of
materialising [S, S]. QK^T and PV run on the MXU in the input dtype.

Used for variable-length/ragged batches where XLA's fused attention falls
short (PAPERS.md: ragged paged attention); ``interpret=True`` runs the same
kernel on CPU for tests.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, tile_k: int, causal: bool, tile_q: int):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [TQ, D]
    tq, d = q.shape
    s = k_ref.shape[2]
    scale = 1.0 / math.sqrt(d)
    n_k = s // tile_k

    q_pos = qi * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tile_k), 0)

    def body(t, carry):
        o, m, l = carry
        k = k_ref[0, 0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [TQ, TK]
        if causal:
            k_pos = t * tile_k + jax.lax.broadcasted_iota(jnp.int32, (tq, tile_k), 1)
            scores = jnp.where(k_pos <= q_pos, scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((tq, d), jnp.float32)
    m0 = jnp.full((tq,), _NEG, jnp.float32)
    l0 = jnp.zeros((tq,), jnp.float32)
    if causal:
        # skip fully-masked K tiles: tile t is relevant only while
        # t*tile_k <= last query position of this Q tile
        n_k_eff = ((qi + 1) * tq + tile_k - 1) // tile_k
        upper = jnp.minimum(n_k, n_k_eff)
    else:
        upper = n_k
    o, m, l = jax.lax.fori_loop(0, upper, body, (o0, m0, l0))
    o_ref[0, 0] = (o / jnp.maximum(l[:, None], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "tile_q", "tile_k", "interpret"))
def flash_attention(q, k, v, *, causal: bool = False, tile_q: int = 128,
                    tile_k: int = 128, interpret: bool = False):
    """q/k/v: [B, H, S, D] -> [B, H, S, D]. S must divide by the tile sizes."""
    b, h, s, d = q.shape
    tile_q = min(tile_q, s)
    tile_k = min(tile_k, s)
    if s % tile_q or s % tile_k:
        raise ValueError(f"seq len {s} must divide tiles ({tile_q}, {tile_k})")
    grid = (b, h, s // tile_q)
    kernel = functools.partial(_flash_kernel, tile_k=tile_k, causal=causal, tile_q=tile_q)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)
