"""Ragged flash attention: per-row sequence lengths, no wasted tiles.

The streaming engine pads variable-length batches to a bucket; plain attention
then burns MXU cycles on padding. This kernel (the ragged-attention pattern of
PAPERS.md "Ragged Paged Attention") takes the true ``lengths`` per row as a
scalar-prefetch argument and bounds the K/V tile loop per (batch, q-tile)
program at the row's real length — fully-padded tiles are never touched, and
padded key positions inside the last tile are masked. Output rows beyond a
row's length are zeros.

Same VMEM/online-softmax structure as ``flash_attention``; use it when batches
are bucketed well above their typical fill.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def flash_softmax_loop(q, k_ref, v_ref, n_tiles, tile_k: int, valid_at):
    """The online-softmax accumulation over K tiles shared by the ragged and
    segment kernels (ops/segment_attention.py) — ONE copy of the numerically
    delicate m/l/corr recurrence. ``valid_at(t) -> [TQ, TK] bool`` supplies
    each kernel's masking rule. Returns (o, m, l) after ``n_tiles`` tiles.
    """
    tq, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def body(t, carry):
        o, m, l = carry
        k = k_ref[0, 0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(t * tile_k, tile_k), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        scores = jnp.where(valid_at(t), scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((tq, d), jnp.float32)
    m0 = jnp.full((tq,), _NEG, jnp.float32)
    l0 = jnp.zeros((tq,), jnp.float32)
    return jax.lax.fori_loop(0, n_tiles, body, (o0, m0, l0))


def _ragged_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref, *, tile_k: int, causal: bool):
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [TQ, D]
    tq, d = q.shape
    s = k_ref.shape[2]
    length = lengths_ref[bi]

    # K tiles that contain any valid key for this row
    n_k_row = (length + tile_k - 1) // tile_k
    if causal:
        n_k_causal = ((qi + 1) * tq + tile_k - 1) // tile_k
        n_k_row = jnp.minimum(n_k_row, n_k_causal)
    n_k_row = jnp.minimum(n_k_row, s // tile_k)

    q_pos = qi * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tile_k), 0)

    def valid_at(t):
        k_pos = t * tile_k + jax.lax.broadcasted_iota(jnp.int32, (tq, tile_k), 1)
        # mask padded keys AND padded queries (pad-query rows emit zeros)
        valid = jnp.logical_and(k_pos < length, q_pos < length)
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        return valid

    o, m, l = flash_softmax_loop(q, k_ref, v_ref, n_k_row, tile_k, valid_at)
    # pad queries (beyond the row's true length) emit zeros; note a fully
    # masked softmax degenerates to uniform (exp(NEG-NEG)=1), so masking by
    # the accumulator alone is not sufficient — mask by query position.
    q_valid = (qi * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, 1), 0)) < length
    o_ref[0, 0] = jnp.where(
        q_valid, o / jnp.maximum(l[:, None], 1e-30), 0.0
    ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "tile_q", "tile_k", "interpret"))
def ragged_flash_attention(q, k, v, lengths, *, causal: bool = False,
                           tile_q: int = 128, tile_k: int = 128,
                           interpret: bool = False):
    """q/k/v: [B, H, S, D]; lengths: [B] int32 true sequence lengths."""
    b, h, s, d = q.shape
    tile_q = min(tile_q, s)
    tile_k = min(tile_k, s)
    if s % tile_q or s % tile_k:
        raise ValueError(f"seq len {s} must divide tiles ({tile_q}, {tile_k})")
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401 (memory spaces default)

    grid = (b, h, s // tile_q)
    kernel = functools.partial(_ragged_kernel, tile_k=tile_k, causal=causal)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi, *_: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi, *_: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi, *_: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, tile_q, d), lambda bi, hi, qi, *_: (bi, hi, qi, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32), q, k, v)
